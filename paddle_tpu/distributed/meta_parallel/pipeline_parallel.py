"""Pipeline-parallel execution.

Parity: /root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py — PipelineParallel.train_batch (:152) and
forward_backward_pipeline (:80, steady-state 1F1B with p2p send/recv), and
the C++ SectionWorker schedules (section_worker.cc:62 1F1B, :139 F-then-B).

TPU-native redesign: the reference interleaves imperative micro-batch
forward/backward with NCCL p2p at run time. Under XLA we express the SAME
schedule as one compiled program: stages live on the 'pp' mesh axis
(shard_map), activations rotate with lax.ppermute, and the microbatch loop is
a lax.scan of S+M-1 ticks (the canonical collective-permute pipeline from the
GSPMD/praxis lineage). jax.grad through the scan yields the backward
schedule; remat bounds activation memory like 1F1B bounds it in the
reference. Schedule modes:
- 'FThenB' / '1F1B': both lower to the same fused program (XLA owns the
  actual interleaving; 1F1B's memory bound is recovered via jax.checkpoint
  on the per-tick body).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...nn.layer import Layer
from ...tensor import Tensor
from ..spmd import P, run_on_mesh
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]

PP_AXIS = "pp"


class PipelineParallel(Layer):
    def __init__(self, layers: PipelineLayer, hcg, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.micro_batch_size = cfg.get("micro_batch_size", 1)
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self._train_step_fn = None
        self.total_loss = None

    # ------------------------------------------------------------------
    # the compiled pipeline program
    # ------------------------------------------------------------------
    def _stage_param_names(self):
        """Map each parameter name to its stage id."""
        bounds = self._layers.segment_parts
        names_by_stage = []
        layers = list(self._layers.run_function)
        for s in range(self.num_stages):
            names = set()
            for li in range(bounds[s], bounds[s + 1]):
                prefix = f"run_function.{li}."
                for n, _ in self._layers.named_parameters():
                    if n.startswith(prefix):
                        names.add(n)
            names_by_stage.append(names)
        return names_by_stage

    def _build_step(self, loss_fn, optimizer):
        """Build the jitted shard_map pipeline train step.

        Parameters are stacked along a leading 'pp' dim (stage-padded to the
        max stage size is avoided by keeping per-stage pytrees; XLA sees each
        stage's params only on its own shard)."""
        raise NotImplementedError  # assembled in parallel_trainer.build_pipeline_step

    # ------------------------------------------------------------------
    # reference-surface API
    # ------------------------------------------------------------------
    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Parity: train_batch(:152). Runs the microbatched pipeline step.

        In single-controller SPMD the full batch arrives here; it is split
        into ``accumulate_steps`` microbatches and driven through the
        compiled pipeline (built lazily on first call via
        parallel_trainer.build_pipeline_step)."""
        from ..parallel_trainer import build_pipeline_step

        x, y = data
        if self._train_step_fn is None:
            self._train_step_fn = build_pipeline_step(
                self._layers, self._hcg, optimizer,
                accumulate_steps=self.accumulate_steps,
                scaler=scaler,
            )
        loss = self._train_step_fn(x, y)
        self._pipe_dirty = True
        if lr_scheduler is not None:
            lr_scheduler.step()
        self.total_loss = loss
        return loss

    def _sync_from_pipeline(self):
        """Write the trained sharded params back into the eager Tensors
        (lazy: only before reads — eval/state_dict — and only when a train
        step ran since the last sync)."""
        if not getattr(self, "_pipe_dirty", False):
            return
        fn = self._train_step_fn
        step = getattr(fn, "_pipeline_step", None)
        if step is not None:
            step.sync_to_model()
        self._pipe_dirty = False

    def eval_batch(self, data, compute_loss: bool = True):
        self._sync_from_pipeline()
        x, y = data
        out = self._layers(x)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, y)
        return out

    def state_dict(self, *a, **k):
        self._sync_from_pipeline()
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
