"""Tensor-parallel layers.

Parity: /root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py — VocabParallelEmbedding:30,
ColumnParallelLinear:97, RowParallelLinear:170, ParallelCrossEntropy:249
(which calls the c_softmax_with_cross_entropy CUDA kernel,
operators/collective/c_softmax_with_cross_entropy_op.cu), and the
c_embedding kernel (c_embedding_op.cu).

TPU-native design — GSPMD-first: each layer holds the FULL weight with a
``partition_spec`` annotation (vocab/column dims on the 'mp' axis). Under
pjit the compiler shards the matmuls and inserts exactly the collectives the
reference codes by hand (c_identity fwd / allreduce bwd around column
parallel, allreduce fwd after row parallel). Inside an explicit shard_map
region the layers detect the bound 'mp' axis and execute the reference's
per-shard algorithm literally (masked embedding lookup + psum; sharded-vocab
softmax-CE with global max/sum-exp) so both SPMD styles are first-class.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...nn import functional as F
from ...nn import initializer as init_mod
from ...nn.layer import Layer
from ...ops._primitive import primitive, unwrap
from ...tensor import Tensor
from ..spmd import P

__all__ = [
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "ParallelCrossEntropy",
    "mp_axis_bound",
    "mp_identity_array",
]

MP_AXIS = "mp"


@jax.custom_vjp
def mp_identity_array(x):
    """c_identity parity (c_identity_op.cc): forward identity, backward
    all-reduce over 'mp'. Every explicit-SPMD column-parallel input must pass
    through this so the partial input-cotangents of the mp ranks recombine —
    without it, params upstream of a TP block (embeddings, layer norms)
    would receive per-rank partial gradients."""
    return x


def _mp_identity_fwd(x):
    return x, None


def _mp_identity_bwd(_, ct):
    return (lax.psum(ct, MP_AXIS),)


mp_identity_array.defvjp(_mp_identity_fwd, _mp_identity_bwd)


@jax.custom_vjp
def mp_allreduce_array(x):
    """c_allreduce_sum parity (c_allreduce_op.h): forward all-reduce over
    'mp', backward identity — the replicated output cotangent flows to each
    rank's partial contribution unchanged. (Without the custom vjp, jax's
    conservative psum transpose under ``check_vma=False`` psums the
    cotangent AGAIN, scaling mp-sharded grads by the mp degree.)"""
    return lax.psum(x, MP_AXIS)


def _mp_allreduce_fwd(x):
    return lax.psum(x, MP_AXIS), None


def _mp_allreduce_bwd(_, ct):
    return (ct,)


mp_allreduce_array.defvjp(_mp_allreduce_fwd, _mp_allreduce_bwd)


@primitive(name="c_identity")
def _c_identity(x):
    return mp_identity_array(x)


def mp_axis_bound() -> bool:
    try:
        lax.axis_index(MP_AXIS)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _mp_world():
    from ..env import get_mesh

    mesh = get_mesh()
    return int(mesh.shape.get(MP_AXIS, 1)) if mesh is not None else 1


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.world_size = _mp_world()
        assert num_embeddings % max(self.world_size, 1) == 0, "vocab must divide mp degree"
        self.per_part_size = num_embeddings // max(self.world_size, 1)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=init_mod.XavierNormal(),
        )
        self.weight.partition_spec = P(MP_AXIS, None)  # vocab-sharded

    def forward(self, x):
        if mp_axis_bound():
            # explicit path: local shard is [per_part, dim]; mask out-of-range
            per = self.per_part_size

            @primitive
            def _lookup(w, ids):
                rank = lax.axis_index(MP_AXIS)
                start = rank * per
                local = ids - start
                in_range = (local >= 0) & (local < per)
                safe = jnp.where(in_range, local, 0)
                emb = jnp.take(w, safe, axis=0)
                emb = jnp.where(in_range[..., None], emb, 0.0)
                return mp_allreduce_array(emb)

            return _lookup(self.weight, unwrap(x))
        # GSPMD path: plain lookup; compiler handles the sharded gather
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=True, fuse_matmul_bias=False, name=None, bias_attr=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.world_size = _mp_world()
        assert out_features % max(self.world_size, 1) == 0
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=init_mod.XavierNormal(),
        )
        self.weight.partition_spec = P(None, MP_AXIS)  # column-sharded
        has_bias = True if has_bias is None else has_bias
        if has_bias:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)
            self.bias.partition_spec = P(MP_AXIS)
        else:
            self.bias = None

    def forward(self, x):
        if mp_axis_bound():
            # c_identity forward (input broadcast, psum backward), local
            # matmul over the out/world shard; gather_output => all_gather
            out = F.linear(_c_identity(x), self.weight, self.bias)
            if self.gather_output:
                @primitive
                def _gather(o):
                    return lax.all_gather(o, MP_AXIS, axis=o.ndim - 1, tiled=True)

                out = _gather(out)
            return out
        from ..spmd import with_sharding_constraint

        out = F.linear(x, self.weight, self.bias,
                       weight_scale=getattr(self, "weight_scale", None),
                       act_scale=getattr(self, "act_scale", None))
        if self.gather_output:
            out = with_sharding_constraint(out, P())
        else:
            spec = [None] * (unwrap(out).ndim - 1) + [MP_AXIS]
            out = with_sharding_constraint(out, P(*spec))
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, name=None, bias_attr=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = _mp_world()
        assert in_features % max(self.world_size, 1) == 0
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=init_mod.XavierNormal(),
        )
        self.weight.partition_spec = P(MP_AXIS, None)  # row-sharded
        if has_bias:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)
            self.bias.partition_spec = P()
        else:
            self.bias = None

    def forward(self, x):
        if mp_axis_bound():
            # local matmul on the row shard, then mp_allreduce; bias after
            @primitive
            def _row(x, w, b):
                y = mp_allreduce_array(jnp.matmul(x, w))
                if b is not None:
                    y = y + b
                return y

            return _row(x, self.weight, self.bias)
        out = F.linear(x, self.weight, self.bias,
                       weight_scale=getattr(self, "weight_scale", None),
                       act_scale=getattr(self, "act_scale", None))
        from ..spmd import with_sharding_constraint

        return with_sharding_constraint(out, P())


class ParallelCrossEntropy(Layer):
    """Vocab-sharded softmax cross entropy.

    Explicit path mirrors c_softmax_with_cross_entropy_op.cu: global max via
    pmax, local sum-exp + psum, pick the local logit when the label falls in
    this shard's vocab range.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.world_size = _mp_world()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        from ...framework.flags import flag

        # r20: the fused Pallas softmax-CE head covers BOTH branches; the
        # jnp paths below stay the default and the parity oracle
        use_fused = bool(flag("FLAGS_use_pallas_softmax_ce"))
        ignore = self.ignore_index
        if not mp_axis_bound():
            if use_fused:
                from ...ops.pallas.softmax_ce import softmax_ce_loss

                @primitive
                def _fused_ce(logits, label):
                    return softmax_ce_loss(
                        logits, label, ignore_index=ignore)[..., None]

                return _fused_ce(input, unwrap(label))
            loss = F.cross_entropy(input, label, reduction="none", ignore_index=self.ignore_index)
            from ...ops.manipulation import unsqueeze

            return unsqueeze(loss, -1)
        per = None  # local vocab size derived inside

        @primitive
        def _pce(logits, label):
            vocab_local = logits.shape[-1]
            rank = lax.axis_index(MP_AXIS)
            start = rank * vocab_local
            m = lax.pmax(lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True)), MP_AXIS)
            shifted = logits - m
            lbl = label.astype(jnp.int32)
            valid = lbl != ignore
            safe_lbl = jnp.where(valid, lbl, 0)
            local = safe_lbl - start
            in_range = (local >= 0) & (local < vocab_local)
            if use_fused:
                # local (sum-exp, picked) partials in one fused pass; the
                # pmax above and the allreduces below stay outside the
                # kernel (reference: c_softmax_with_cross_entropy_op)
                from ...ops.pallas.softmax_ce import softmax_ce_partials

                loc = jnp.where(in_range & valid, local, -1)
                se, picked = softmax_ce_partials(shifted, loc)
                sum_exp = mp_allreduce_array(se[..., None])
                picked = mp_allreduce_array(picked)
            else:
                sum_exp = mp_allreduce_array(jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True))
                picked = jnp.take_along_axis(shifted, jnp.where(in_range, local, 0)[..., None], axis=-1)[..., 0]
                picked = jnp.where(in_range, picked, 0.0)
                picked = mp_allreduce_array(picked)
            loss = jnp.log(sum_exp[..., 0]) - picked
            loss = jnp.where(valid, loss, 0.0)
            return loss[..., None]

        return _pce(input, unwrap(label))
