"""Mixture-of-experts layer with expert parallelism.

Parity: the reference routes MoE through the ``global_scatter`` /
``global_gather`` all-to-all ops
(/root/reference/paddle/fluid/operators/collective/global_scatter_op.cc:19-28)
dispatching variable per-expert row counts between ranks.

TPU-native redesign (GShard-style): static expert *capacity* instead of
dynamic counts — gating builds dense dispatch/combine tensors, expert inputs
are one einsum, and the cross-rank exchange is a single ``lax.all_to_all``
over the 'ep' mesh axis (ICI-friendly, fully static shapes so XLA tiles the
expert FFN matmuls onto the MXU). Expert weights are *stacked* along a
leading expert dimension (one big batched matmul instead of a Python loop of
per-expert Linears).

Dual SPMD modes, matching mp_layers.py:
- inside shard_map with 'ep' bound: each shard holds
  ``num_experts // ep_world`` experts' weights and local tokens; dispatch →
  all_to_all → stacked-expert FFN → all_to_all → combine.
- GSPMD / single-shard: all experts local (weights carry a
  ``partition_spec`` with 'ep' on the expert dim so pjit shards them).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...nn import initializer as init_mod
from ...nn.layer import Layer
from ...ops._primitive import primitive, unwrap
from ..collective import _axis_bound
from ..spmd import P

__all__ = ["MoELayer", "ExpertFFN", "top_k_gating"]

EP_AXIS = "ep"


def ep_axis_bound(axis: str = EP_AXIS) -> bool:
    return _axis_bound(axis)


def _ep_world(axis: str = EP_AXIS) -> int:
    from ..env import get_mesh

    mesh = get_mesh()
    return int(mesh.shape.get(axis, 1)) if mesh is not None else 1


def top_k_gating(logits, k: int, capacity: int, num_experts: int):
    """GShard top-1/top-2 gating. Returns (combine [g,e,c], dispatch bool
    [g,e,c], l_aux scalar). Pure jax — usable inside any trace."""
    gates = jax.nn.softmax(logits, axis=-1)  # [g, e]
    idx1 = jnp.argmax(gates, axis=-1)
    mask1_raw = jax.nn.one_hot(idx1, num_experts, dtype=logits.dtype)

    # load-balancing aux loss on the top-1 assignment (GShard eq. 13)
    density = jnp.mean(mask1_raw, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    l_aux = jnp.sum(density * density_proxy) * num_experts

    locations1 = jnp.cumsum(mask1_raw, axis=0) - mask1_raw  # position within expert
    mask1 = mask1_raw * (locations1 < capacity)
    pos1 = jnp.sum(locations1 * mask1, axis=-1).astype(jnp.int32)
    gate1 = jnp.sum(gates * mask1, axis=-1)

    if k == 1:
        combine = gate1[:, None, None] * mask1[..., None] \
            * jax.nn.one_hot(pos1, capacity, dtype=logits.dtype)[:, None, :]
        dispatch = combine > 0
        return combine, dispatch, l_aux

    # second expert: mask out the first choice (the RAW top-1 one-hot — a
    # token whose top-1 overflowed capacity must still pick a DIFFERENT
    # second expert, not re-select the full one and get dropped)
    logits2 = jnp.where(mask1_raw > 0, -jnp.inf, logits)
    idx2 = jnp.argmax(logits2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, num_experts, dtype=logits.dtype)
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
    mask2 = mask2 * (locations2 < capacity)
    pos2 = jnp.sum(locations2 * mask2, axis=-1).astype(jnp.int32)
    gate2 = jnp.sum(gates * mask2, axis=-1)

    # renormalize the two gate values
    denom = jnp.maximum(gate1 + gate2, jnp.finfo(gates.dtype).eps)
    gate1n, gate2n = gate1 / denom, gate2 / denom

    oh1 = jax.nn.one_hot(pos1, capacity, dtype=logits.dtype)
    oh2 = jax.nn.one_hot(pos2, capacity, dtype=logits.dtype)
    combine = (gate1n[:, None, None] * mask1[..., None] * oh1[:, None, :]
               + gate2n[:, None, None] * mask2[..., None] * oh2[:, None, :])
    dispatch = combine > 0
    return combine, dispatch, l_aux


def top_k_gating_compact(logits, k: int, capacity: int, num_experts: int):
    """top_k_gating without the [g, e, c] one-hot tensors: returns per-token
    (expert id, capacity slot, normalized gate, kept?) pairs plus l_aux.
    Same assignment policy as top_k_gating (GShard cumsum capacity); the
    caller dispatches by scatter/gather instead of einsum one-hots — O(g·e)
    memory instead of O(g·e·c), which keeps large-expert-count compiles
    tractable."""
    gates = jax.nn.softmax(logits, axis=-1)  # [g, e]
    idx1 = jnp.argmax(gates, axis=-1)
    mask1_raw = jax.nn.one_hot(idx1, num_experts, dtype=logits.dtype)
    density = jnp.mean(mask1_raw, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    l_aux = jnp.sum(density * density_proxy) * num_experts

    locations1 = jnp.cumsum(mask1_raw, axis=0) - mask1_raw
    mask1 = mask1_raw * (locations1 < capacity)
    pos1 = jnp.sum(locations1 * mask1, axis=-1).astype(jnp.int32)
    keep1 = jnp.sum(mask1, axis=-1) > 0
    gate1 = jnp.sum(gates * mask1, axis=-1)

    if k == 1:
        return ((idx1.astype(jnp.int32), pos1, gate1, keep1),
                None, l_aux)

    logits2 = jnp.where(mask1_raw > 0, -jnp.inf, logits)
    idx2 = jnp.argmax(logits2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, num_experts, dtype=logits.dtype)
    locations2 = (jnp.cumsum(mask2, axis=0) - mask2
                  + jnp.sum(mask1, axis=0, keepdims=True))
    mask2 = mask2 * (locations2 < capacity)
    pos2 = jnp.sum(locations2 * mask2, axis=-1).astype(jnp.int32)
    keep2 = jnp.sum(mask2, axis=-1) > 0
    gate2 = jnp.sum(gates * mask2, axis=-1)

    denom = jnp.maximum(gate1 + gate2, jnp.finfo(gates.dtype).eps)
    return ((idx1.astype(jnp.int32), pos1, gate1 / denom, keep1),
            (idx2.astype(jnp.int32), pos2, gate2 / denom, keep2), l_aux)


def _stacked_ffn(xin, w1, b1, w2, b2, act):
    """Batched expert FFN: xin [e, c, m] with stacked weights [e, m, h]/[e, h, m]."""
    h = jnp.einsum("ecm,emh->ech", xin, w1) + b1[:, None, :]
    h = act(h)
    return jnp.einsum("ech,ehm->ecm", h, w2) + b2[:, None, :]


_ACTS = {
    "gelu": jax.nn.gelu,
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
}


class ExpertFFN(Layer):
    """Stacked per-expert 2-layer MLP — weights [num_local_experts, ...]."""

    def __init__(self, num_local_experts: int, d_model: int, d_hidden: int, activation: str = "gelu"):
        super().__init__()
        self.num_local_experts = num_local_experts
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.activation = activation
        self.w1 = self.create_parameter(
            [num_local_experts, d_model, d_hidden], default_initializer=init_mod.XavierNormal())
        self.b1 = self.create_parameter([num_local_experts, d_hidden], is_bias=True)
        self.w2 = self.create_parameter(
            [num_local_experts, d_hidden, d_model], default_initializer=init_mod.XavierNormal())
        self.b2 = self.create_parameter([num_local_experts, d_model], is_bias=True)
        # GSPMD: shard the stacked-expert dim over 'ep'
        self.w1.partition_spec = P(EP_AXIS, None, None)
        self.b1.partition_spec = P(EP_AXIS, None)
        self.w2.partition_spec = P(EP_AXIS, None, None)
        self.b2.partition_spec = P(EP_AXIS, None)

    def forward(self, xin):
        @primitive
        def _ffn(xin, w1, b1, w2, b2):
            return _stacked_ffn(xin, w1, b1, w2, b2, _ACTS[self.activation])

        return _ffn(xin, self.w1, self.b1, self.w2, self.b2)


class MoELayer(Layer):
    """Capacity-routed mixture of experts over the 'ep' mesh axis.

    ``num_experts`` is the GLOBAL expert count; each ep shard owns
    ``num_experts // ep_world`` experts. ``forward(x)`` returns the combined
    output with ``self.l_aux`` holding the load-balancing loss from the same
    trace (add it to the training loss).
    """

    # the aux-loss side output (self.l_aux) escapes forward as an attribute;
    # tracing it inside a cached jit would leak a tracer — always run eager
    _jit_forward_exempt = True

    def __init__(self, d_model: int, d_hidden: int, num_experts: int, *,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 activation: str = "gelu", ep_group=None,
                 name: Optional[str] = None):
        super().__init__()
        assert top_k in (1, 2), "top_k must be 1 or 2"
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = (ep_group.axis_name if ep_group is not None
                        and getattr(ep_group, "axis_name", None) else EP_AXIS)
        self.ep_world = _ep_world(self.ep_axis)
        assert num_experts % max(self.ep_world, 1) == 0, "experts must divide ep degree"
        self.num_local_experts = num_experts // max(self.ep_world, 1)
        self.gate_weight = self.create_parameter(
            [d_model, num_experts], default_initializer=init_mod.XavierNormal())
        self.gate_weight.partition_spec = P()  # gate is replicated
        # full stacked weights; explicit shard_map slices them via in_specs
        # (mp_layers convention), GSPMD shards them via partition_spec
        self.experts = ExpertFFN(num_experts, d_model, d_hidden, activation)
        self.l_aux = None

    def _capacity(self, tokens: int) -> int:
        return max(1, int(math.ceil(self.top_k * self.capacity_factor * tokens / self.num_experts)))

    def forward(self, x):
        lead_shape = unwrap(x).shape[:-1]
        tokens = math.prod(lead_shape) if lead_shape else 1
        cap = self._capacity(tokens)
        e, k = self.num_experts, self.top_k
        act = _ACTS[self.experts.activation]
        ep_axis = self.ep_axis
        bound = ep_axis_bound(ep_axis)

        @primitive
        def _moe(x, gate_w, w1, b1, w2, b2):
            g = x.reshape(-1, x.shape[-1])  # [tokens, m]
            logits = g @ gate_w
            picks1, picks2, l_aux = top_k_gating_compact(logits, k, cap, e)
            # scatter/gather dispatch: slot (expert, pos) ← token row; no
            # [g, e, c] one-hot (compile-heavy at large expert counts)
            gt = jnp.arange(g.shape[0], dtype=jnp.int32)
            slot_src = jnp.full((e * cap,), g.shape[0], jnp.int32)
            for p in (picks1, picks2):
                if p is None:
                    continue
                eid, pos, _gt, keepm = p
                flat_slot = eid * cap + pos
                # each kept token owns a distinct (expert, slot) target;
                # dropped tokens get DISTINCT out-of-range indices
                # (e*cap + token) so the index set is globally unique and
                # mode="drop" discards them — unique_indices then lets XLA
                # lower a parallel scatter instead of the serialized
                # conservative path
                slot_src = slot_src.at[
                    jnp.where(keepm, flat_slot, e * cap + gt)
                ].set(gt, mode="drop", unique_indices=True)
            g_pad = jnp.concatenate(
                [g, jnp.zeros((1, g.shape[-1]), g.dtype)], axis=0)
            xin = jnp.take(g_pad, slot_src, axis=0).reshape(e, cap, -1)
            if bound:
                # dispatch: send each rank its experts' rows
                n = lax.axis_size(ep_axis)
                local_e = e // n
                xin = lax.all_to_all(
                    xin.reshape(n, local_e, cap, xin.shape[-1]),
                    ep_axis, split_axis=0, concat_axis=0, tiled=False)
                # xin now [n_src, local_e, c, m] → fold sources into capacity
                xin = jnp.transpose(xin, (1, 0, 2, 3)).reshape(local_e, n * cap, -1)
                out = _stacked_ffn(xin, w1, b1, w2, b2, act)
                # inverse exchange
                out = out.reshape(local_e, n, cap, -1).transpose(1, 0, 2, 3)
                out = lax.all_to_all(out, ep_axis, split_axis=0, concat_axis=0, tiled=False)
                out = out.reshape(e, cap, -1)
            else:
                out = _stacked_ffn(xin, w1, b1, w2, b2, act)
            out_flat = out.reshape(e * cap, -1)
            y = jnp.zeros_like(g)
            for p in (picks1, picks2):
                if p is None:
                    continue
                eid, pos, gate_n, keepm = p
                rows = jnp.take(out_flat, eid * cap + pos, axis=0)
                y = y + jnp.where(keepm[:, None],
                                  gate_n[:, None].astype(g.dtype) * rows, 0.0)
            return y.reshape(x.shape), l_aux

        out, l_aux = _moe(x, self.gate_weight, self.experts.w1, self.experts.b1,
                          self.experts.w2, self.experts.b2)
        self.l_aux = l_aux
        return out
