"""Sequence/context parallelism: ring attention + Ulysses all2all.

The reference has NO sequence parallelism (SURVEY §5.7 — repo-wide grep for
ring attention / context parallel / Ulysses finds nothing; long sequences
rely on TP+PP+recompute only). This subsystem is a required TPU-native
addition: long-context attention sharded over the 'sp' mesh axis.

Two schemes, both SPMD-explicit (run inside shard_map with 'sp' bound):

- **Ring attention** (`ring_attention`): K/V blocks rotate around the ring
  via ``lax.ppermute`` while each shard's Q stays put; an online-softmax
  (flash-attention style running max/sum in f32) accumulates exact attention
  over the full sequence with O(T/n) memory per chip and comm overlapped by
  XLA. Causal masking uses global token positions, so shard boundaries are
  exact.
- **Ulysses** (`ulysses_attention`): one ``lax.all_to_all`` re-shards
  sequence→heads ([B, H, T/n, D] → [B, H/n, T, D]), full attention runs
  locally per head group (dispatching to the Pallas flash kernel on TPU),
  then the inverse all2all restores sequence sharding. The sp degree must
  divide the head count. This reuses the same all2all machinery the MoE
  layer uses (the reference expresses its all2all as global_scatter/
  global_gather — SURVEY §5.7 notes SP should reuse it).

Both are pure-jax functions differentiable end-to-end (ppermute/all_to_all
have exact transposes), exposed eagerly through ``@primitive`` wrappers.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...ops._primitive import primitive, unwrap
from ..collective import _axis_bound

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "sp_axis_bound",
    "split_sequence",
    "gather_sequence",
    "SP_AXIS",
]

SP_AXIS = "sp"
_NEG = -1e9  # finite mask value — avoids -inf NaNs in the online softmax


def sp_axis_bound(axis: str = SP_AXIS) -> bool:
    return _axis_bound(axis)


def split_sequence(x, axis_name: str = SP_AXIS, seq_axis: int = 1):
    """Keep this shard's sequence slice (explicit-SPMD entry helper)."""
    arr = unwrap(x)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if arr.shape[seq_axis] % n != 0:
        raise ValueError(f"sequence length {arr.shape[seq_axis]} must be "
                         f"divisible by the sp degree {n}")
    size = arr.shape[seq_axis] // n
    return lax.dynamic_slice_in_dim(arr, idx * size, size, axis=seq_axis)


def gather_sequence(x, axis_name: str = SP_AXIS, seq_axis: int = 1):
    """All-gather sequence shards back to the full sequence."""
    return lax.all_gather(unwrap(x), axis_name, axis=seq_axis, tiled=True)


def _ring_attention_raw(q, k, v, axis_name: str, causal: bool, sm_scale: Optional[float]):
    """q,k,v: [B, H, T_loc, D] — this shard's contiguous sequence block."""
    orig_dtype = q.dtype
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, t_loc, d = q.shape
    qf = q.astype(jnp.float32) * scale

    q_pos = my * t_loc + jnp.arange(t_loc)  # global positions of local queries

    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: shard i -> i+1

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (my - i) % n  # whose K/V block we hold at step i
        logits = jnp.einsum("bhtd,bhsd->bhts", qf, k_blk.astype(jnp.float32))
        if causal:
            k_pos = src * t_loc + jnp.arange(t_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, _NEG)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhts,bhsd->bhtd", p, v_blk.astype(jnp.float32))
        # rotate K/V around the ring for the next step (last rotation is a
        # no-op consumer but keeps the loop shape-uniform)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return o, m_new, l, k_blk, v_blk

    o0 = jnp.zeros((b, h, t_loc, d), jnp.float32)
    m0 = jnp.full((b, h, t_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, t_loc), jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v), unroll=True)
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(orig_dtype)


def ring_attention(q, k, v, *, axis_name: str = SP_AXIS, causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Exact attention over the ring-sharded sequence. Eager/taped wrapper."""

    @primitive
    def _ring(q, k, v):
        return _ring_attention_raw(q, k, v, axis_name, causal, sm_scale)

    return _ring(q, k, v)


def _local_full_attention(q, k, v, causal: bool, scale: float):
    """Plain XLA attention used inside Ulysses (flash kernel on TPU)."""
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except RuntimeError:
        on_tpu = False
    t, s, dd = q.shape[-2], k.shape[-2], q.shape[-1]
    if on_tpu and t % 128 == 0 and s % 128 == 0 and dd % 64 == 0 and t >= 512:
        from ...ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, sm_scale=scale)
    logits = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, s), bool), k=s - t)
        logits = jnp.where(mask, logits, _NEG)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", w, v.astype(jnp.float32)).astype(q.dtype)


def _ulysses_raw(q, k, v, axis_name: str, causal: bool, sm_scale: Optional[float]):
    """q,k,v: [B, H, T_loc, D] sequence-sharded → heads-sharded full-T attention."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = lax.axis_size(axis_name)
    if q.shape[1] % n != 0:
        raise ValueError(f"num_heads {q.shape[1]} must be divisible by the "
                         f"sp degree {n} for Ulysses")
    # sequence→head re-shard: split heads, concat sequence
    a2a = partial(lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True)
    qh, kh, vh = a2a(q), a2a(k), a2a(v)  # [B, H/n, T, D]
    out = _local_full_attention(qh, kh, vh, causal, scale)
    # head→sequence re-shard back
    return lax.all_to_all(out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(q, k, v, *, axis_name: str = SP_AXIS, causal: bool = False,
                      sm_scale: Optional[float] = None):
    """Ulysses all2all sequence-parallel attention. Eager/taped wrapper."""

    @primitive
    def _ulysses(q, k, v):
        return _ulysses_raw(q, k, v, axis_name, causal, sm_scale)

    return _ulysses(q, k, v)
