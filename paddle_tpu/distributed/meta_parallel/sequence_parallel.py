"""Sequence/context parallelism: ring attention + Ulysses all2all.

The reference has NO sequence parallelism (SURVEY §5.7 — repo-wide grep for
ring attention / context parallel / Ulysses finds nothing; long sequences
rely on TP+PP+recompute only). This subsystem is a required TPU-native
addition: long-context attention sharded over the 'sp' mesh axis.

Two schemes, both SPMD-explicit (run inside shard_map with 'sp' bound):

- **Ring attention** (`ring_attention`): K/V blocks rotate around the ring
  via ``lax.ppermute`` while each shard's Q stays put; an online-softmax
  (flash-attention style running max/sum in f32) accumulates exact attention
  over the full sequence with O(T/n) memory per chip and comm overlapped by
  XLA. Causal masking uses global token positions, so shard boundaries are
  exact.
- **Ulysses** (`ulysses_attention`): one ``lax.all_to_all`` re-shards
  sequence→heads ([B, H, T/n, D] → [B, H/n, T, D]), full attention runs
  locally per head group (dispatching to the Pallas flash kernel on TPU),
  then the inverse all2all restores sequence sharding. The sp degree must
  divide the head count. This reuses the same all2all machinery the MoE
  layer uses (the reference expresses its all2all as global_scatter/
  global_gather — SURVEY §5.7 notes SP should reuse it).

Both are pure-jax functions differentiable end-to-end (ppermute/all_to_all
have exact transposes), exposed eagerly through ``@primitive`` wrappers.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...ops._primitive import primitive, unwrap
from ..collective import _axis_bound

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "sp_axis_bound",
    "split_sequence",
    "gather_sequence",
    "SP_AXIS",
]

SP_AXIS = "sp"
_NEG = -1e9  # finite mask value — avoids -inf NaNs in the online softmax


def sp_axis_bound(axis: str = SP_AXIS) -> bool:
    return _axis_bound(axis)


def split_sequence(x, axis_name: str = SP_AXIS, seq_axis: int = 1):
    """Keep this shard's sequence slice (explicit-SPMD entry helper)."""
    arr = unwrap(x)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if arr.shape[seq_axis] % n != 0:
        raise ValueError(f"sequence length {arr.shape[seq_axis]} must be "
                         f"divisible by the sp degree {n}")
    size = arr.shape[seq_axis] // n
    return lax.dynamic_slice_in_dim(arr, idx * size, size, axis=seq_axis)


def gather_sequence(x, axis_name: str = SP_AXIS, seq_axis: int = 1):
    """All-gather sequence shards back to the full sequence."""
    return lax.all_gather(unwrap(x), axis_name, axis=seq_axis, tiled=True)


def _ring_attention_raw(q, k, v, axis_name: str, causal: bool, sm_scale: Optional[float]):
    """q,k,v: [B, H, T_loc, D] — this shard's contiguous sequence block."""
    orig_dtype = q.dtype
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, t_loc, d = q.shape
    qf = q.astype(jnp.float32) * scale

    q_pos = my * t_loc + jnp.arange(t_loc)  # global positions of local queries

    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: shard i -> i+1

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (my - i) % n  # whose K/V block we hold at step i
        logits = jnp.einsum("bhtd,bhsd->bhts", qf, k_blk.astype(jnp.float32))
        if causal:
            k_pos = src * t_loc + jnp.arange(t_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, _NEG)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhts,bhsd->bhtd", p, v_blk.astype(jnp.float32))
        # rotate K/V around the ring for the next step (last rotation is a
        # no-op consumer but keeps the loop shape-uniform)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return o, m_new, l, k_blk, v_blk

    o0 = jnp.zeros((b, h, t_loc, d), jnp.float32)
    m0 = jnp.full((b, h, t_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, t_loc), jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v), unroll=True)
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(orig_dtype)


_RING_NEG = -1e30  # finite -inf stand-in: keeps the cross-hop merge NaN-free


def _ring_hop_specs(t_loc: int, d: int):
    from ...ops.pallas.flash_attention import _fit

    block_q = _fit(t_loc, 1024)
    block_k = _fit(t_loc, 1024 if d < 128 else 512)
    return block_q, block_k


def _hop_kind(my, src, causal):
    """0 = fully masked (future block), 1 = diagonal (local causal),
    2 = fully visible (past block)."""
    if not causal:
        return None
    return jnp.where(src == my, 1, jnp.where(src < my, 2, 0)).astype(jnp.int32)


# Residuals-as-inputs remat structure (same design as
# ops/pallas/flash_attention.py): the ring forward runs on stop_gradient'd
# operands, its (o, lse) outputs are checkpoint_name-tagged with the SAME
# names the flash policies save, and the gradient attaches via a
# custom_vjp whose residuals are its inputs — a remat'd long-context layer
# under 'selective'/'core_attn' never replays the n-hop ring forward
# (ppermutes included) in backward.
@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _ring_attach(q, k, v, o, lse, axis_name, causal, scale, block_q,
                 block_k, interpret):
    return o


def _ring_attach_fwd(q, k, v, o, lse, axis_name, causal, scale, block_q,
                     block_k, interpret):
    return o, (q, k, v, o, lse)


def _ring_attach_bwd(axis_name, causal, scale, block_q, block_k, interpret,
                     res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _ring_flash_bwd(axis_name, causal, scale, block_q, block_k,
                                 interpret, res, do)
    return dq, dk, dv, jnp.zeros_like(o), jnp.zeros_like(lse)


_ring_attach.defvjp(_ring_attach_fwd, _ring_attach_bwd)


def _ring_flash(q, k, v, axis_name, causal, scale, block_q, block_k, interpret):
    from jax.ad_checkpoint import checkpoint_name

    o, (_, _, _, _, lse) = _ring_flash_fwd(
        lax.stop_gradient(q), lax.stop_gradient(k), lax.stop_gradient(v),
        axis_name, causal, scale, block_q, block_k, interpret)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return _ring_attach(q, k, v, o, lse, axis_name, causal, scale, block_q,
                        block_k, interpret)


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                    interpret):
    """Per-hop Pallas flash kernels + online cross-hop merge: each hop
    produces (o_hop, lse_hop) for one rotating K/V block; partial softmaxes
    combine exactly via logaddexp — O(T_loc) memory, no [T, T] logits."""
    from ...ops.pallas.flash_attention import _fwd

    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    bh, t_loc, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    o_run = jnp.zeros((bh, t_loc, d), jnp.float32)
    lse_run = jnp.full((bh, t_loc), _RING_NEG, jnp.float32)
    k_blk, v_blk = k, v
    for i in range(n):
        src = (my - i) % n
        kind = _hop_kind(my, src, causal)

        def full_hop(q, kb, vb):
            o, lse = _fwd(q, kb, vb, scale, False, block_q, block_k, interpret)
            return o.astype(jnp.float32), lse

        def diag_hop(q, kb, vb):
            o, lse = _fwd(q, kb, vb, scale, True, block_q, block_k, interpret)
            return o.astype(jnp.float32), lse

        def masked_hop(q, kb, vb):
            return (jnp.zeros((bh, t_loc, d), jnp.float32),
                    jnp.full((bh, t_loc), _RING_NEG, jnp.float32))

        if kind is None:
            o_hop, lse_hop = full_hop(q, k_blk, v_blk)
        else:
            o_hop, lse_hop = lax.switch(
                kind, [masked_hop, diag_hop, full_hop], q, k_blk, v_blk)
        lse_new = jnp.logaddexp(lse_run, lse_hop)
        # guard: rows with nothing visible yet keep lse at the finite floor
        lse_new = jnp.maximum(lse_new, _RING_NEG)
        o_run = (o_run * jnp.exp(lse_run - lse_new)[..., None]
                 + o_hop * jnp.exp(lse_hop - lse_new)[..., None])
        lse_run = lse_new
        if i + 1 < n:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
    out = o_run.astype(q.dtype)
    return out, (q, k, v, out, lse_run)


def _ring_flash_bwd(axis_name, causal, scale, block_q, block_k, interpret,
                    res, do):
    """Ring backward: re-rotate K/V, run the flash backward kernels per hop
    with the GLOBAL lse/delta (standard blockwise flash backward), and
    rotate the dK/dV accumulators alongside so each lands back on its
    owner after n hops."""
    from ...ops.pallas.flash_attention import _bwd

    q, k, v, o, lse = res
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq = jnp.zeros(q.shape, jnp.float32)
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    k_blk, v_blk = k, v
    for i in range(n):
        src = (my - i) % n
        kind = _hop_kind(my, src, causal)

        def full_hop(q, kb, vb, o, lse, do):
            return _bwd(scale, False, block_q, block_k, interpret,
                        (q, kb, vb, o, lse), do)

        def diag_hop(q, kb, vb, o, lse, do):
            return _bwd(scale, True, block_q, block_k, interpret,
                        (q, kb, vb, o, lse), do)

        def masked_hop(q, kb, vb, o, lse, do):
            return (jnp.zeros(q.shape, q.dtype), jnp.zeros(kb.shape, kb.dtype),
                    jnp.zeros(vb.shape, vb.dtype))

        if kind is None:
            dq_h, dk_h, dv_h = full_hop(q, k_blk, v_blk, o, lse, do)
        else:
            dq_h, dk_h, dv_h = lax.switch(
                kind, [masked_hop, diag_hop, full_hop],
                q, k_blk, v_blk, o, lse, do)
        dq = dq + dq_h.astype(jnp.float32)
        dk_acc = dk_acc + dk_h.astype(jnp.float32)
        dv_acc = dv_acc + dv_h.astype(jnp.float32)
        # rotate the grad accumulators every hop (the final rotation lands
        # each on its owner rank); K/V only need rotating while more hops
        # will read them
        if i + 1 < n:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


def _ring_attention_flash(q, k, v, axis_name, causal, sm_scale, interpret):
    """[B, H, T_loc, D] wrapper: head-fold, lane-pad D, pick blocks."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b, h, t_loc, d = q.shape
    d_pad = (-d) % 64
    if d_pad:
        pad = [(0, 0)] * 3 + [(0, d_pad)]
        q, k, v = (jnp.pad(a, pad) for a in (q, k, v))
    qf = q.reshape(b * h, t_loc, d + d_pad)
    kf = k.reshape(b * h, t_loc, d + d_pad)
    vf = v.reshape(b * h, t_loc, d + d_pad)
    block_q, block_k = _ring_hop_specs(t_loc, d + d_pad)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out = _ring_flash(qf, kf, vf, axis_name, causal, float(scale),
                      block_q, block_k, bool(interpret))
    out = out.reshape(b, h, t_loc, d + d_pad)
    return out[..., :d] if d_pad else out


def _ring_use_flash(t_loc: int) -> bool:
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except RuntimeError:
        on_tpu = False
    return on_tpu and t_loc % 128 == 0 and t_loc >= 256


def ring_attention(q, k, v, *, axis_name: str = SP_AXIS, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   use_flash: Optional[bool] = None,
                   interpret: Optional[bool] = None):
    """Exact attention over the ring-sharded sequence. Eager/taped wrapper.

    On TPU with 128-aligned shard lengths each ring hop runs the Pallas
    flash kernel (O(T_loc) memory — no [T_loc, T_loc] logits); other shapes
    use the einsum online-softmax fallback."""

    t_loc = unwrap(q).shape[-2]
    flash = _ring_use_flash(t_loc) if use_flash is None else use_flash

    @primitive
    def _ring(q, k, v):
        if flash:
            return _ring_attention_flash(q, k, v, axis_name, causal,
                                         sm_scale, interpret)
        return _ring_attention_raw(q, k, v, axis_name, causal, sm_scale)

    return _ring(q, k, v)


def _local_full_attention(q, k, v, causal: bool, scale: float):
    """Plain XLA attention used inside Ulysses (flash kernel on TPU)."""
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except RuntimeError:
        on_tpu = False
    t, s, dd = q.shape[-2], k.shape[-2], q.shape[-1]
    if on_tpu and t % 128 == 0 and s % 128 == 0 and dd % 64 == 0 and t >= 512:
        from ...ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, sm_scale=scale)
    logits = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, s), bool), k=s - t)
        logits = jnp.where(mask, logits, _NEG)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", w, v.astype(jnp.float32)).astype(q.dtype)


def _ulysses_raw(q, k, v, axis_name: str, causal: bool, sm_scale: Optional[float]):
    """q,k,v: [B, H, T_loc, D] sequence-sharded → heads-sharded full-T attention."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = lax.axis_size(axis_name)
    if q.shape[1] % n != 0:
        raise ValueError(f"num_heads {q.shape[1]} must be divisible by the "
                         f"sp degree {n} for Ulysses")
    # sequence→head re-shard: split heads, concat sequence
    a2a = partial(lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True)
    qh, kh, vh = a2a(q), a2a(k), a2a(v)  # [B, H/n, T, D]
    out = _local_full_attention(qh, kh, vh, causal, scale)
    # head→sequence re-shard back
    return lax.all_to_all(out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(q, k, v, *, axis_name: str = SP_AXIS, causal: bool = False,
                      sm_scale: Optional[float] = None):
    """Ulysses all2all sequence-parallel attention. Eager/taped wrapper."""

    @primitive
    def _ulysses(q, k, v):
        return _ulysses_raw(q, k, v, axis_name, causal, sm_scale)

    return _ulysses(q, k, v)
