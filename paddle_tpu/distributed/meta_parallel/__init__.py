"""meta_parallel — hybrid-parallel wrappers and parallel layers.

Parity: python/paddle/distributed/fleet/meta_parallel/ in the reference.
"""
from .hybrid_optimizer import DygraphShardingOptimizer, HybridParallelOptimizer  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .moe_layer import ExpertFFN, MoELayer, top_k_gating  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .pipeline_schedule import GPTPipelineModule, build_gpt_pipeline_step  # noqa: F401
from .sequence_parallel import (  # noqa: F401
    gather_sequence,
    ring_attention,
    split_sequence,
    ulysses_attention,
)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .tensor_parallel import TensorParallel  # noqa: F401
