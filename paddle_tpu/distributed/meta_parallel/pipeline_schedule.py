"""Stage-parallel pipeline schedule: the ppermute-scan pipeline program.

Parity: the reference's 1F1B pipeline — static-graph
``PipelineOptimizer``/``SectionWorker`` (fluid/optimizer.py:4176,
framework/section_worker.cc:62 schedule_mode==1) and dygraph
``PipelineParallel.forward_backward_pipeline``
(fleet/meta_parallel/pipeline_parallel.py:80) with send_v2/recv_v2 p2p ops —
composed with tensor parallelism (partial_send p2p-under-mp,
fleet/meta_parallel/pp_utils/p2p_communication.py:149-155), ZeRO sharding
(fleet/meta_optimizers/sharding_optimizer.py:140 hybrid mp x sharding x pp x
dp degrees), and the TP RNG tracker for dropout determinism
(fleet/meta_parallel/parallel_layers/random.py).

TPU-native redesign (the canonical GSPMD/praxis collective-permute
pipeline): ONE shard_map over every mesh axis —

- 'pp'   — stages own a stacked [1, k, ...] slice of the decoder layers;
  the microbatch loop is a ``lax.scan`` of M + S - 1 ticks where activations
  rotate stage→stage+1 via ``lax.ppermute``. ``jax.grad`` through the scan
  yields the reverse schedule (the p2p transposes ARE the backward p2p) and
  ``jax.checkpoint`` on the per-tick stage body recovers 1F1B's O(S)
  activation-memory bound.
- 'mp'   — stage params carry their tensor-parallel shard (column/row
  splits per ``partition_spec``); blocks run the explicit Megatron
  algorithm (mp_layers' ``mp_axis_bound`` path: c_identity fwd/psum bwd,
  row-parallel psum, sharded-vocab embedding + softmax-CE).
- 'dp' / 'sharding' — both shard the batch; grads are pmean'd over 'dp'
  and reduce-scattered over 'sharding' (ZeRO-2), optimizer slots live
  sliced 1/n per sharding rank, updated params all-gather back.
- dropout — per-(microbatch, layer) PRNG keys are folded in inside the
  scan so masks are deterministic and reproducible by a sequential run
  (replaces the reference's RNG state tracker).

Shared (tied) embedding + final-norm + head params are replicated over 'pp'
with gradient psum, replacing the reference's SharedLayerDesc allreduce of
tied-embedding grads (pp_layers.py:49).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding

from ...autograd import tape
from ...random import get_rng_state, set_rng_state
from ...tensor import Tensor
from ..env import get_mesh
from ..spmd import P, sanitize_spec
from .mp_layers import (
    MP_AXIS,
    mp_allreduce_array,
    mp_axis_bound,
    mp_identity_array,
)

__all__ = ["build_gpt_pipeline_step", "stack_layer_params", "GPTPipelineModule"]

PP_AXIS = "pp"
DP_AXIS = "dp"
SH_AXIS = "sharding"
_EMBED_FOLD = 1 << 20  # fold_in tag separating the embed-dropout stream


def stack_layer_params(blocks):
    """[{name: arr}] per block → {name: arr[N, ...]} stacked."""
    trees = [{n: p._data for n, p in blk.named_parameters()} for blk in blocks]
    return {n: jnp.stack([t[n] for t in trees]) for n in trees[0]}


def _only_mp(spec: P) -> P:
    """Keep only 'mp' placements of a partition spec (dp/fsdp annotations
    don't apply to stacked pipeline params)."""
    dims = []
    for d in spec:
        if d == MP_AXIS or (isinstance(d, tuple) and MP_AXIS in d):
            dims.append(MP_AXIS)
        else:
            dims.append(None)
    return P(*dims)


def _local_shape(global_shape, spec, mesh):
    dims = list(spec) + [None] * (len(global_shape) - len(spec))
    out = []
    for s, d in zip(global_shape, dims):
        if d is None:
            out.append(s)
        else:
            axes = (d,) if isinstance(d, str) else tuple(d)
            f = 1
            for a in axes:
                f *= int(mesh.shape[a])
            out.append(s // f)
    return tuple(out)


class GPTPipelineModule:
    """Functional pipeline program for a GPTForPretraining model.

    Splits ``model.gpt.h`` (N uniform decoder blocks) into S = pp-degree
    stages of k = N/S layers each. Parameters:
      - ``stages``: {name: [S, k, ...]} — dim 0 on 'pp', tensor-parallel
        dims on 'mp' per the block's ``partition_spec`` annotations
      - ``shared``: tied wte (vocab on 'mp') / wpe / final LN
    """

    def __init__(self, model, num_stages: int, microbatches: int, mesh=None):
        cfg = model.gpt.config
        if getattr(cfg, "num_experts", 0):
            raise ValueError("pipeline schedule requires a uniform decoder "
                             "stack; MoE configs interleave MoE/dense blocks "
                             "with different parameter structures — use "
                             "ParallelTrainer (ep axis) for MoE models")
        n_layers = len(model.gpt.h)
        if n_layers % num_stages != 0:
            raise ValueError(f"layer count {n_layers} must be divisible by "
                             f"the stage count {num_stages}")
        mesh = mesh or get_mesh()
        self.mesh = mesh
        self.mp_size = int(mesh.shape.get(MP_AXIS, 1)) if mesh is not None else 1
        self.has_mp = self.mp_size > 1
        self.model = model
        self.cfg = cfg
        self.num_stages = num_stages
        self.layers_per_stage = n_layers // num_stages
        self.microbatches = microbatches
        self._block = model.gpt.h[0]  # structural template for all blocks

        # tensor-parallel placement per block param (Megatron column/row)
        self.block_specs = {}
        for n, p in self._block.named_parameters():
            spec = getattr(p, "partition_spec", None) or P()
            if mesh is not None:
                spec = sanitize_spec(spec, mesh)
            self.block_specs[n] = _only_mp(spec)

        stacked = stack_layer_params(list(model.gpt.h))
        self.stage_params = {
            n: a.reshape((num_stages, self.layers_per_stage) + a.shape[1:])
            for n, a in stacked.items()
        }
        self.stage_specs = {
            n: P(PP_AXIS, None, *self.block_specs[n]) for n in self.stage_params
        }
        emb = model.gpt.embeddings
        self.shared_params = {
            "wte": emb.word_embeddings.weight._data,
            "ln_f.weight": model.gpt.ln_f.weight._data,
            "ln_f.bias": model.gpt.ln_f.bias._data,
        }
        self.shared_specs = {
            "wte": P(MP_AXIS, None) if self.has_mp else P(),
            "ln_f.weight": P(), "ln_f.bias": P(),
        }
        if getattr(emb, "use_wpe", True):  # rope configs carry no wpe
            self.shared_params["wpe"] = emb.position_embeddings.weight._data
            self.shared_specs["wpe"] = P()

    # -- functional pieces ------------------------------------------------
    def _apply_block(self, layer_params, h):
        """One decoder layer, pure: layer_params {name: arr}, h [mb, T, H].
        Inside an 'mp' shard_map region the params are the local TP shards
        and the block runs the explicit Megatron collectives."""
        with tape.no_grad():
            out, _ = self._block.functional_call_with_state(layer_params, {}, Tensor(h))
        return out._data

    def _embed(self, shared, ids, key=None):
        t = ids.shape[-1]
        pos = jnp.arange(t)
        wte = shared["wte"]
        if self.has_mp and mp_axis_bound():
            # sharded-vocab lookup (c_embedding parity): mask + psum
            per = wte.shape[0]
            rank = lax.axis_index(MP_AXIS)
            local = ids - rank * per
            ok = (local >= 0) & (local < per)
            emb = jnp.take(wte, jnp.where(ok, local, 0), axis=0)
            emb = jnp.where(ok[..., None], emb, 0.0)
            emb = mp_allreduce_array(emb)
        else:
            emb = jnp.take(wte, ids, axis=0)
        h = emb + shared["wpe"][pos] if "wpe" in shared else emb
        p = self.cfg.hidden_dropout_prob
        if key is not None and p > 0.0:
            keep = jax.random.bernoulli(key, 1.0 - p, h.shape)
            h = jnp.where(keep, h / (1.0 - p), 0.0).astype(h.dtype)
        return h

    def _head_loss(self, shared, h, labels):
        eps = self.cfg.layer_norm_epsilon
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        hn = (h - mu) / jnp.sqrt(var + eps) * shared["ln_f.weight"] + shared["ln_f.bias"]
        lbl = labels.astype(jnp.int32)
        valid = lbl != -100  # ignore_index parity with GPTPretrainingCriterion
        safe = jnp.where(valid, lbl, 0)
        if self.has_mp and mp_axis_bound():
            # vocab-sharded softmax-CE (c_softmax_with_cross_entropy parity);
            # identity-fwd/psum-bwd on h so ln_f sees the full cotangent
            hn = mp_identity_array(hn)
            logits = jnp.einsum("bth,vh->btv", hn, shared["wte"]).astype(jnp.float32)
            per = logits.shape[-1]
            start = lax.axis_index(MP_AXIS) * per
            # stop_gradient BEFORE pmax: the max shift is grad-free and pmax
            # has no JVP rule (zero-tangent operands skip it)
            m = lax.pmax(lax.stop_gradient(jnp.max(logits, -1, keepdims=True)), MP_AXIS)
            shifted = logits - m
            sum_exp = mp_allreduce_array(jnp.sum(jnp.exp(shifted), -1, keepdims=True))
            loc = safe - start
            ok = (loc >= 0) & (loc < per)
            picked = jnp.take_along_axis(shifted, jnp.where(ok, loc, 0)[..., None], -1)[..., 0]
            picked = jnp.where(ok, picked, 0.0)
            picked = mp_allreduce_array(picked)
            ll = picked - jnp.log(sum_exp[..., 0])
        else:
            logits = jnp.einsum("bth,vh->btv", hn, shared["wte"])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        ll = jnp.where(valid, ll, 0.0)
        return -ll.sum() / jnp.maximum(valid.sum(), 1)

    # -- the pipelined local loss (runs inside shard_map) -----------------
    def local_loss(self, stage_params, shared, x, y, key=None):
        """x, y: [M*mb, T] on this (dp, sharding) shard; stage_params /
        shared are this rank's (pp, mp) shards. ``key``: PRNG key for the
        dropout streams (None ⇒ deterministic eval). Returns the replicated
        mean loss."""
        n = lax.axis_size(PP_AXIS)
        s_idx = lax.axis_index(PP_AXIS)
        m = self.microbatches
        mb = x.shape[0] // m
        x_mb = x.reshape((m, mb) + x.shape[1:])
        y_mb = y.reshape((m, mb) + y.shape[1:])
        local_stage = jax.tree_util.tree_map(lambda a: a[0], stage_params)  # [k, ...]
        k_layers = self.layers_per_stage
        use_rng = key is not None and self.model.training and (
            self.cfg.hidden_dropout_prob > 0 or self.cfg.attention_dropout_prob > 0)
        if key is None:
            key = jax.random.key(0)

        def stage_fn(h, stage_key):
            # per-layer dropout keys: fold the GLOBAL layer index into the
            # microbatch key so a sequential run derives identical masks
            layer_ids = jnp.arange(k_layers) + s_idx * k_layers
            keys = jax.vmap(lambda i: jax.random.fold_in(stage_key, i))(layer_ids)

            def body(h, xs):
                lp, lk = xs
                saved = get_rng_state()
                set_rng_state(lk)
                try:
                    out = self._apply_block(lp, h)
                finally:
                    set_rng_state(saved)
                return out, None

            h, _ = lax.scan(body, h, (local_stage, keys))
            return h

        # 1F1B memory bound: recompute stage activations in backward
        stage_fn = jax.checkpoint(stage_fn)

        ticks = m + n - 1
        t_seq, h_dim = x.shape[1], self.cfg.hidden_size
        perm = [(i, i + 1) for i in range(n - 1)]  # stage i -> i+1 (no wrap)

        def tick(carry, t):
            h_in, loss_acc = carry
            inj_mb = jnp.clip(t, 0, m - 1)
            inj_key = jax.random.fold_in(
                jax.random.fold_in(key, inj_mb), _EMBED_FOLD)
            inj = self._embed(shared, x_mb[inj_mb], inj_key if use_rng else None)
            h = jnp.where(s_idx == 0, inj, h_in)
            # stage s processes at tick t the microbatch injected at t - s
            stage_key = jax.random.fold_in(key, jnp.clip(t - s_idx, 0, m - 1))
            h = stage_fn(h, stage_key)
            out_idx = t - (n - 1)
            valid = (out_idx >= 0) & (out_idx < m)
            lbl = y_mb[jnp.clip(out_idx, 0, m - 1)]
            l = self._head_loss(shared, h, lbl)
            loss_acc = loss_acc + jnp.where((s_idx == n - 1) & valid, l, 0.0)
            h_next = lax.ppermute(h, PP_AXIS, perm)
            return (h_next, loss_acc), None

        h0 = jnp.zeros((mb, t_seq, h_dim), self.shared_params["wte"].dtype)
        (_, loss_acc), _ = lax.scan(tick, (h0, jnp.zeros((), jnp.float32)),
                                    jnp.arange(ticks))
        # Only the last stage accumulated loss. Differentiate the LOCAL value
        # (cross-stage credit flows through the ppermute transposes); the
        # psum only replicates the VALUE — routing gradient through it would
        # scale all grads by the pp degree (each shard's replicated copy
        # would contribute cotangent 1).
        local = loss_acc / m
        total = lax.psum(loss_acc, PP_AXIS) / m
        return local + lax.stop_gradient(total - local)

    # -- write trained params back into the model -------------------------
    def sync_to_model(self, stage_params, shared):
        flat = {
            n: a.reshape((self.num_stages * self.layers_per_stage,) + a.shape[2:])
            for n, a in stage_params.items()
        }
        for i, blk in enumerate(self.model.gpt.h):
            for n, p in blk.named_parameters():
                p._set_data(flat[n][i])
        emb = self.model.gpt.embeddings
        emb.word_embeddings.weight._set_data(shared["wte"])
        if "wpe" in shared:
            emb.position_embeddings.weight._set_data(shared["wpe"])
        self.model.gpt.ln_f.weight._set_data(shared["ln_f.weight"])
        self.model.gpt.ln_f.bias._set_data(shared["ln_f.bias"])


def _zero_slot_layout(pipe, optimizer, mesh, n_shard):
    """ZeRO slot layout: every param leaf's slots live flattened + padded as
    [S, M, n_shard, sz] (pp stack, mp parts, sharding slices) so each
    (pp, mp, sharding) rank holds exactly the 1/n_shard slice it updates —
    the reference's Shard._split_params (sharding/shard.py:22) re-expressed
    as an array layout instead of a param-name map."""
    layouts = {}
    slots = {}
    for grp, params, specs in (
        ("stages", pipe.stage_params, pipe.stage_specs),
        ("shared", pipe.shared_params, pipe.shared_specs),
    ):
        layouts[grp] = {}
        slots[grp] = {}
        for n, arr in params.items():
            spec = specs[n]
            local = _local_shape(arr.shape, spec, mesh)
            size = 1
            for s in local:
                size *= s
            sz = -(-size // n_shard)
            s_dim = pipe.num_stages if grp == "stages" else 1
            mp_sharded = any(d == MP_AXIS or (isinstance(d, tuple) and MP_AXIS in d)
                             for d in spec)
            m_dim = pipe.mp_size if mp_sharded else 1
            full_shape = (s_dim, m_dim, n_shard, sz)
            spec4 = P(PP_AXIS if grp == "stages" else None,
                      MP_AXIS if mp_sharded else None,
                      SH_AXIS if n_shard > 1 else None,
                      None)
            layouts[grp][n] = (size, sz, spec4)
            init = optimizer._init_slots(jnp.zeros((sz,), arr.dtype))
            slots[grp][n] = {
                sn: jax.device_put(jnp.broadcast_to(sv, full_shape),
                                   NamedSharding(mesh, spec4))
                for sn, sv in init.items()
            }
    return layouts, slots


def _clip_grads_meshaware(clip, grads, pipe, has_mp):
    """Gradient clipping inside the shard_map body: the global norm must sum
    squares over the 'pp' stack and the 'mp' shards of each leaf (reference:
    sharding/utils ClipGradByGlobalNorm cross-rank norm reduce)."""
    from ...nn.clip import ClipGradByGlobalNorm, ClipGradByValue

    if isinstance(clip, ClipGradByValue):
        from ...nn.clip import clip_grads_functional

        return clip_grads_functional(clip, grads)  # elementwise: shard-safe
    if not isinstance(clip, ClipGradByGlobalNorm):
        raise NotImplementedError(
            f"{type(clip).__name__} is shard-local; the hybrid pipeline "
            "supports ClipGradByGlobalNorm / ClipGradByValue")
    specs = {"stages": pipe.stage_specs, "shared": pipe.shared_specs}
    sumsq = jnp.zeros((), jnp.float32)
    for grp in grads:
        for n, g in grads[grp].items():
            s = jnp.sum(jnp.square(g.astype(jnp.float32)))
            spec = specs[grp][n]
            mp_sharded = any(d == MP_AXIS or (isinstance(d, tuple) and MP_AXIS in d)
                             for d in spec)
            if mp_sharded and has_mp:
                s = lax.psum(s, MP_AXIS)
            if grp == "stages":
                s = lax.psum(s, PP_AXIS)  # each pp rank owns distinct layers
            sumsq = sumsq + s
    norm = jnp.sqrt(sumsq)
    scale = clip.clip_norm / jnp.maximum(norm, clip.clip_norm)
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


def _apply_updates(optimizer, params, grads, opt_state, n_shard, has_sh, pipe,
                   has_mp):
    """Optimizer apply with ZeRO-2 semantics over 'sharding': reduce-scatter
    each (flattened) grad, update the local slot slice, all-gather params.
    Runs inside the shard_map body. Parity: sharding_optimizer.py grad
    reduce + Shard param split + broadcast-back."""
    clip = optimizer._grad_clip
    scatter = has_sh and n_shard > 1
    sliced = False
    if clip is not None:
        if scatter:
            # the norm needs fully reduced grads: trade the reduce-scatter
            # for an all-reduce, then slice
            grads = jax.tree_util.tree_map(lambda g: lax.pmean(g, SH_AXIS), grads)
            scatter = False
            sliced = True
        grads = _clip_grads_meshaware(clip, grads, pipe, has_mp)

    wd = optimizer._weight_decay_coeff
    decoupled = optimizer._decoupled_wd
    hyper = optimizer._hyper()
    lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
    step = opt_state["step"] + 1
    upd = type(optimizer)._update

    def leaf(p, g, slots):
        g = g.astype(p.dtype)
        if wd and not decoupled:
            g = g + wd * p
        size = p.size
        sz = -(-size // n_shard)
        pad = sz * n_shard - size
        gf = jnp.pad(g.reshape(-1), (0, pad))
        sl = {k: v.reshape(-1) for k, v in slots.items()}
        if scatter or sliced:
            if scatter:
                gl = lax.psum_scatter(gf, SH_AXIS, scatter_dimension=0,
                                      tiled=True) / n_shard
            else:
                gl = lax.dynamic_slice(
                    gf, (lax.axis_index(SH_AXIS) * sz,), (sz,))
            pf = jnp.pad(p.reshape(-1), (0, pad))
            pl = lax.dynamic_slice(pf, (lax.axis_index(SH_AXIS) * sz,), (sz,))
            pn, sn = upd(pl, gl, sl, lr, step, hyper)
            pnew = lax.all_gather(pn, SH_AXIS, tiled=True)[:size].reshape(p.shape)
        else:
            pn, sn = upd(jnp.pad(p.reshape(-1), (0, pad)), gf, sl, lr, step, hyper)
            pnew = pn[:size].reshape(p.shape)
        return pnew, {k: v.reshape(slots[k].shape) for k, v in sn.items()}

    new_p = {}
    new_s = {}
    for grp in params:
        new_p[grp] = {}
        new_s[grp] = {}
        for n in params[grp]:
            pn, sn = leaf(params[grp][n], grads[grp][n],
                          opt_state["slots"][grp][n])
            new_p[grp][n] = pn
            new_s[grp][n] = sn
    return new_p, {"slots": new_s, "step": step}


def build_gpt_pipeline_step(model, optimizer, *, microbatches: int,
                            num_stages: Optional[int] = None, mesh=None):
    """Build the jitted hybrid train step for a GPT model: pp x mp x dp x
    sharding composed in ONE shard_map program (the reference's north-star
    hybrid, sharding_optimizer.py:140 degrees assertion).

    The mesh may carry any subset of {'pp' (required), 'mp', 'dp',
    'sharding'} with degree > 1. Batch dim 0 is sharded over
    dp x sharding; per-param hyper overrides (AdamW apply_decay_param_fun)
    are not applied on this path.

    Returns a callable ``step(x, y) -> loss`` holding sharded params +
    optimizer state; ``step.sync_to_model()`` writes arrays back.
    """
    mesh = mesh or get_mesh()
    if mesh is None or PP_AXIS not in mesh.shape:
        raise RuntimeError("pipeline step needs a mesh with a 'pp' axis")
    num_stages = num_stages or int(mesh.shape[PP_AXIS])
    pipe = GPTPipelineModule(model, num_stages, microbatches, mesh=mesh)
    has_dp = DP_AXIS in mesh.shape and int(mesh.shape[DP_AXIS]) > 1
    has_sh = SH_AXIS in mesh.shape and int(mesh.shape[SH_AXIS]) > 1
    n_shard = int(mesh.shape.get(SH_AXIS, 1))

    param_specs = {"stages": pipe.stage_specs, "shared": pipe.shared_specs}
    params = {
        grp: {
            n: jax.device_put(a, NamedSharding(mesh, param_specs[grp][n]))
            for n, a in src.items()
        }
        for grp, src in (("stages", pipe.stage_params),
                         ("shared", pipe.shared_params))
    }
    layouts, slot_tree = _zero_slot_layout(pipe, optimizer, mesh, n_shard)
    opt_state = {
        "slots": slot_tree,
        "step": jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P())),
    }
    slot_specs = {
        grp: {n: {sn: layouts[grp][n][2] for sn in slot_tree[grp][n]}
              for n in slot_tree[grp]}
        for grp in slot_tree
    }

    def spmd_step(params, opt_state, x, y, kd):
        key = jax.random.wrap_key_data(kd)

        def loss_fn(params):
            return pipe.local_loss(params["stages"], params["shared"], x, y, key)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # local slot slices arrive [1, 1, 1, sz]: flatten for the update
        local_opt = {
            "slots": jax.tree_util.tree_map(
                lambda a: a.reshape(a.shape[-1:]), opt_state["slots"]),
            "step": opt_state["step"],
        }
        # shared (tied/replicated) params were used by several stages:
        # combine their grads over 'pp' (≙ SharedLayerDesc allreduce)
        grads["shared"] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, PP_AXIS), grads["shared"])
        if has_dp:
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, DP_AXIS), grads)
            loss = lax.pmean(loss, DP_AXIS)
        if has_sh:
            loss = lax.pmean(loss, SH_AXIS)
        new_params, new_opt = _apply_updates(
            optimizer, params, grads, local_opt, n_shard, has_sh, pipe,
            pipe.has_mp)
        # restore the [1, 1, 1, sz] layout for the out specs
        new_opt = {
            "slots": jax.tree_util.tree_map(
                lambda a: a.reshape((1, 1, 1) + a.shape), new_opt["slots"]),
            "step": new_opt["step"],
        }
        return new_params, new_opt, loss

    opt_prefix = {"slots": slot_specs, "step": P()}
    data_axes = tuple(a for a in (DP_AXIS, SH_AXIS) if a in mesh.shape)
    data_spec = P(data_axes) if data_axes else P()

    from jax import shard_map

    mapped = shard_map(
        spmd_step, mesh=mesh,
        in_specs=(param_specs, opt_prefix, data_spec, data_spec, P()),
        out_specs=(param_specs, opt_prefix, P()),
        check_vma=False,
    )
    jitted = jax.jit(mapped, donate_argnums=(0, 1))

    state = {"params": params, "opt": opt_state}

    def step(x, y):
        from ...random import split_key

        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        y = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        kd = jax.random.key_data(split_key())
        state["params"], state["opt"], loss = jitted(
            state["params"], state["opt"], x, y, kd)
        return loss

    step.pipe = pipe
    step.state = state
    step.sync_to_model = lambda: pipe.sync_to_model(
        state["params"]["stages"], state["params"]["shared"])
    return step
