"""Stage-parallel pipeline schedule: the ppermute-scan pipeline program.

Parity: the reference's pipeline schedules — static-graph
``PipelineOptimizer``/``SectionWorker`` (fluid/optimizer.py:4176,
framework/section_worker.cc:62 schedule_mode==1 (1F1B), :139 (F-then-B)) and
dygraph ``PipelineParallel.forward_backward_pipeline``
(fleet/meta_parallel/pipeline_parallel.py:80) with send_v2/recv_v2 p2p ops —
composed with tensor parallelism (partial_send p2p-under-mp,
fleet/meta_parallel/pp_utils/p2p_communication.py:149-155), ZeRO sharding
(fleet/meta_optimizers/sharding_optimizer.py:140 hybrid mp x sharding x pp x
dp degrees), expert parallelism (global_scatter/global_gather all2all,
collective/global_scatter_op.cc:19), interleaved virtual stages
(pp_layers.py get_stage_from_index with num_virtual_pipeline_stages), and
the TP RNG tracker for dropout determinism
(fleet/meta_parallel/parallel_layers/random.py).

TPU-native redesign (the canonical GSPMD/praxis collective-permute
pipeline): ONE shard_map over every mesh axis —

- 'pp'   — stages own a stacked [1, k, ...] slice of the body layers; the
  microbatch loop is a ``lax.scan`` where activations rotate stage→stage+1
  (wrapping last→first for virtual-stage chunk transitions) via
  ``lax.ppermute``. ``jax.grad`` through the scan yields the reverse
  schedule (the p2p transposes ARE the backward p2p) and ``jax.checkpoint``
  on the per-tick stage body recovers 1F1B's O(S) activation-memory bound.
- interleaved virtual stages — with v > 1 each rank holds v chunks of
  k/v layers (chunk c of rank s = global layers [(c*S+s)*kv, ...+kv)); a
  microbatch circles the ring v times, shrinking the bubble from
  (S-1)/(M+S-1) to (S-1)/(v*M+S-1) in ticks.
- 'mp'   — stage params carry their tensor-parallel shard (column/row
  splits per ``partition_spec``); blocks run the explicit Megatron
  algorithm (mp_layers' ``mp_axis_bound`` path).
- 'ep'   — expert-parallel MoE blocks run their lax.all_to_all exchange
  inside the same shard_map; expert-stacked weights are sharded over 'ep'
  while dense params are replicated over it (grads pmean'd).
- 'dp' / 'sharding' — both shard the batch; grads are pmean'd over 'dp'
  and reduce-scattered over 'sharding' (ZeRO-2), optimizer slots live
  sliced 1/n per sharding rank, updated params all-gather back. With
  ``sharding_stage=3`` the stage params themselves live sliced per rank
  ([S, M, R, n_shard, szl] layout) and are all-gathered on use inside the
  per-layer remat region — the gather's VJP reduce-scatters grads and
  backward re-gathers, so peak param memory is one layer's full weights.
- dropout — per-(microbatch, global-layer) PRNG keys are folded in inside
  the scan so masks are deterministic and reproducible by a sequential run
  (replaces the reference's RNG state tracker).

Shared (tied) embedding + final-norm + head params are replicated over 'pp'
with gradient psum, replacing the reference's SharedLayerDesc allreduce of
tied-embedding grads (pp_layers.py:49).
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding

from ...autograd import tape
from ...profiler.scope import annotate as prof_annotate
from ...profiler.scope import scope as prof_scope
from ...profiler.scope import timer_registry, timers_enabled
from ...random import get_rng_state, set_rng_state
from ...tensor import Tensor
from ..env import get_mesh
from ..spmd import P, sanitize_spec
from .mp_layers import (
    MP_AXIS,
    mp_allreduce_array,
    mp_axis_bound,
    mp_identity_array,
)

__all__ = [
    "build_gpt_pipeline_step",
    "build_pipeline_layer_step",
    "stack_layer_params",
    "GPTPipelineModule",
    "PipelineModule",
]

PP_AXIS = "pp"


def _remat_jax_policy(remat_policy: str):
    """Map a schedule remat_policy name to a jax.checkpoint policy — the
    shared table in ops/pallas/flash_attention.py, where 'selective'
    additionally saves the flash forward via its checkpoint_name tags so
    the backward never replays the Pallas kernel."""
    from ...ops.pallas.flash_attention import granularity_policy

    return granularity_policy(remat_policy)
DP_AXIS = "dp"
SH_AXIS = "sharding"
EP_AXIS = "ep"
_EMBED_FOLD = 1 << 20  # fold_in tag separating the embed-dropout stream


def stack_layer_params(blocks):
    """[{name: arr}] per block → {name: arr[N, ...]} stacked."""
    trees = [{n: p._data for n, p in blk.named_parameters()} for blk in blocks]
    return {n: jnp.stack([t[n] for t in trees]) for n in trees[0]}


def _keep_axes(spec: P, axes=(MP_AXIS, EP_AXIS)) -> P:
    """Keep only model-sharding placements ('mp'/'ep') of a partition spec
    (dp/fsdp annotations don't apply to stacked pipeline params)."""
    dims = []
    for d in spec:
        hit = None
        for a in axes:
            if d == a or (isinstance(d, tuple) and a in d):
                hit = a
                break
        dims.append(hit)
    return P(*dims)


def _spec_has(spec, axis) -> bool:
    return any(d == axis or (isinstance(d, tuple) and axis in d) for d in spec)


def _local_shape(global_shape, spec, mesh):
    dims = list(spec) + [None] * (len(global_shape) - len(spec))
    out = []
    for s, d in zip(global_shape, dims):
        if d is None:
            out.append(s)
        else:
            axes = (d,) if isinstance(d, str) else tuple(d)
            f = 1
            for a in axes:
                f *= int(mesh.shape[a])
            out.append(s // f)
    return tuple(out)


def _block_signature(layer) -> tuple:
    """Structural identity of a layer: type + named param shapes/dtypes.
    Slots whose (stage, chunk) instances share a signature can be stacked."""
    return (type(layer).__name__,
            tuple((n, tuple(p._data.shape), str(p._data.dtype))
                  for n, p in sorted(layer.named_parameters())))


class PipelineModule:
    """Generic functional pipeline program over a uniform body of blocks.

    ``blocks``: N layers forming the pipelined body. They are segmented as
    N = S * v * kv (stages x virtual chunks x layers-per-chunk); chunk c of
    stage s owns global layers [(c*S+s)*kv, (c*S+s+1)*kv) — the reference's
    interleaved assignment (pp_layers.py get_stage_from_index). At every
    slot position i in [0, kv), all (s, c) instances must be structurally
    identical so their params stack to [S, v, ...]; heterogeneous patterns
    (e.g. MoE-every-2) are legal as long as the pattern period divides kv.

    Subclasses provide ``_inject`` (shared, x_mb, key) -> h0 (the stage-0
    input computation) and ``_head_loss`` (shared, h, y_mb) -> scalar (the
    last-stage loss), plus the shared (pp-replicated) param group.
    """

    def __init__(self, blocks, num_stages: int, microbatches: int, *,
                 mesh=None, num_virtual_stages: int = 1, training: bool = True,
                 aux_of: Optional[Callable] = None, aux_weight: float = 0.0,
                 remat_policy: str = "full", scan_unroll: int = 1,
                 sharding_stage: int = 2):
        mesh = mesh or get_mesh()
        self.mesh = mesh
        self.mp_size = int(mesh.shape.get(MP_AXIS, 1)) if mesh is not None else 1
        self.has_mp = self.mp_size > 1
        self.num_stages = num_stages
        self.num_virtual = int(num_virtual_stages)
        self.microbatches = microbatches
        self._training = training
        self._aux_of = aux_of
        self._aux_weight = aux_weight
        if remat_policy not in ("full", "selective", "core_attn", "none"):
            raise ValueError("remat_policy must be 'full' (recompute each "
                             "layer, min memory), 'selective' (keep "
                             "weight-matmul and flash-attention outputs, "
                             "fewer recompute flops), 'core_attn' (keep only "
                             "flash-attention outputs) "
                             "or 'none' (save everything, max speed)")
        self._remat_policy = remat_policy
        self._scan_unroll = max(int(scan_unroll), 1)
        n_layers = len(blocks)
        sv = num_stages * self.num_virtual
        if n_layers % sv != 0:
            raise ValueError(
                f"layer count {n_layers} must be divisible by stages x "
                f"virtual chunks = {num_stages} x {self.num_virtual}")
        self.layers_per_chunk = kv = n_layers // sv
        self.layers_per_stage = kv * self.num_virtual  # rows per stage

        # layer at (slot i, stage s, chunk c) = blocks[(c*S+s)*kv + i]
        self._blocks = list(blocks)
        self.slot_templates: List = [self._blocks[i] for i in range(kv)]
        for i in range(kv):
            sig0 = _block_signature(self.slot_templates[i])
            for c in range(self.num_virtual):
                for s in range(num_stages):
                    blk = self._blocks[(c * num_stages + s) * kv + i]
                    if _block_signature(blk) != sig0:
                        raise ValueError(
                            f"pipeline slot {i}: layer {(c*num_stages+s)*kv+i} "
                            f"({type(blk).__name__}) does not match the slot "
                            f"template ({type(self.slot_templates[i]).__name__});"
                            " stage/chunk structures must align (e.g. "
                            "moe_every must divide layers-per-chunk)")
        homog = all(_block_signature(t) == _block_signature(self.slot_templates[0])
                    for t in self.slot_templates)
        self._scan_body = homog

        # per-slot tensor placement (Megatron column/row + expert stacking)
        def spec_of_block(blk):
            out = {}
            for n, p in blk.named_parameters():
                spec = getattr(p, "partition_spec", None) or P()
                if mesh is not None:
                    spec = sanitize_spec(spec, mesh)
                out[n] = _keep_axes(spec)
            return out

        # stage params: {name: [S, k, ...]} (k = v*kv rows per stage, chunk-
        # major) when homogeneous — scanned; else {"slot{i}.name": [S, v, ...]}
        self.stage_params = {}
        self.stage_specs = {}
        # pp=1, v=1 keeps each layer's params as SEPARATE leaves: the
        # stacked [1, k, ...] layout makes every layer's weights a slice of
        # one big buffer, which costs ~25% step time vs the plain layout on
        # v5e (XLA layouts/prefetch). sharding_stage=3 keeps the stacked
        # form (its flat-slice machinery needs the row dim).
        mesh_pp = int(mesh.shape.get(PP_AXIS, 1)) if mesh is not None else 1
        unstack_ok = (num_stages == 1 and self.num_virtual == 1
                      and mesh_pp == 1 and int(sharding_stage) < 3)
        self._unstacked_pp1 = bool(self._scan_body and unstack_ok)
        if self._scan_body and unstack_ok:
            bspec = spec_of_block(self.slot_templates[0])
            for i in range(kv):
                blk = self._blocks[i]
                for n, p in blk.named_parameters():
                    self.stage_params[f"L{i}.{n}"] = p._data
                    self.stage_specs[f"L{i}.{n}"] = bspec[n]  # pre-sanitized
        elif self._scan_body:
            rows = []  # per stage: list of blocks in (chunk, slot) order
            for s in range(num_stages):
                stage_rows = []
                for c in range(self.num_virtual):
                    for i in range(kv):
                        stage_rows.append(self._blocks[(c * num_stages + s) * kv + i])
                rows.append(stack_layer_params(stage_rows))
            bspec = spec_of_block(self.slot_templates[0])
            for n in rows[0]:
                self.stage_params[n] = jnp.stack([r[n] for r in rows])
                self.stage_specs[n] = P(PP_AXIS, None, *bspec[n])
        else:
            for i, tmpl in enumerate(self.slot_templates):
                bspec = spec_of_block(tmpl)
                insts = {}
                for n, _ in tmpl.named_parameters():
                    per_stage = []
                    for s in range(num_stages):
                        per_chunk = [
                            dict(self._blocks[(c * num_stages + s) * kv + i]
                                 .named_parameters())[n]._data
                            for c in range(self.num_virtual)
                        ]
                        per_stage.append(jnp.stack(per_chunk))
                    insts[n] = jnp.stack(per_stage)  # [S, v, ...]
                for n, arr in insts.items():
                    self.stage_params[f"slot{i}.{n}"] = arr
                    self.stage_specs[f"slot{i}.{n}"] = P(PP_AXIS, None, *bspec[n])

        self.shared_params = {}
        self.shared_specs = {}

        # ZeRO stage-3 over 'sharding': stage-stacked params live sliced
        # 1/n_shard per rank (per layer row) and are all-gathered on use
        # inside the per-layer remat region — the gather's VJP is the
        # reduce-scatter of grads, and backward re-gathers (gather-on-use
        # both directions). Parity: sharding_optimizer.py stage=3 +
        # sharding/shard.py:22 param split, redesigned as an array layout.
        self._stage3 = False
        self._s3meta = {}
        n_shard = int(mesh.shape.get(SH_AXIS, 1)) if mesh is not None else 1
        if int(sharding_stage) >= 3 and n_shard > 1:
            self._to_stage3_layout(mesh, n_shard)

    # -- ZeRO-3 layout ----------------------------------------------------
    def _to_stage3_layout(self, mesh, n_shard):
        """Re-lay stage params [S, R, *rest] → [S, M, R, n_shard, szl]:
        model-axis parts explicit (dim 1), each layer row flattened, padded
        and split into n_shard slices (dim 3). shard_map in_specs then give
        each (pp, mp|ep, sharding) rank exactly its [R, szl] slice."""
        new_params, new_specs = {}, {}
        for n, arr in self.stage_params.items():
            spec = self.stage_specs[n]
            bspec = P(*tuple(spec)[2:])
            rest = arr.shape[2:]
            model_axis = next((ax for ax in (MP_AXIS, EP_AXIS)
                               if _spec_has(bspec, ax)), None)
            m_dim = int(mesh.shape.get(model_axis, 1)) if model_axis else 1
            local_rest = _local_shape(rest, bspec, mesh)
            lsz = 1
            for s in local_rest:
                lsz *= s
            szl = -(-lsz // n_shard)
            pad = szl * n_shard - lsz
            S, R = arr.shape[:2]
            if model_axis and m_dim > 1:
                d = next(i for i, x in enumerate(tuple(bspec))
                         if x == model_axis
                         or (isinstance(x, tuple) and model_axis in x))
                parts = jnp.split(arr, m_dim, axis=2 + d)
            else:
                parts = [arr]
            flat = jnp.stack([p.reshape(S, R, lsz) for p in parts], axis=1)
            flat = jnp.pad(flat, ((0, 0), (0, 0), (0, 0), (0, pad)))
            new_params[n] = flat.reshape(S, m_dim, R, n_shard, szl)
            new_specs[n] = P(PP_AXIS, model_axis, None, SH_AXIS, None)
            self._s3meta[n] = (tuple(local_rest), lsz, szl, model_axis,
                               tuple(rest))
        self.stage_params = new_params
        self.stage_specs = new_specs
        self._stage3 = True
        self._s3_nshard = n_shard

    def _s3_gather(self, lp_flat, prefix=""):
        """All-gather one layer's param slices over 'sharding' and restore
        their (model-local) shapes. Runs inside the per-layer checkpoint so
        backward re-gathers (ZeRO-3 allgather-on-use)."""
        out = {}
        for n, v in lp_flat.items():
            local_rest, lsz, _szl, _ax, _rest = self._s3meta[prefix + n]
            full = lax.all_gather(v, SH_AXIS, tiled=True)
            out[n] = full[:lsz].reshape(local_rest)
        return out

    def maybe_from_stage3(self, stages):
        """Inverse layout transform: [S, M, R, n_shard, szl] → [S, R, *rest]
        (host side, for sync_to_model / tests)."""
        if not self._stage3:
            return stages
        out = {}
        for n, arr in stages.items():
            local_rest, lsz, szl, model_axis, rest = self._s3meta[n]
            S, m_dim, R = arr.shape[:3]
            flat = arr.reshape(S, m_dim, R, arr.shape[3] * szl)[..., :lsz]
            parts = flat.reshape((S, m_dim, R) + local_rest)
            if model_axis and m_dim > 1:
                # the model-sharded rest dim is the one whose size shrank
                d = next(i for i in range(len(rest))
                         if rest[i] != local_rest[i])
                out[n] = jnp.concatenate(
                    [parts[:, j] for j in range(m_dim)], axis=2 + d)
            else:
                out[n] = parts[:, 0]
        return out

    def param_memory_report(self):
        """Per-rank stage-param bytes under the current layout (the ZeRO-3
        accounting line: stage bytes ÷ (mp|ep parts × shard degree))."""
        stage_global = 0
        stage_local = 0
        for n, arr in self.stage_params.items():
            nbytes = arr.size * arr.dtype.itemsize
            stage_global += nbytes
            local = _local_shape(arr.shape, self.stage_specs[n], self.mesh)
            lsize = 1
            for s in local:
                lsize *= s
            stage_local += lsize * arr.dtype.itemsize
        shared = sum(a.size * a.dtype.itemsize
                     for a in self.shared_params.values())
        return {
            "stage_param_bytes_global": stage_global,
            "stage_param_bytes_per_rank": stage_local,
            "shared_param_bytes": shared,
            "sharding_degree": getattr(self, "_s3_nshard", 1)
            if self._stage3 else 1,
            "stage3": self._stage3,
        }

    # -- hooks -----------------------------------------------------------
    def _inject(self, shared, x_mb, key=None):
        raise NotImplementedError

    def _head_loss(self, shared, h, y_mb):
        raise NotImplementedError

    def _h0_shape_dtype(self, shared, x):
        """Shape/dtype of the rotating activation, from the inject hook
        (``shared`` is the rank-local tree when tracing inside shard_map)."""
        mb = x.shape[0] // self.microbatches
        spec = jax.eval_shape(
            lambda sh, xa: self._inject(sh, xa), shared,
            jax.ShapeDtypeStruct((mb,) + tuple(x.shape[1:]), x.dtype))
        return spec.shape, spec.dtype

    # -- functional pieces ------------------------------------------------
    def _apply_slot(self, template, layer_params, h):
        """One body layer, pure. Inside an 'mp'/'ep' shard_map region the
        params are the local shards and the block runs the explicit
        collectives (mp_layers / moe_layer bound paths). Returns (h, aux)."""
        with tape.no_grad():
            out, _ = template.functional_call_with_state(layer_params, {}, Tensor(h))
        aux = self._aux_of(template) if self._aux_of is not None else None
        if aux is None:
            aux = jnp.zeros((), jnp.float32)
        elif isinstance(aux, Tensor):
            aux = aux._data
        return out._data, jnp.asarray(aux, jnp.float32)

    def _apply_block(self, layer_params, h):
        """Single-template compat form (tests' dense references): apply one
        body layer, return the hidden only."""
        out, _ = self._apply_slot(self.slot_templates[0], layer_params, h)
        return out

    def _run_layer(self, tmpl, lp, h, lk, prefix=""):
        """One body layer under the per-layer checkpoint policy (shared by
        the scheduled path, the pp=1 specialization and the profiler's
        stage probes).

        Per-layer remat: without it the tick backward materializes EVERY
        layer's residuals (e.g. [k, mb, T, 4H] MLP intermediates)
        simultaneously — per-layer checkpoint bounds that to one layer
        ('full') or its dot outputs ('selective'). NOTE: this is the ONLY
        checkpoint level — wrapping the stage body as well would recompute
        the forward twice (measured +35% step time at 350m)."""
        def _one(lp, h, lk):
            if self._stage3:
                # ZeRO-3 allgather-on-use inside the remat region: the
                # checkpoint saves only the [szl] slices; backward
                # re-gathers, and the gather's VJP reduce-scatters grads
                lp = self._s3_gather(lp, prefix)
            saved = get_rng_state()
            set_rng_state(lk)
            try:
                out, aux = self._apply_slot(tmpl, lp, h)
            finally:
                set_rng_state(saved)
            return out, aux

        with prof_scope("pp.stage_compute"):
            if self._remat_policy == "none":
                return _one(lp, h, lk)
            policy = _remat_jax_policy(self._remat_policy)
            return jax.checkpoint(_one, policy=policy)(lp, h, lk)

    @prof_annotate("pipeline.stage_apply")
    def _stage_apply(self, local_stage, c, s_idx, h, mb_key):
        """Apply this rank's chunk ``c`` (kv layers) to h. local_stage leaves
        are [k, ...] (scan layout, chunk-major rows) or [v, ...] per slot."""
        kv = self.layers_per_chunk
        n = self.num_stages
        layer_base = (c * n + s_idx) * kv  # global index of the chunk's 1st layer

        run_layer = self._run_layer

        if self._scan_body:
            chunk = jax.tree_util.tree_map(
                lambda a: lax.dynamic_slice_in_dim(a, c * kv, kv, axis=0),
                local_stage)
            keys = jax.vmap(lambda i: jax.random.fold_in(mb_key, i))(
                jnp.arange(kv) + layer_base)
            tmpl = self.slot_templates[0]

            def body(h, xs):
                lp, lk = xs
                out, aux = run_layer(tmpl, lp, h, lk)
                return out, aux

            h, auxs = lax.scan(body, h, (chunk, keys),
                               unroll=min(self._scan_unroll, kv))
            return h, jnp.sum(auxs)
        aux_sum = jnp.zeros((), jnp.float32)
        for i, tmpl in enumerate(self.slot_templates):
            prefix = f"slot{i}."
            lp = {
                name[len(prefix):]: lax.dynamic_index_in_dim(
                    arr, c, axis=0, keepdims=False)
                for name, arr in local_stage.items()
                if name.startswith(prefix)
            }
            lk = jax.random.fold_in(mb_key, layer_base + i)
            h, aux = run_layer(tmpl, lp, h, lk, prefix=prefix)
            aux_sum = aux_sum + aux
        return h, aux_sum

    def _tick_indices(self, t, s_idx, n):
        """The interleaved schedule's per-tick bookkeeping: which (virtual
        chunk ``c``, clipped microbatch ``mb_c``) this rank addresses at
        tick ``t``, and whether the tick is valid. ``n`` is the pp degree
        (the bound axis size inside shard_map). Shared by the tick loop
        and the profiler's bookkeeping probe so the probe cannot diverge
        from the real schedule."""
        v, m = self.num_virtual, self.microbatches
        p = t - s_idx
        r = jnp.where(p >= 0, p % n, 0)
        q = jnp.where(p >= 0, (p - r) // n, 0)
        c = q % v          # virtual chunk this rank applies at tick t
        g = q // v
        mb_i = g * n + r   # microbatch currently at this rank
        valid = (p >= 0) & (mb_i < m)
        mb_c = jnp.clip(mb_i, 0, m - 1).astype(jnp.int32)
        return c, mb_c, valid

    def _local_stage_view(self, stage_params):
        """This rank's stage leaves as the layer-apply layout: strip the
        pp-stack dim (except unstacked pp=1) and flatten ZeRO-3 slices to
        [R, szl] rows. Shared with the profiler's tick probes."""
        if self._unstacked_pp1:
            local_stage = stage_params  # per-layer leaves, no stage dim
        else:
            local_stage = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        if self._stage3:
            # [1, R, 1, szl] local slice → [R, szl] rows of flat slices
            local_stage = {
                n: a.reshape(a.shape[1], a.shape[3])
                for n, a in local_stage.items()
            }
        return local_stage

    # -- the pipelined local loss (runs inside shard_map) -----------------
    @prof_annotate("pipeline.local_loss")
    def local_loss(self, stage_params, shared, x, y, key=None):
        """x, y: [M*mb, T...] on this data shard; stage_params / shared are
        this rank's (pp, mp, ep) shards. ``key``: PRNG key for the dropout
        streams (None ⇒ deterministic eval). Returns the replicated mean
        loss (CE + weighted aux)."""
        n = lax.axis_size(PP_AXIS)
        s_idx = lax.axis_index(PP_AXIS)
        m = self.microbatches
        v = self.num_virtual
        mb = x.shape[0] // m
        x_mb = x.reshape((m, mb) + x.shape[1:])
        y_mb = y.reshape((m, mb) + y.shape[1:])
        local_stage = self._local_stage_view(stage_params)
        use_rng = key is not None and self._training and self._has_dropout()
        if key is None:
            key = jax.random.key(0)

        if n == 1 and v == 1:
            # degenerate pipeline: the ring permute is the identity and
            # every tick is a whole microbatch — skip the schedule machinery
            # entirely (the reference pays its schedule cost only when
            # pp > 1, section_worker.cc:62) and run straight
            # microbatch-accumulation with statically-indexed layers so XLA
            # optimizes across layers like the plain step
            return self._pp1_loss(local_stage, shared, x_mb, y_mb, key,
                                  use_rng)

        def stage_fn(h, c, mb_key):
            return self._stage_apply(local_stage, c, s_idx, h, mb_key)

        # interleaved schedule: microbatches are injected in groups of n;
        # group g's microbatch r enters the ring at tick g*v*n + r and
        # circles it v times. ticks: v*m + n - 1 for m % n == 0.
        #
        # Overlap-optimized tick (r6): the stage-boundary transfer — the
        # ppermute of the PREVIOUS tick's output — is issued FIRST, so the
        # activation rotation overlaps everything that does not depend on
        # it: the previous tick's CE head (deferred one tick through the
        # scan carry exactly for this purpose) and this tick's embedding
        # lookup. The CE head and the inject run under lax.cond, so only
        # the ranks the schedule addresses (last stage / first stage) spend
        # the [mb, T, V] head or embedding work — every other rank's tick
        # is stage compute plus the in-flight boundary permute. The cond
        # predicates depend on (pp rank, tick) only, so they are uniform
        # across 'mp'/'ep' groups and the collectives inside the branches
        # stay consistent. All per-tick bookkeeping (which microbatch the
        # deferred head belongs to and whether it is live) rides in the
        # scanned carry: the whole schedule is ONE jitted lax.scan with no
        # per-tick host sync in the steady-state 1F1B region.
        ticks = self.schedule_ticks()
        perm = [(i, (i + 1) % n) for i in range(n)]  # ring (wrap = next chunk)
        is_last = s_idx == n - 1

        def head_if(live, h, mb_i):
            with prof_scope("pp.head_loss"):
                return lax.cond(
                    live,
                    lambda hh, i: self._head_loss(shared, hh, y_mb[i]),
                    lambda hh, i: jnp.zeros((), jnp.float32),
                    h, mb_i)

        def tick(carry, t):
            h_prev, prev_mb, prev_live, loss_acc, aux_acc = carry
            # (1) boundary transfer first: the previous tick's output
            # starts rotating before anything else is scheduled
            with prof_scope("pp.boundary_ppermute"):
                h_in = lax.ppermute(h_prev, PP_AXIS, perm)
            # (2) the deferred CE head of the previous tick's output — off
            # the permute's critical path (it reads h_prev, not h_in)
            loss_acc = loss_acc + head_if(prev_live, h_prev, prev_mb)
            # (3) schedule bookkeeping for this tick
            c, mb_c, valid = self._tick_indices(t, s_idx, n)
            # (4) first-stage inject — also independent of the permute
            with prof_scope("pp.inject"):
                def inject(hp, i):
                    inj_key = jax.random.fold_in(
                        jax.random.fold_in(key, i), _EMBED_FOLD)
                    return self._inject(shared, x_mb[i],
                                        inj_key if use_rng else None)

                h = lax.cond((s_idx == 0) & (c == 0), inject,
                             lambda hp, i: hp, h_in, mb_c)
            # (5) the stage body
            with prof_scope("pp.stage_compute"):
                mb_key = jax.random.fold_in(key, mb_c)
                h, aux = stage_fn(h, c, mb_key)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            live = is_last & (c == v - 1) & valid
            return (h, mb_c, live, loss_acc, aux_acc), None

        h_shape, h_dtype = self._h0_shape_dtype(shared, x)
        h0 = jnp.zeros(h_shape, h_dtype)
        carry0 = (h0, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.bool_),
                  jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        (h_tail, tail_mb, tail_live, loss_acc, aux_acc), _ = lax.scan(
            tick, carry0, jnp.arange(ticks))
        # the final tick's deferred head (every other tick's ran inside its
        # successor)
        loss_acc = loss_acc + head_if(tail_live, h_tail, tail_mb)
        # Only the last stage accumulated CE loss; every rank accumulated its
        # own layers' aux. Differentiate the LOCAL value (cross-stage credit
        # flows through the ppermute transposes); the psum only replicates
        # the VALUE — routing gradient through it would scale all grads by
        # the pp degree (each shard's replicated copy would contribute
        # cotangent 1).
        total = loss_acc / m
        if self._aux_weight:
            total = total + self._aux_weight * aux_acc / m
        rep = lax.psum(total, PP_AXIS)
        return total + lax.stop_gradient(rep - total)

    def _pp1_body(self, local_stage, h, mb_key):
        """The kv statically-indexed body layers of one microbatch (shared
        by :meth:`_pp1_loss` and the profiler's pp=1 stage probe). Returns
        (h, aux_sum)."""
        kv = self.layers_per_chunk
        aux_acc = jnp.zeros((), jnp.float32)
        if self._unstacked_pp1:
            tmpl = self.slot_templates[0]
            for i in range(kv):
                prefix = f"L{i}."
                lp = {nm[len(prefix):]: a
                      for nm, a in local_stage.items()
                      if nm.startswith(prefix)}
                h, aux = self._run_layer(tmpl, lp, h,
                                         jax.random.fold_in(mb_key, i))
                aux_acc = aux_acc + aux
        elif self._scan_body:
            tmpl = self.slot_templates[0]
            for i in range(kv):
                lp = jax.tree_util.tree_map(lambda a, i=i: a[i],
                                            local_stage)
                h, aux = self._run_layer(tmpl, lp, h,
                                         jax.random.fold_in(mb_key, i))
                aux_acc = aux_acc + aux
        else:
            for i, tmpl in enumerate(self.slot_templates):
                prefix = f"slot{i}."
                lp = {nm[len(prefix):]: arr[0]
                      for nm, arr in local_stage.items()
                      if nm.startswith(prefix)}
                h, aux = self._run_layer(tmpl, lp, h,
                                         jax.random.fold_in(mb_key, i),
                                         prefix=prefix)
                aux_acc = aux_acc + aux
        return h, aux_acc

    def _pp1_loss(self, local_stage, shared, x_mb, y_mb, key, use_rng):
        """pp=1, v=1 specialization: plain microbatch accumulation with
        statically-indexed layers — no ppermute, no tick scan, no dynamic
        weight slicing, no per-tick guards. PRNG folding matches the
        scheduled path exactly (per-(microbatch, layer) keys), so dropout
        masks are identical to a pp>1 run of the same program."""
        total = jnp.zeros((), jnp.float32)
        aux_acc = jnp.zeros((), jnp.float32)
        for j in range(self.microbatches):
            mb_key = jax.random.fold_in(key, j)
            inj_key = jax.random.fold_in(mb_key, _EMBED_FOLD)
            with prof_scope("pp.inject"):
                h = self._inject(shared, x_mb[j], inj_key if use_rng else None)
            h, aux = self._pp1_body(local_stage, h, mb_key)
            aux_acc = aux_acc + aux
            with prof_scope("pp.head_loss"):
                total = total + self._head_loss(shared, h, y_mb[j])
        total = total / self.microbatches
        if self._aux_weight:
            total = total + self._aux_weight * aux_acc / self.microbatches
        return total

    def _has_dropout(self) -> bool:
        return False

    def schedule_ticks(self) -> int:
        """Scan length of the schedule. Each tick applies one kv-layer chunk
        per rank; per-rank useful work is microbatches x virtual chunks, so
        the bubble fraction is 1 - m*v/ticks — interleaving (v > 1) shrinks
        it from (S-1)/(m+S-1) toward (S-1)/(v*m+S-1) (section_worker.cc:62
        1F1B vs :139 F-then-B schedules, Megatron interleaved analog)."""
        m, n, v = self.microbatches, self.num_stages, self.num_virtual
        return ((m - 1) // n) * (v * n) + ((m - 1) % n) + v * n

    def bubble_fraction(self) -> float:
        return 1.0 - (self.microbatches * self.num_virtual) / self.schedule_ticks()

    # -- write trained params back into the model -------------------------
    def sync_to_model(self, stage_params, shared):
        kv = self.layers_per_chunk
        n = self.num_stages
        if self._unstacked_pp1:
            for i in range(kv):
                blk = self._blocks[i]
                for pname, p in blk.named_parameters():
                    p._set_data(stage_params[f"L{i}.{pname}"])
        elif self._scan_body:
            for s in range(n):
                for c in range(self.num_virtual):
                    for i in range(kv):
                        blk = self._blocks[(c * n + s) * kv + i]
                        row = c * kv + i
                        for pname, p in blk.named_parameters():
                            p._set_data(stage_params[pname][s, row])
        else:
            for s in range(n):
                for c in range(self.num_virtual):
                    for i in range(kv):
                        blk = self._blocks[(c * n + s) * kv + i]
                        for pname, p in blk.named_parameters():
                            p._set_data(stage_params[f"slot{i}.{pname}"][s, c])


class GPTPipelineModule(PipelineModule):
    """Pipeline program for a GPTForPretraining model.

    Parameters:
      - ``stages``: {name: [S, k, ...]} — dim 0 on 'pp', tensor-parallel
        dims on 'mp' / expert dims on 'ep' per ``partition_spec``
      - ``shared``: tied wte (vocab on 'mp') / wpe / final LN
    """

    def __init__(self, model, num_stages: int, microbatches: int, mesh=None,
                 num_virtual_stages: int = 1, remat_policy: str = "full",
                 scan_unroll: int = 1, sharding_stage: int = 2):
        cfg = model.gpt.config
        aux_w = float(getattr(cfg, "moe_aux_loss_weight", 0.0) or 0.0)

        def aux_of(blk):
            if getattr(blk, "is_moe", False) and blk.mlp.l_aux is not None:
                return blk.mlp.l_aux
            return None

        super().__init__(
            list(model.gpt.h), num_stages, microbatches, mesh=mesh,
            num_virtual_stages=num_virtual_stages, training=model.training,
            aux_of=aux_of if getattr(cfg, "num_experts", 0) else None,
            aux_weight=aux_w, remat_policy=remat_policy,
            scan_unroll=scan_unroll, sharding_stage=sharding_stage)
        self.model = model
        self.cfg = cfg
        emb = model.gpt.embeddings
        self.shared_params = {
            "wte": emb.word_embeddings.weight._data,
            "ln_f.weight": model.gpt.ln_f.weight._data,
            "ln_f.bias": model.gpt.ln_f.bias._data,
        }
        self.shared_specs = {
            "wte": P(MP_AXIS, None) if self.has_mp else P(),
            "ln_f.weight": P(), "ln_f.bias": P(),
        }
        if getattr(emb, "use_wpe", True):  # rope configs carry no wpe
            self.shared_params["wpe"] = emb.position_embeddings.weight._data
            self.shared_specs["wpe"] = P()

    def _has_dropout(self) -> bool:
        return (self.cfg.hidden_dropout_prob > 0
                or self.cfg.attention_dropout_prob > 0)

    def _h0_shape_dtype(self, shared, x):
        mb = x.shape[0] // self.microbatches
        return (mb, x.shape[1], self.cfg.hidden_size), shared["wte"].dtype

    def _inject(self, shared, ids, key=None):
        t = ids.shape[-1]
        pos = jnp.arange(t)
        wte = shared["wte"]
        if self.has_mp and mp_axis_bound():
            # sharded-vocab lookup (c_embedding parity): mask + psum
            per = wte.shape[0]
            rank = lax.axis_index(MP_AXIS)
            local = ids - rank * per
            ok = (local >= 0) & (local < per)
            emb = jnp.take(wte, jnp.where(ok, local, 0), axis=0)
            emb = jnp.where(ok[..., None], emb, 0.0)
            emb = mp_allreduce_array(emb)
        else:
            emb = jnp.take(wte, ids, axis=0)
        h = emb + shared["wpe"][pos] if "wpe" in shared else emb
        p = self.cfg.hidden_dropout_prob
        if key is not None and p > 0.0:
            keep = jax.random.bernoulli(key, 1.0 - p, h.shape)
            h = jnp.where(keep, h / (1.0 - p), 0.0).astype(h.dtype)
        return h

    _embed = _inject  # historical name (tests' dense references)

    def _head_loss(self, shared, h, labels):
        eps = self.cfg.layer_norm_epsilon
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        hn = (h - mu) / jnp.sqrt(var + eps) * shared["ln_f.weight"] + shared["ln_f.bias"]
        lbl = labels.astype(jnp.int32)
        valid = lbl != -100  # ignore_index parity with GPTPretrainingCriterion
        safe = jnp.where(valid, lbl, 0)
        if self.has_mp and mp_axis_bound():
            # vocab-sharded softmax-CE (c_softmax_with_cross_entropy parity);
            # identity-fwd/psum-bwd on h so ln_f sees the full cotangent
            hn = mp_identity_array(hn)
            logits = jnp.einsum("bth,vh->btv", hn, shared["wte"]).astype(jnp.float32)
            per = logits.shape[-1]
            start = lax.axis_index(MP_AXIS) * per
            # stop_gradient BEFORE pmax: the max shift is grad-free and pmax
            # has no JVP rule (zero-tangent operands skip it)
            m = lax.pmax(lax.stop_gradient(jnp.max(logits, -1, keepdims=True)), MP_AXIS)
            shifted = logits - m
            sum_exp = mp_allreduce_array(jnp.sum(jnp.exp(shifted), -1, keepdims=True))
            loc = safe - start
            ok = (loc >= 0) & (loc < per)
            picked = jnp.take_along_axis(shifted, jnp.where(ok, loc, 0)[..., None], -1)[..., 0]
            picked = jnp.where(ok, picked, 0.0)
            picked = mp_allreduce_array(picked)
            ll = picked - jnp.log(sum_exp[..., 0])
        else:
            # float32 softmax statistics, matching the mp branch's numerics
            # (ADVICE r5 #1: the r5 native-dtype log_softmax made the loss
            # depend on mp degree under bf16) — but WITHOUT materializing a
            # float32 [B, T, V] array: the upcast-subtract-exp chain fuses
            # into the sum reduction (bf16 HBM reads, f32 accumulation) and
            # the picked logit is gathered in the native dtype then upcast
            # ([B, T]-sized). The r5 comment's ~9% cost (sweep_r5b) was the
            # full-f32 log_softmax output; the fused form keeps the mp
            # branch's f32 max-shift/exp/log math at bf16-like traffic.
            logits = jnp.einsum("bth,vh->btv", hn, shared["wte"])
            mx = lax.stop_gradient(jnp.max(logits, -1, keepdims=True))
            shifted32 = logits.astype(jnp.float32) - mx.astype(jnp.float32)
            sum_exp = jnp.sum(jnp.exp(shifted32), -1)
            picked = jnp.take_along_axis(logits, safe[..., None], -1)[..., 0]
            ll = (picked.astype(jnp.float32)
                  - mx[..., 0].astype(jnp.float32) - jnp.log(sum_exp))
        ll = jnp.where(valid, ll.astype(jnp.float32), 0.0)
        return -ll.sum() / jnp.maximum(valid.sum(), 1)

    def sync_to_model(self, stage_params, shared):
        super().sync_to_model(stage_params, shared)
        emb = self.model.gpt.embeddings
        emb.word_embeddings.weight._set_data(shared["wte"])
        if "wpe" in shared:
            emb.position_embeddings.weight._set_data(shared["wpe"])
        self.model.gpt.ln_f.weight._set_data(shared["ln_f.weight"])
        self.model.gpt.ln_f.bias._set_data(shared["ln_f.bias"])


class _LayerStackPipelineModule(PipelineModule):
    """PipelineModule over a PipelineLayer's built layers: the maximal
    structurally-uniform run becomes the pipelined body; leading/trailing
    layers run replicated as the inject/head edges (grads psum'd over 'pp',
    the SharedLayerDesc treatment). Parity target:
    fleet/meta_parallel/parallel_layers/pp_layers.py:132 `PipelineLayer` +
    `_segment_network`:282."""

    def __init__(self, pipe_layer, num_stages: int, microbatches: int, *,
                 mesh=None, num_virtual_stages: int = 1, loss_fn=None):
        layers = list(pipe_layer.run_function)
        sv = num_stages * num_virtual_stages
        lo, hi = _uniform_body_span(layers, sv)
        if hi - lo < sv:
            raise ValueError(
                f"PipelineLayer has no structurally-uniform run of >= "
                f"{sv} layers to pipeline (found {hi - lo}); use the GSPMD "
                "fallback (ParallelTrainer)")
        # trim the run to a multiple of S*v, pushing leftovers to the edges
        extra = (hi - lo) % sv
        hi -= extra
        self._prefix = layers[:lo]
        self._suffix = layers[hi:]
        self._loss_fn = loss_fn or pipe_layer._loss_fn or (
            lambda out, y: out.mean() if hasattr(out, "mean") else jnp.mean(out))
        super().__init__(layers[lo:hi], num_stages, microbatches, mesh=mesh,
                         num_virtual_stages=num_virtual_stages,
                         training=pipe_layer.training)
        self.pipe_layer = pipe_layer
        # identity-dedup tied Parameters (SharedLayerDesc: the same tensor
        # appears in several edge layers — one shared leaf, one update)
        seen = {}
        self._edge_keymaps = {"prefix": [], "suffix": []}
        self._shared_param_tensors = {}
        for group, edge in (("prefix", self._prefix), ("suffix", self._suffix)):
            for j, lyr in enumerate(edge):
                keymap = {}
                for n, p in lyr.named_parameters():
                    pid = id(p)
                    if pid not in seen:
                        key = f"{group}.{j}.{n}"
                        seen[pid] = key
                        spec = getattr(p, "partition_spec", None) or P()
                        if self.mesh is not None:
                            spec = sanitize_spec(spec, self.mesh)
                        self.shared_params[key] = p._data
                        self.shared_specs[key] = _keep_axes(spec)
                        self._shared_param_tensors[key] = p
                    keymap[n] = seen[pid]
                self._edge_keymaps[group].append(keymap)

    def _apply_edge(self, group, edge, shared, h):
        from .pp_layers import _is_first_shared

        for j, lyr in enumerate(edge):
            keymap = self._edge_keymaps[group][j]
            tree = {n: shared[keymap[n]] for n in keymap}
            fwd = getattr(lyr, "_shared_forward", None)
            call = None
            if fwd is not None and not _is_first_shared(self.pipe_layer, lyr):
                call = (lambda *a, _l=lyr, _f=fwd: _f(_l, *a))
            with tape.no_grad():
                out, _ = lyr.functional_call_with_state(
                    tree, {}, Tensor(h), _call_fn=call)
            h = out._data if isinstance(out, Tensor) else out
        return h

    def _inject(self, shared, x_mb, key=None):
        return self._apply_edge("prefix", self._prefix, shared, x_mb)

    def _head_loss(self, shared, h, y_mb):
        out = self._apply_edge("suffix", self._suffix, shared, h)
        loss = self._loss_fn(Tensor(out), Tensor(y_mb))
        arr = loss._data if isinstance(loss, Tensor) else jnp.asarray(loss)
        return arr.astype(jnp.float32)

    def sync_to_model(self, stage_params, shared):
        super().sync_to_model(stage_params, shared)
        for key, p in self._shared_param_tensors.items():
            p._set_data(shared[key])


def _uniform_body_span(layers, min_len):
    """(lo, hi) of the longest run of structurally-identical layers."""
    sigs = [_block_signature(l) for l in layers]
    best = (0, 0)
    i = 0
    while i < len(sigs):
        j = i
        while j < len(sigs) and sigs[j] == sigs[i]:
            j += 1
        if j - i > best[1] - best[0]:
            best = (i, j)
        i = j
    return best


def _zero_slot_layout(pipe, optimizer, mesh, n_shard):
    """ZeRO slot layout: every param leaf's slots live flattened + padded as
    [S, M, n_shard, sz] (pp stack, mp/ep parts, sharding slices) so each
    (pp, mp|ep, sharding) rank holds exactly the 1/n_shard slice it updates —
    the reference's Shard._split_params (sharding/shard.py:22) re-expressed
    as an array layout instead of a param-name map.

    With NO populated 'sharding' axis (n_shard == 1) there is nothing to
    slice, so slots live in the PARAM'S OWN layout and sharding and the
    optimizer applies per leaf with no flatten/pad/slice round-trip — the
    flat form exists to give each sharding rank its slice, and only then
    (r6: the flatten/pad apply was the profiled machinery tax of the pp=1
    bench leg, VERDICT r5 weak #1)."""
    layouts = {}
    slots = {}
    for grp, params, specs in (
        ("stages", pipe.stage_params, pipe.stage_specs),
        ("shared", pipe.shared_params, pipe.shared_specs),
    ):
        layouts[grp] = {}
        slots[grp] = {}
        for n, arr in params.items():
            spec = specs[n]
            if n_shard == 1:
                # natural layout: slot leaves mirror the param leaf exactly
                init = optimizer._init_slots(jnp.zeros(arr.shape, arr.dtype))
                layouts[grp][n] = (arr.size, arr.size, spec)
                slots[grp][n] = {
                    sn: jax.device_put(sv, NamedSharding(mesh, spec))
                    for sn, sv in init.items()
                }
                continue
            if grp == "stages" and pipe._stage3:
                # slots mirror the stage-3 param layout exactly: each rank
                # updates its own [R, szl] slices in place
                szl = arr.shape[-1]
                local = _local_shape(arr.shape, spec, mesh)
                lsize = 1
                for s in local:
                    lsize *= s
                layouts[grp][n] = (lsize, szl, spec)
                init = optimizer._init_slots(jnp.zeros((szl,), arr.dtype))
                slots[grp][n] = {
                    sn: jax.device_put(jnp.broadcast_to(sv, arr.shape),
                                       NamedSharding(mesh, spec))
                    for sn, sv in init.items()
                }
                continue
            local = _local_shape(arr.shape, spec, mesh)
            size = 1
            for s in local:
                size *= s
            sz = -(-size // n_shard)
            s_dim = pipe.num_stages if grp == "stages" else 1
            model_axis = None
            for ax in (MP_AXIS, EP_AXIS):
                if _spec_has(spec, ax):
                    model_axis = ax
                    break
            m_dim = int(mesh.shape.get(model_axis, 1)) if model_axis else 1
            full_shape = (s_dim, m_dim, n_shard, sz)
            spec4 = P(PP_AXIS if grp == "stages" else None,
                      model_axis,
                      SH_AXIS if n_shard > 1 else None,
                      None)
            layouts[grp][n] = (size, sz, spec4)
            init = optimizer._init_slots(jnp.zeros((sz,), arr.dtype))
            slots[grp][n] = {
                sn: jax.device_put(jnp.broadcast_to(sv, full_shape),
                                   NamedSharding(mesh, spec4))
                for sn, sv in init.items()
            }
    return layouts, slots


def _clip_grads_meshaware(clip, grads, pipe, mesh_axes, stage3=False):
    """Gradient clipping inside the shard_map body: the global norm must sum
    squares over the 'pp' stack and the 'mp'/'ep' shards of each leaf —
    plus, under ZeRO-3, the 'sharding' slices of stage leaves
    (reference: sharding/utils ClipGradByGlobalNorm cross-rank norm reduce)."""
    from ...nn.clip import ClipGradByGlobalNorm, ClipGradByValue

    if isinstance(clip, ClipGradByValue):
        from ...nn.clip import clip_grads_functional

        return clip_grads_functional(clip, grads)  # elementwise: shard-safe
    if not isinstance(clip, ClipGradByGlobalNorm):
        raise NotImplementedError(
            f"{type(clip).__name__} is shard-local; the hybrid pipeline "
            "supports ClipGradByGlobalNorm / ClipGradByValue")
    specs = {"stages": pipe.stage_specs, "shared": pipe.shared_specs}
    sumsq = jnp.zeros((), jnp.float32)
    for grp in grads:
        for n, g in grads[grp].items():
            s = jnp.sum(jnp.square(g.astype(jnp.float32)))
            spec = specs[grp][n]
            for ax in (MP_AXIS, EP_AXIS):
                if _spec_has(spec, ax) and ax in mesh_axes:
                    s = lax.psum(s, ax)
            if grp == "stages":
                s = lax.psum(s, PP_AXIS)  # each pp rank owns distinct layers
                if stage3:
                    s = lax.psum(s, SH_AXIS)  # ZeRO-3: distinct slices/rank
            sumsq = sumsq + s
    norm = jnp.sqrt(sumsq)
    scale = clip.clip_norm / jnp.maximum(norm, clip.clip_norm)
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


def _decay_masks(pipe, optimizer):
    """Per-leaf weight-decay applicability (AdamW apply_decay_param_fun,
    python/paddle/optimizer/adamw.py _append_decoupled_weight_decay): the
    stacked leaves of every (stage, chunk) instance share one decision,
    taken from the slot template's Parameter (name + structure)."""
    fn = getattr(optimizer, "_apply_decay_param_fun", None)
    if fn is None:
        return None
    masks = {"stages": {}, "shared": {}}
    kv = pipe.layers_per_chunk
    if pipe._scan_body:
        tmpl_params = dict(pipe.slot_templates[0].named_parameters())
        for n in pipe.stage_params:
            # unstacked pp=1 leaves are keyed "L{i}.{name}"
            base = n.split(".", 1)[1] if pipe._unstacked_pp1 else n
            masks["stages"][n] = bool(fn(tmpl_params[base].name))
    else:
        for i, tmpl in enumerate(pipe.slot_templates):
            tp = dict(tmpl.named_parameters())
            for n in tp:
                masks["stages"][f"slot{i}.{n}"] = bool(fn(tp[n].name))
    shared_tensors = getattr(pipe, "_shared_param_tensors", None)
    for n in pipe.shared_params:
        pname = None
        if shared_tensors and n in shared_tensors:
            pname = shared_tensors[n].name
        masks["shared"][n] = bool(fn(pname)) if pname is not None else bool(fn(n))
    return masks


def _apply_updates(optimizer, params, grads, opt_state, n_shard, has_sh, pipe,
                   mesh_axes, lr):
    """Optimizer apply with ZeRO-2 semantics over 'sharding': reduce-scatter
    each (flattened) grad, update the local slot slice, all-gather params.
    Runs inside the shard_map body. Parity: sharding_optimizer.py grad
    reduce + Shard param split + broadcast-back.

    Without a populated 'sharding' axis the flat machinery is skipped
    entirely: params, grads and slots stay in the param's own layout and
    each leaf updates elementwise (donated buffers alias in place)."""
    clip = optimizer._grad_clip
    scatter = has_sh and n_shard > 1
    natural = n_shard == 1  # slots in param layout (_zero_slot_layout)
    stage3 = pipe._stage3
    sliced = False
    if clip is not None:
        if scatter:
            # the norm needs fully reduced grads: trade the reduce-scatter
            # for an all-reduce, then slice. Stage-3 stage grads are already
            # reduced slices — leave them; their sq-sums psum over
            # 'sharding' inside _clip_grads_meshaware instead.
            if stage3:
                grads["shared"] = jax.tree_util.tree_map(
                    lambda g: lax.pmean(g, SH_AXIS), grads["shared"])
            else:
                grads = jax.tree_util.tree_map(
                    lambda g: lax.pmean(g, SH_AXIS), grads)
            scatter = False
            sliced = True
        grads = _clip_grads_meshaware(clip, grads, pipe, mesh_axes,
                                      stage3=stage3 and has_sh)

    wd = optimizer._weight_decay_coeff
    decoupled = optimizer._decoupled_wd
    hyper = optimizer._hyper()
    hyper_no_decay = optimizer._hyper_no_decay()
    decay_masks = _decay_masks(pipe, optimizer)
    step = opt_state["step"] + 1
    upd = type(optimizer)._update

    def leaf(p, g, slots, decay_ok, s3=False):
        g = g.astype(p.dtype)
        leaf_wd = wd if decay_ok else 0.0
        # optimizers that pack wd into their hyper tuple expose the
        # zeroed variant via _hyper_no_decay (no positional assumptions)
        leaf_hyper = hyper if decay_ok else hyper_no_decay
        if leaf_wd and not decoupled:
            g = g + leaf_wd * p
        if natural:
            # param-layout apply: elementwise over the leaf, no flatten,
            # no pad, no slice/gather-back
            return upd(p, g, slots, lr, step, leaf_hyper)
        if s3:
            # ZeRO-3 leaf: p/g/slots are this rank's slices already — update
            # in place, no re-sharding and no gather-back (the forward
            # gathers on use)
            sl = {k: v.reshape(-1) for k, v in slots.items()}
            pn, sn = upd(p.reshape(-1), g.reshape(-1), sl, lr, step,
                         leaf_hyper)
            return (pn.reshape(p.shape),
                    {k: v.reshape(slots[k].shape) for k, v in sn.items()})
        # ZeRO-2 flat leaf (n_shard > 1, which implies a populated
        # 'sharding' axis): pad + slice this rank's 1/n_shard, update,
        # all-gather back. Grads arrive either un-reduced (scatter: the
        # psum_scatter does reduce + slice in one collective) or already
        # all-reduced by the clip path (sliced: plain slice).
        size = p.size
        sz = -(-size // n_shard)
        pad = sz * n_shard - size
        gf = jnp.pad(g.reshape(-1), (0, pad))
        sl = {k: v.reshape(-1) for k, v in slots.items()}
        if scatter:
            gl = lax.psum_scatter(gf, SH_AXIS, scatter_dimension=0,
                                  tiled=True) / n_shard
        else:
            gl = lax.dynamic_slice(
                gf, (lax.axis_index(SH_AXIS) * sz,), (sz,))
        pf = jnp.pad(p.reshape(-1), (0, pad))
        pl = lax.dynamic_slice(pf, (lax.axis_index(SH_AXIS) * sz,), (sz,))
        pn, sn = upd(pl, gl, sl, lr, step, leaf_hyper)
        pnew = lax.all_gather(pn, SH_AXIS, tiled=True)[:size].reshape(p.shape)
        return pnew, {k: v.reshape(slots[k].shape) for k, v in sn.items()}

    new_p = {}
    new_s = {}
    for grp in params:
        new_p[grp] = {}
        new_s[grp] = {}
        for n in params[grp]:
            decay_ok = True if decay_masks is None else decay_masks[grp][n]
            pn, sn = leaf(params[grp][n], grads[grp][n],
                          opt_state["slots"][grp][n], decay_ok,
                          s3=stage3 and grp == "stages")
            new_p[grp][n] = pn
            new_s[grp][n] = sn
    return new_p, {"slots": new_s, "step": step}


def _build_pipeline_step(pipe, optimizer, mesh, compute_dtype=None,
                         sentinel=None):
    """Assemble the jitted hybrid train step for any PipelineModule:
    pp x mp x ep x dp x sharding composed in ONE shard_map program (the
    reference's north-star hybrid, sharding_optimizer.py:140 degrees
    assertion). ``compute_dtype`` (e.g. bfloat16) casts floating params
    inside the loss so the MXU runs bf16 while masters/grads stay f32 (AMP
    O2 master-weight pattern). ``sentinel`` (resilience.SentinelConfig)
    adds in-graph anomaly detection + skip gating; disabled/None leaves the
    trace untouched (the sentinel carry is an empty pytree)."""
    has_dp = DP_AXIS in mesh.shape and int(mesh.shape[DP_AXIS]) > 1
    has_sh = SH_AXIS in mesh.shape and int(mesh.shape[SH_AXIS]) > 1
    has_ep = EP_AXIS in mesh.shape and int(mesh.shape[EP_AXIS]) > 1
    n_shard = int(mesh.shape.get(SH_AXIS, 1))
    mesh_axes = set(mesh.shape)

    param_specs = {"stages": pipe.stage_specs, "shared": pipe.shared_specs}
    params = {
        grp: {
            n: jax.device_put(a, NamedSharding(mesh, param_specs[grp][n]))
            for n, a in src.items()
        }
        for grp, src in (("stages", pipe.stage_params),
                         ("shared", pipe.shared_params))
    }
    layouts, slot_tree = _zero_slot_layout(pipe, optimizer, mesh, n_shard)
    opt_state = {
        "slots": slot_tree,
        "step": jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P())),
    }
    use_sentinel = sentinel is not None and sentinel.enabled
    if use_sentinel:
        from ...resilience.sentinel import SENTINEL_OK, sentinel_init_state, sentinel_observe

        repl_sh = NamedSharding(mesh, P())
        sent_state = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, repl_sh), sentinel_init_state())
    else:
        sent_state = {}
    slot_specs = {
        grp: {n: {sn: layouts[grp][n][2] for sn in slot_tree[grp][n]}
              for n in slot_tree[grp]}
        for grp in slot_tree
    }

    def spmd_step(params, opt_state, x, y, kd, lr, sent):
        key = jax.random.wrap_key_data(kd)

        def loss_fn(params):
            if compute_dtype is not None:
                params = jax.tree_util.tree_map(
                    lambda a: a.astype(compute_dtype)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
            return pipe.local_loss(params["stages"], params["shared"], x, y, key)

        with prof_scope("pipeline.loss_grad"):
            loss, grads = jax.value_and_grad(loss_fn)(params)
        # shared (tied/replicated) params were used by several stages:
        # combine their grads over 'pp' (≙ SharedLayerDesc allreduce)
        grads["shared"] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, PP_AXIS), grads["shared"])
        if has_sh and pipe._stage3:
            # ZeRO-3 stage grads arrive reduce-scattered (all_gather VJP):
            # the SUM over sharding ranks of per-rank local-mean losses —
            # scale to the grad of the global MEAN loss
            grads["stages"] = jax.tree_util.tree_map(
                lambda g: g / n_shard, grads["stages"])
        if has_dp:
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, DP_AXIS), grads)
            loss = lax.pmean(loss, DP_AXIS)
        if has_ep:
            # batch is sharded over 'ep' too: dense (ep-replicated) params
            # need their grads combined; expert-sharded leaves already got
            # their cross-rank contributions through the all_to_all
            # transpose, but as a SUM of per-rank local-mean losses — scale
            # by 1/ep so both kinds of leaf carry the grad of the global
            # MEAN loss (consistent with the GSPMD ParallelTrainer EP path)
            ep_size = int(mesh.shape[EP_AXIS])
            for grp, specs in (("stages", pipe.stage_specs),
                               ("shared", pipe.shared_specs)):
                for n, g in grads[grp].items():
                    if not _spec_has(specs[n], EP_AXIS):
                        grads[grp][n] = lax.pmean(g, EP_AXIS)
                    else:
                        grads[grp][n] = g / ep_size
            loss = lax.pmean(loss, EP_AXIS)
        if has_sh:
            loss = lax.pmean(loss, SH_AXIS)
        # anomaly sentinel: loss is replicated by the reductions above, but
        # grads differ per rank (pp stages own distinct layers, ZeRO ranks
        # distinct slices) — pmin the finite verdict over EVERY mesh axis so
        # all ranks take the same keep/skip branch, or the params would
        # silently diverge across the mesh
        if use_sentinel:
            finite = jnp.asarray(True)
            if sentinel.check_nonfinite:
                for g in jax.tree_util.tree_leaves(grads):
                    finite = finite & jnp.all(jnp.isfinite(g))
            fin = finite.astype(jnp.int32)
            for ax in mesh.shape:
                fin = lax.pmin(fin, ax)
            code, new_sent = sentinel_observe(sent, loss, fin > 0, sentinel)
            ok = code == SENTINEL_OK
        else:
            new_sent = sent
            ok = None
        # slots arrive in their local layouts — param-shaped (natural),
        # [1, 1, 1, sz] (ZeRO-2) or [1, 1, R, 1, szl] (ZeRO-3); each leaf
        # reshapes (or not) for its own update and restores the layout
        with prof_scope("pipeline.optimizer_apply"):
            new_params, new_opt = _apply_updates(
                optimizer, params, grads, opt_state, n_shard, has_sh, pipe,
                mesh_axes, lr)
        if ok is not None:
            keep = lambda new, old: jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), new, old)
            new_params = keep(new_params, params)
            new_opt = keep(new_opt, opt_state)
        return new_params, new_opt, loss, new_sent

    opt_prefix = {"slots": slot_specs, "step": P()}
    data_axes = tuple(a for a in (DP_AXIS, SH_AXIS, EP_AXIS)
                      if a in mesh.shape)
    data_spec = P(data_axes) if data_axes else P()

    from ..spmd import shard_map

    mapped = shard_map(
        spmd_step, mesh=mesh,
        in_specs=(param_specs, opt_prefix, data_spec, data_spec, P(), P(), P()),
        out_specs=(param_specs, opt_prefix, P(), P()),
        check_vma=False,
    )
    jitted = jax.jit(mapped, donate_argnums=(0, 1))

    state = {"params": params, "opt": opt_state, "sentinel": sent_state}

    def step(x, y):
        from ...random import split_key

        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        y = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        kd = jax.random.key_data(split_key())
        # lr as a runtime scalar: LR schedules apply to the compiled step
        lr_now = jnp.asarray(float(optimizer.get_lr()), jnp.float32)
        # host span: time-to-return of the async dispatch (device time is
        # NOT included — jit returns after enqueue). No clock reads when
        # timers are disabled (the default).
        t0 = time.perf_counter() if timers_enabled() else None
        state["params"], state["opt"], loss, state["sentinel"] = jitted(
            state["params"], state["opt"], x, y, kd, lr_now,
            state["sentinel"])
        if t0 is not None:
            timer_registry.record("pipeline.step.host_dispatch",
                                  time.perf_counter() - t0)
        return loss

    step.pipe = pipe
    step.state = state
    step.mesh = mesh
    step.optimizer = optimizer
    step.compute_dtype = compute_dtype
    step.jitted = jitted  # exposed for AOT lowering / cost analysis
    step.sync_to_model = lambda: pipe.sync_to_model(
        pipe.maybe_from_stage3(state["params"]["stages"]),
        state["params"]["shared"])
    return step


def build_gpt_pipeline_step(model, optimizer, *, microbatches: int,
                            num_stages: Optional[int] = None, mesh=None,
                            num_virtual_stages: int = 1, compute_dtype=None,
                            remat_policy: str = "full", scan_unroll: int = 1,
                            sharding_stage: int = 2, sentinel=None):
    """Build the jitted hybrid train step for a GPT model over a mesh with
    any subset of {'pp' (required), 'mp', 'ep', 'dp', 'sharding'} axes.
    Batch dim 0 is sharded over dp x sharding x ep. Per-param AdamW decay
    overrides (apply_decay_param_fun) are honored. ``sharding_stage=3``
    additionally shards the stage params over 'sharding' with
    allgather-on-use (ZeRO-3; stage 2 shards grads + optimizer slots only).

    Returns a callable ``step(x, y) -> loss`` holding sharded params +
    optimizer state; ``step.sync_to_model()`` writes arrays back.
    """
    mesh = mesh or get_mesh()
    if mesh is None or PP_AXIS not in mesh.shape:
        raise RuntimeError("pipeline step needs a mesh with a 'pp' axis")
    num_stages = num_stages or int(mesh.shape[PP_AXIS])
    pipe = GPTPipelineModule(model, num_stages, microbatches, mesh=mesh,
                             num_virtual_stages=num_virtual_stages,
                             remat_policy=remat_policy, scan_unroll=scan_unroll,
                             sharding_stage=sharding_stage)
    # shared leaves ↔ live Parameters (decay-mask naming)
    emb = model.gpt.embeddings
    pipe._shared_param_tensors = {
        "wte": emb.word_embeddings.weight,
        "ln_f.weight": model.gpt.ln_f.weight,
        "ln_f.bias": model.gpt.ln_f.bias,
    }
    if "wpe" in pipe.shared_params:
        pipe._shared_param_tensors["wpe"] = emb.position_embeddings.weight
    return _build_pipeline_step(pipe, optimizer, mesh, compute_dtype,
                                sentinel=sentinel)


def build_pipeline_layer_step(pipe_layer, optimizer, *, microbatches: int,
                              num_stages: Optional[int] = None, mesh=None,
                              num_virtual_stages: int = 1, loss_fn=None,
                              compute_dtype=None, sentinel=None):
    """Real stage-parallel step for a generic ``PipelineLayer``: the
    structurally-uniform body rotates over 'pp' (ppermute-scan), edge layers
    run pp-replicated with psum'd grads. Raises ValueError when no uniform
    body of >= stages x virtual-chunks layers exists (callers should fall
    back to the GSPMD step loudly)."""
    mesh = mesh or get_mesh()
    if mesh is None or PP_AXIS not in mesh.shape:
        raise RuntimeError("pipeline step needs a mesh with a 'pp' axis")
    num_stages = num_stages or int(mesh.shape[PP_AXIS])
    pipe = _LayerStackPipelineModule(
        pipe_layer, num_stages, microbatches, mesh=mesh,
        num_virtual_stages=num_virtual_stages, loss_fn=loss_fn)
    return _build_pipeline_step(pipe, optimizer, mesh, compute_dtype,
                                sentinel=sentinel)
