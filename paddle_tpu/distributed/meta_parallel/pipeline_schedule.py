"""Stage-parallel pipeline schedule: the ppermute-scan pipeline program.

Parity: the reference's 1F1B pipeline — static-graph
``PipelineOptimizer``/``SectionWorker`` (fluid/optimizer.py:4176,
framework/section_worker.cc:62 schedule_mode==1) and dygraph
``PipelineParallel.forward_backward_pipeline``
(fleet/meta_parallel/pipeline_parallel.py:80) with send_v2/recv_v2 p2p ops.

TPU-native redesign (the canonical GSPMD/praxis collective-permute
pipeline): stages live on the 'pp' mesh axis under shard_map; each stage
owns a contiguous slice of decoder layers whose parameters are STACKED on a
leading stage dim (so each pp shard holds [1, k, ...] slices); the
microbatch loop is one ``lax.scan`` of M + S - 1 ticks where activations
rotate stage→stage+1 via ``lax.ppermute``. ``jax.grad`` through the scan
yields the reverse (backward) schedule — the p2p transposes ARE the
backward p2p of the reference — and ``jax.checkpoint`` on the per-tick
stage body recovers 1F1B's O(S) activation memory bound.

Scope: uniform-decoder-stack models (the GPT family — BASELINE #4's shape).
Shared (tied) embedding + final-norm + head params are replicated over 'pp'
with gradient psum, replacing the reference's SharedLayerDesc allreduce of
tied-embedding grads (pp_layers.py:49).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding

from ...autograd import tape
from ...tensor import Tensor
from ..env import get_mesh
from ..spmd import P

__all__ = ["build_gpt_pipeline_step", "stack_layer_params", "GPTPipelineModule"]

PP_AXIS = "pp"
DP_AXIS = "dp"


def stack_layer_params(blocks):
    """[{name: arr}] per block → {name: arr[N, ...]} stacked."""
    trees = [{n: p._data for n, p in blk.named_parameters()} for blk in blocks]
    return {n: jnp.stack([t[n] for t in trees]) for n in trees[0]}


class GPTPipelineModule:
    """Functional pipeline program for a GPTForPretraining model.

    Splits ``model.gpt.h`` (N uniform decoder blocks) into S = pp-degree
    stages of k = N/S layers each. Parameters:
      - ``stages``: {name: [S, k, ...]} — sharded P('pp') on dim 0
      - ``shared``: tied wte/wpe + final LN — replicated
    """

    def __init__(self, model, num_stages: int, microbatches: int):
        cfg = model.gpt.config
        if cfg.hidden_dropout_prob or cfg.attention_dropout_prob:
            raise ValueError("pipeline schedule requires dropout probs = 0 "
                             "(per-tick RNG plumbing lands with the dygraph "
                             "dropout path)")
        if getattr(cfg, "num_experts", 0):
            raise ValueError("pipeline schedule requires a uniform decoder "
                             "stack; MoE configs interleave MoE/dense blocks "
                             "with different parameter structures — use "
                             "ParallelTrainer (ep axis) for MoE models")
        n_layers = len(model.gpt.h)
        if n_layers % num_stages != 0:
            raise ValueError(f"layer count {n_layers} must be divisible by "
                             f"the stage count {num_stages}")
        self.model = model
        self.cfg = cfg
        self.num_stages = num_stages
        self.layers_per_stage = n_layers // num_stages
        self.microbatches = microbatches
        self._block = model.gpt.h[0]  # structural template for all blocks

        stacked = stack_layer_params(list(model.gpt.h))
        self.stage_params = {
            n: a.reshape((num_stages, self.layers_per_stage) + a.shape[1:])
            for n, a in stacked.items()
        }
        emb = model.gpt.embeddings
        self.shared_params = {
            "wte": emb.word_embeddings.weight._data,
            "wpe": emb.position_embeddings.weight._data,
            "ln_f.weight": model.gpt.ln_f.weight._data,
            "ln_f.bias": model.gpt.ln_f.bias._data,
        }

    # -- functional pieces ------------------------------------------------
    def _apply_block(self, layer_params, h):
        """One decoder layer, pure: layer_params {name: arr}, h [mb, T, H]."""
        with tape.no_grad():
            out, _ = self._block.functional_call_with_state(layer_params, {}, Tensor(h))
        return out._data

    def _embed(self, shared, ids):
        t = ids.shape[-1]
        pos = jnp.arange(t)
        return jnp.take(shared["wte"], ids, axis=0) + shared["wpe"][pos]

    def _head_loss(self, shared, h, labels):
        eps = self.cfg.layer_norm_epsilon
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        hn = (h - mu) / jnp.sqrt(var + eps) * shared["ln_f.weight"] + shared["ln_f.bias"]
        logits = jnp.einsum("bth,vh->btv", hn, shared["wte"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lbl = labels.astype(jnp.int32)
        valid = lbl != -100  # ignore_index parity with GPTPretrainingCriterion
        safe = jnp.where(valid, lbl, 0)
        ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        ll = jnp.where(valid, ll, 0.0)
        return -ll.sum() / jnp.maximum(valid.sum(), 1)

    # -- the pipelined local loss (runs inside shard_map over 'pp') -------
    def local_loss(self, stage_params, shared, x, y):
        """x, y: [M*mb, T] on this shard. Returns replicated mean loss."""
        n = lax.axis_size(PP_AXIS)
        s_idx = lax.axis_index(PP_AXIS)
        m = self.microbatches
        mb = x.shape[0] // m
        x_mb = x.reshape((m, mb) + x.shape[1:])
        y_mb = y.reshape((m, mb) + y.shape[1:])
        local_stage = jax.tree_util.tree_map(lambda a: a[0], stage_params)  # [k, ...]

        def stage_fn(h):
            def body(h, lp):
                return self._apply_block(lp, h), None

            h, _ = lax.scan(body, h, local_stage)
            return h

        # 1F1B memory bound: recompute stage activations in backward
        stage_fn = jax.checkpoint(stage_fn)

        ticks = m + n - 1
        t_seq, h_dim = x.shape[1], self.cfg.hidden_size
        perm = [(i, i + 1) for i in range(n - 1)]  # stage i -> i+1 (no wrap)

        def tick(carry, t):
            h_in, loss_acc = carry
            inj = self._embed(shared, x_mb[jnp.clip(t, 0, m - 1)])
            h = jnp.where(s_idx == 0, inj, h_in)
            h = stage_fn(h)
            out_idx = t - (n - 1)
            valid = (out_idx >= 0) & (out_idx < m)
            lbl = y_mb[jnp.clip(out_idx, 0, m - 1)]
            l = self._head_loss(shared, h, lbl)
            loss_acc = loss_acc + jnp.where((s_idx == n - 1) & valid, l, 0.0)
            h_next = lax.ppermute(h, PP_AXIS, perm)
            return (h_next, loss_acc), None

        h0 = jnp.zeros((mb, t_seq, h_dim), self.shared_params["wte"].dtype)
        (_, loss_acc), _ = lax.scan(tick, (h0, jnp.zeros((), jnp.float32)),
                                    jnp.arange(ticks))
        # Only the last stage accumulated loss. Differentiate the LOCAL value
        # (cross-stage credit flows through the ppermute transposes); the
        # psum only replicates the VALUE — routing gradient through it would
        # scale all grads by the pp degree (each shard's replicated copy
        # would contribute cotangent 1).
        local = loss_acc / m
        total = lax.psum(loss_acc, PP_AXIS) / m
        return local + lax.stop_gradient(total - local)

    # -- write trained params back into the model -------------------------
    def sync_to_model(self, stage_params, shared):
        flat = {
            n: a.reshape((self.num_stages * self.layers_per_stage,) + a.shape[2:])
            for n, a in stage_params.items()
        }
        for i, blk in enumerate(self.model.gpt.h):
            for n, p in blk.named_parameters():
                p._set_data(flat[n][i])
        emb = self.model.gpt.embeddings
        emb.word_embeddings.weight._set_data(shared["wte"])
        emb.position_embeddings.weight._set_data(shared["wpe"])
        self.model.gpt.ln_f.weight._set_data(shared["ln_f.weight"])
        self.model.gpt.ln_f.bias._set_data(shared["ln_f.bias"])


def build_gpt_pipeline_step(model, optimizer, *, microbatches: int,
                            num_stages: Optional[int] = None, mesh=None):
    """Build the jitted stage-parallel train step for a GPT model.

    Returns a callable ``step(x, y) -> loss`` holding sharded params +
    optimizer state; ``step.sync_to_model()`` writes arrays back.
    """
    mesh = mesh or get_mesh()
    if mesh is None or PP_AXIS not in mesh.shape:
        raise RuntimeError("pipeline step needs a mesh with a 'pp' axis")
    if "mp" in mesh.shape and int(mesh.shape["mp"]) > 1:
        raise NotImplementedError("pp x mp hybrid pipeline lands via GSPMD "
                                  "sharding specs; use ParallelTrainer for mp")
    num_stages = num_stages or int(mesh.shape[PP_AXIS])
    pipe = GPTPipelineModule(model, num_stages, microbatches)
    has_dp = DP_AXIS in mesh.shape and int(mesh.shape[DP_AXIS]) > 1

    params = {
        "stages": jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P(PP_AXIS))),
            pipe.stage_params),
        "shared": jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P())),
            pipe.shared_params),
    }
    opt_state = optimizer.init_state(params)
    opt_state = {
        "slots": {
            "stages": jax.tree_util.tree_map(
                lambda a: jax.device_put(a, NamedSharding(mesh, P(PP_AXIS)))
                if a.ndim >= 1 and a.shape[0] == num_stages else
                jax.device_put(a, NamedSharding(mesh, P())),
                opt_state["slots"]["stages"]),
            "shared": jax.tree_util.tree_map(
                lambda a: jax.device_put(a, NamedSharding(mesh, P())),
                opt_state["slots"]["shared"]),
        },
        "step": jax.device_put(opt_state["step"], NamedSharding(mesh, P())),
    }

    def spmd_step(params, opt_state, x, y):
        def loss_fn(params):
            return pipe.local_loss(params["stages"], params["shared"], x, y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # shared (tied/replicated) params were used by several stages:
        # combine their grads over 'pp' (≙ SharedLayerDesc allreduce)
        grads["shared"] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, PP_AXIS), grads["shared"])
        if has_dp:
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, DP_AXIS), grads)
            loss = lax.pmean(loss, DP_AXIS)
        new_params, new_opt = optimizer.apply_gradients(params, grads, opt_state)
        return new_params, new_opt, loss

    param_prefix = {"stages": P(PP_AXIS), "shared": P()}
    opt_prefix = {"slots": {"stages": P(PP_AXIS), "shared": P()}, "step": P()}
    data_spec = P(DP_AXIS) if has_dp else P()

    from jax import shard_map

    mapped = shard_map(
        spmd_step, mesh=mesh,
        in_specs=(param_prefix, opt_prefix, data_spec, data_spec),
        out_specs=(param_prefix, opt_prefix, P()),
        check_vma=False,
    )
    jitted = jax.jit(mapped, donate_argnums=(0, 1))

    state = {"params": params, "opt": opt_state}

    def step(x, y):
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        y = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        state["params"], state["opt"], loss = jitted(state["params"], state["opt"], x, y)
        return loss

    step.pipe = pipe
    step.state = state
    step.sync_to_model = lambda: pipe.sync_to_model(
        state["params"]["stages"], state["params"]["shared"])
    return step
