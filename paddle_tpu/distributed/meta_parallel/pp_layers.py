"""Pipeline layer description + segmentation.

Parity: /root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py — LayerDesc, SharedLayerDesc (:49, tied
embeddings), PipelineLayer (:132) with ``_segment_network`` (:282) by
'uniform' or 'layer:<Class>' seg_method.

TPU-native: segmentation metadata is kept for ALL stages (single-controller
SPMD owns every stage's params); stage assignment becomes a mapping
layer-index → 'pp' mesh coordinate used by the pipeline schedule, instead of
each process building only its local sublayers.
"""
from __future__ import annotations

import math
import re
from typing import Callable, List, Optional

from ...nn.layer import Layer, LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages: Optional[int] = None, topology=None,
                 loss_fn=None, seg_method: str = "uniform", recompute_interval: int = 0,
                 recompute_ctx=None, num_virtual_pipeline_stages: int = 1):
        super().__init__()
        self._loss_fn = loss_fn
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval
        self._num_virtual_pipeline_stages = max(int(num_virtual_pipeline_stages or 1), 1)
        self._topo = topology
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or 1

        self._layer_descs: List = list(layers)
        self._shared: dict = {}
        built: List[Layer] = []
        for i, d in enumerate(self._layer_descs):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    base = self._shared[d.layer_name]
                    inst = d.build_layer()
                    # tie the shared weight to the first instance's tensor
                    setattr(inst, d.shared_weight_attr, getattr(base, d.shared_weight_attr))
                    inst._shared_forward = d.forward_func
                    built.append(inst)
                else:
                    inst = d.build_layer()
                    inst._shared_forward = d.forward_func
                    self._shared[d.layer_name] = inst
                    built.append(inst)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FuncLayer(d))
            else:
                raise TypeError(f"bad pipeline entry {d!r}")
        self.run_function = LayerList(built)
        self.segment_parts = self._segment_network(len(built), self._num_stages, seg_method)

    # ------------------------------------------------------------------
    def _segment_network(self, n_layers: int, n_stages: int, seg_method: str) -> List[int]:
        """Return stage boundary indices, len == n_stages+1 (parity:
        _segment_network:282 — 'uniform' or 'layer:Class' balancing)."""
        if seg_method.startswith("layer:"):
            cls_name = seg_method.split(":", 1)[1]
            marks = [
                i
                for i, l in enumerate(self.run_function)
                if type(l).__name__ == cls_name
            ]
            if len(marks) < n_stages:
                raise ValueError(f"only {len(marks)} {cls_name} layers for {n_stages} stages")
            per = len(marks) / n_stages
            bounds = [0]
            for s in range(1, n_stages):
                bounds.append(marks[math.floor(s * per)])
            bounds.append(n_layers)
            return bounds
        per = n_layers / n_stages
        return [math.floor(i * per) for i in range(n_stages)] + [n_layers]

    def get_stage_layers(self, stage_id: int) -> List[Layer]:
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x):
        """Full-model forward (all stages in order) — correct semantics on a
        single program; the pipeline schedule partitions this by stage."""
        for i, l in enumerate(self.run_function):
            fwd = getattr(l, "_shared_forward", None)
            if fwd is not None and not _is_first_shared(self, l):
                x = fwd(l, x)
            else:
                x = l(x) if not isinstance(x, tuple) else l(*x)
        return x


def _is_first_shared(pipe: PipelineLayer, layer: Layer) -> bool:
    return any(v is layer for v in pipe._shared.values())


class _FuncLayer(Layer):
    def __init__(self, fn: Callable):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)
