"""HybridParallelOptimizer + dygraph ZeRO-1 sharding optimizer.

Parity:
- HybridParallelOptimizer (/root/reference/python/paddle/distributed/fleet/
  meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:173) — wraps
  the user optimizer, turns plain global-norm clip into a hybrid-aware clip,
  syncs dp gradients before step.
- DygraphShardingOptimizer (dygraph_optimizer/dygraph_sharding_optimizer.py:27)
  — ZeRO-1: greedy-by-size parameter partition (:90) + broadcast of updated
  params (:136-147).

TPU-native: in single-controller SPMD the mesh is one program — global norm
IS global, and dp gradient sync happens inside the compiled step, so the
eager wrapper's job is mostly bookkeeping; ZeRO state sharding is expressed
as optimizer-state PartitionSpecs consumed by parallel_trainer (the jitted
path), while the eager path keeps paddle's API shape.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...optimizer.optimizer import Optimizer
from ..spmd import P

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer"]


class HybridParallelOptimizer:
    def __init__(self, optimizer: Optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._sharding = hcg.get_sharding_parallel_world_size() > 1 if hcg else False

    @property
    def _learning_rate(self):
        return self._inner_opt._learning_rate

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def clear_grad(self):
        return self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def step(self):
        # dp gradient sync for the eager path (jitted steps sync in-program)
        model = getattr(self, "_model", None)
        if model is not None and hasattr(model, "apply_collective_grads"):
            model.apply_collective_grads()
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        if hasattr(self._inner_opt, "record_loss"):
            self._inner_opt.record_loss(loss)  # adaptive-localsgd k feedback
        loss.backward()
        self.step()
        return None, []

    def __getattr__(self, name):
        # delegate the remaining optimizer surface (get/set lr handled above)
        return getattr(self.__dict__["_inner_opt"], name)

    # functional surface for the jitted trainer
    def init_state(self, params_tree):
        return self._inner_opt.init_state(params_tree)

    def apply_gradients(self, params, grads, state, lr=None):
        return self._inner_opt.apply_gradients(params, grads, state, lr)

    def state_partition_specs(self, params_specs, axis: str = "sharding"):
        """ZeRO-1: shard every optimizer slot over ``axis`` along each
        param's largest divisible dim (parallel_trainer consumes this)."""
        from ..env import get_mesh

        mesh = get_mesh()
        n = int(mesh.shape.get(axis, 1)) if mesh is not None else 1

        def slot_spec(param_spec_and_shape):
            spec, shape = param_spec_and_shape
            if n <= 1:
                return spec
            # prefer sharding dim 0 if divisible and unsharded
            dims = list(spec) + [None] * (len(shape) - len(spec))
            for d, s in enumerate(shape):
                if dims[d] is None and s % n == 0:
                    dims[d] = axis
                    break
            return P(*dims)

        return {k: slot_spec(v) for k, v in params_specs.items()}


class DygraphShardingOptimizer:
    """Eager ZeRO-1 (parity: dygraph_sharding_optimizer.py). Greedy-by-size
    partition of parameters across the sharding group; each rank steps only
    its shard, then updated params broadcast. In single-controller SPMD the
    'broadcast' is implicit — kept for API parity and for the partition map
    it produces (used to place optimizer state)."""

    def __init__(self, hcg, user_defined_strategy, params, inner_optimizer_class, **inner_kw):
        self._hcg = hcg
        self._params: List = list(params)
        self.n_shards = max(1, hcg.get_sharding_parallel_world_size())
        self._rank2params = self._partition_parameters()
        self._inner_opt = inner_optimizer_class(parameters=self._params, **inner_kw)

    def _partition_parameters(self):
        """Greedy: biggest param to the least-loaded shard (:90)."""
        sizes = [0.0] * self.n_shards
        mapping = {i: [] for i in range(self.n_shards)}
        for p in sorted(self._params, key=lambda p: -p.size):
            dst = int(np.argmin(sizes))
            mapping[dst].append(p)
            sizes[dst] += p.size
        return mapping

    def shard_of(self, param) -> int:
        for r, ps in self._rank2params.items():
            if any(q is param for q in ps):
                return r
        return -1

    def step(self):
        self._inner_opt.step()

    def clear_grad(self):
        self._inner_opt.clear_grad()

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)
