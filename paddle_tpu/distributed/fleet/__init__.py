"""paddle_tpu.distributed.fleet — parity with paddle.distributed.fleet."""
from . import elastic  # noqa: F401
from .. import meta_parallel  # noqa: F401
from ..topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode  # noqa: F401
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import (  # noqa: F401
    Fleet,
    PaddleCloudRoleMaker,
    UserDefinedRoleMaker,
    UtilBase,
    fleet,
)

# module-level function surface (parity: fleet/__init__.py delegates to the
# singleton)
init = fleet.init
is_initialized = fleet.is_initialized
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
worker_num = fleet.worker_num
worker_index = fleet.worker_index
is_worker = fleet.is_worker
is_server = fleet.is_server
is_first_worker = fleet.is_first_worker
worker_endpoints = fleet.worker_endpoints
barrier_worker = fleet.barrier_worker
minimize = fleet.minimize
server_num = fleet.server_num
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
save_persistables = fleet.save_persistables
save_inference_model = fleet.save_inference_model
util = fleet.util
