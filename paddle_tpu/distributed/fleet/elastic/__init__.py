from .collective import (  # noqa: F401
    ElasticCollective,
    RankFailure,
    pack_arrays,
    unpack_arrays,
)
from .manager import (  # noqa: F401
    ELASTIC_EXIT_CODE,
    ElasticManager,
    ElasticStatus,
    StoreUnavailable,
    enable_elastic,
    launch_elastic,
)
