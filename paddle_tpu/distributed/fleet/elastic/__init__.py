from .manager import (  # noqa: F401
    ELASTIC_EXIT_CODE,
    ElasticManager,
    ElasticStatus,
    enable_elastic,
    launch_elastic,
)
