import sys

from .manager import main

sys.exit(main())
