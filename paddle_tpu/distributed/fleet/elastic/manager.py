"""Elastic training manager: node registry, membership watch, auto-relaunch.

Parity: the reference's etcd-based ``ElasticManager``
(/root/reference/python/paddle/distributed/fleet/elastic/manager.py:103 —
registers the host under PADDLE_ELASTIC_* env :107-126, watches the node set
(host_call_back:176), rewrites DISTRIBUTED_TRAINER_ENDPOINTS on change and
relaunches training; elastic/__init__.py:41-60 restart loop where child exit
code 101 requests a relaunch; fault-tolerance levels :118).

TPU-native redesign: etcd is replaced by a shared-filesystem KV store
(heartbeat files under PADDLE_ELASTIC_STORE_PATH — TPU pods mount shared NFS/
GCS-fuse; single host works out of the box) and by SIGTERM-based preemption
hooks (TPU preemption notice), wired to auto-checkpoint for resume. The
restart protocol (exit code 101, endpoint env rewrite) is kept verbatim so
reference launch scripts port unchanged.

Self-healing (the resilience layer): every store operation retries with
exponential backoff + jitter (one transient ConnectionError must never kill
the heartbeat thread — the etcd client's retry policy, re-homed in
resilience/retry.py); the beat thread contains ALL exceptions, and when the
store stays unreachable past its TTL the manager degrades to single-node
operation (training continues, membership watch answers "no change") and
rejoins automatically on the first successful beat after the store returns.
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
import warnings
from typing import Callable, List, Optional

__all__ = ["ElasticManager", "ElasticStatus", "StoreUnavailable",
           "enable_elastic", "launch_elastic", "ELASTIC_EXIT_CODE"]

ELASTIC_EXIT_CODE = 101  # child exit code meaning "please relaunch me"


class StoreUnavailable(ConnectionError):
    """The elastic registry could not be reached even after retries."""


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


def enable_elastic(args=None) -> bool:
    """Elastic is on when PADDLE_ELASTIC_NP is set (parity:
    elastic/__init__.py:26 enable_elastic checks elastic env)."""
    return bool(os.environ.get("PADDLE_ELASTIC_NP"))


class _FileStore:
    """Minimal KV/heartbeat store on a shared filesystem (etcd stand-in)."""

    def __init__(self, path: str, ttl: float = 10.0):
        self.path = path
        self.ttl = ttl
        os.makedirs(path, exist_ok=True)

    def register(self, node_id: str, value: str):
        with open(os.path.join(self.path, node_id), "w") as f:
            f.write(value)

    def heartbeat(self, node_id: str):
        os.utime(os.path.join(self.path, node_id), None)

    def deregister(self, node_id: str):
        try:
            os.remove(os.path.join(self.path, node_id))
        except FileNotFoundError:
            pass

    def nodes(self) -> List[str]:
        # file mtimes are inherently wall-clock; cross-host shared-FS TTLs
        # can't use a monotonic base. The in-process TTL bookkeeping
        # (ElasticManager._beat) and the TCP store's server-side stamps ARE
        # monotonic — this path is the single-host/shared-NFS fallback.
        now = time.time()
        alive = []
        for name in os.listdir(self.path):
            p = os.path.join(self.path, name)
            try:
                if now - os.path.getmtime(p) <= self.ttl:
                    alive.append(name)
            except FileNotFoundError:
                pass
        return sorted(alive)

    def endpoints(self) -> List[str]:
        eps = []
        for name in self.nodes():
            # same guard nodes() has: a node expiring between the scan and
            # the open (deregister raced the TTL walk) is skipped, not a
            # crash in the caller's membership poll
            try:
                with open(os.path.join(self.path, name)) as f:
                    eps.append(f.read().strip())
            except FileNotFoundError:
                pass
        return eps


class _TcpStore:
    """KV/heartbeat registry over the HTTP KV server — the cross-host etcd
    equivalent (reference manager.py:103 etcd registry). Same interface as
    :class:`_FileStore`, liveness by server-side write timestamps.

    Every operation retries with exponential backoff + jitter (per-attempt
    timeouts budgeted so a full retry burst stays well under the TTL) and
    raises
    :class:`StoreUnavailable` only after the budget is exhausted — a single
    transient ConnectionError never surfaces to the beat thread."""

    def __init__(self, addr: str, scope: str, ttl: float = 10.0,
                 retries: int = 3):
        from ..utils.http_server import KVClient
        from ..utils.replicated_store import ReplicatedKVClient

        # budget the WHOLE burst (attempts x timeout + backoff sleeps) well
        # under the TTL: a timeout-bound stall (black-holed store, not
        # connection-refused) must not silence the heartbeat long enough
        # for peers to expire this node — that restart is exactly what the
        # retry layer exists to prevent. With a replica SET the budget is
        # per PASS (one attempt visits up to every replica sequentially),
        # so the per-hop timeout divides by the fan-out too.
        n_addr = addr.count(",") + 1
        timeout = max(ttl / 4 / (int(retries) + 1) / n_addr, 0.25)
        if "," in addr:
            # multi-address spec = the quorum-replicated store (r16):
            # leader discovery, NotLeader redirects and failover live in
            # the client; THIS layer's retry/backoff/StoreUnavailable
            # policy is identical either way. Single-address behavior is
            # unchanged (the bit-comparison fallback).
            self.client = ReplicatedKVClient(addr.split(","),
                                             timeout=timeout)
        else:
            self.client = KVClient(addr, timeout=timeout)
        self.scope = f"elastic_{scope}"
        # SIBLING scope for the raw KV plane: membership liveness is
        # "every key in self.scope with a fresh stamp is a node", so data
        # keys (rendezvous views, gradient blobs) must live next door or
        # they'd register as phantom nodes
        self.kv_scope = f"elastic_{scope}_kv"
        self.ttl = ttl
        self.retries = int(retries)
        self._values = {}

    def _retrying(self, name: str, fn, ok=lambda r: True):
        from ....resilience.inject import InjectedFault, fire as _inject_fire
        from ....resilience.retry import RetryError, call_with_retries

        # per-ATTEMPT injection seam (`elastic.store.rpc.<op>`): a raised
        # fault here engages the real backoff/retry path — an `every=1`
        # persistent fault burns retries exactly like a dead store, which
        # is what the shared RetryBudget exists to cap. The public-method
        # seams (`elastic.store.<op>`) stay message-level (drop/duplicate)
        def attempt():
            _inject_fire(f"elastic.store.rpc.{name}", store=self.scope)
            return fn()

        try:
            return call_with_retries(
                attempt, retries=self.retries, base=0.05,
                max_delay=max(min(self.ttl / 8, 1.0), 0.05),
                # ValueError: a scan response truncated mid-flight parses as
                # malformed JSON — transient, same treatment as a dead
                # socket. InjectedFault: faults at this seam model
                # transport failures WHATEVER class the schedule raises,
                # so they retry and surface as StoreUnavailable like the
                # real thing (the message-level seam is the bypass)
                retry_on=(OSError, ValueError, InjectedFault), ok=ok)
        except RetryError as e:
            raise StoreUnavailable(
                f"elastic store {self.client.addr} unreachable "
                f"({name}, {self.retries + 1} attempts)") from e

    @staticmethod
    def _message_op(point: str, call, *, absent=None, **labels):
        """MESSAGE-level injection seam shared by every public store op:
        raise/delay/timeout are handled inside fire(); a ``drop`` fault
        loses the whole logical RPC (returns ``absent`` without calling),
        a ``duplicate`` fault performs it twice. No schedule armed ⇒ one
        None check."""
        from ....resilience.inject import fire

        f = fire(point, **labels)
        if f is not None and f.kind == "drop":
            return absent
        out = call()
        if f is not None and f.kind == "duplicate":
            out = call()
        return out

    def register(self, node_id: str, value: str):
        self._values[node_id] = value
        self._message_op(
            "elastic.store.register",
            lambda: self._retrying(
                "register",
                lambda: self.client.put(self.scope, node_id, value,
                                        strict=True), ok=bool),
            node=node_id)

    def heartbeat(self, node_id: str):
        val = self._values.get(node_id, "")
        self._message_op(
            "elastic.store.heartbeat",
            lambda: self._retrying(
                "heartbeat",
                lambda: self.client.put(self.scope, node_id, val,
                                        strict=True), ok=bool),
            node=node_id)

    def deregister(self, node_id: str):
        # a dropped deregister just means the node expires by TTL
        self._message_op(
            "elastic.store.deregister",
            lambda: self._retrying(
                "deregister",
                lambda: self.client.delete(self.scope, node_id,
                                           strict=True), ok=bool),
            node=node_id)

    def _alive(self):
        """One snapshot: {node_id: endpoint} for live nodes (a second scan
        could race a concurrent registration)."""
        snap = self._retrying(
            "scan", lambda: self.client.scan(self.scope, strict=True))
        return {k: v for k, (v, age) in snap.items() if age <= self.ttl}

    def nodes(self) -> List[str]:
        return sorted(self._alive())

    def endpoints(self) -> List[str]:
        live = self._alive()
        return [live[k] for k in sorted(live)]

    # -- raw KV plane (retrying) ---------------------------------------
    # The elastic coordinator rides the SAME store for its data plane
    # (rendezvous views, gradient blobs) under a sibling scope; these
    # accessors get the identical backoff/StoreUnavailable policy as the
    # membership operations above.
    def put(self, key: str, value: str):
        self._message_op(
            "elastic.store.kv.put",
            lambda: self._retrying(
                "put", lambda: self.client.put(self.kv_scope, key, value,
                                               strict=True), ok=bool),
            key=key)

    def get(self, key: str) -> Optional[str]:
        # absence is a legitimate answer (None), not a transport failure;
        # a dropped response reads as absence too
        return self._message_op(
            "elastic.store.kv.get",
            lambda: self._retrying(
                "get", lambda: self.client.get(self.kv_scope, key,
                                               strict=True)),
            key=key)

    def delete(self, key: str):
        self._message_op(
            "elastic.store.kv.delete",
            lambda: self._retrying(
                "delete", lambda: self.client.delete(self.kv_scope, key,
                                                     strict=True), ok=bool),
            key=key)

    def scan(self, keys_only: bool = False, prefix: str = None):
        """{key: (value, age_seconds)} snapshot of the KV plane.
        ``keys_only`` ships (None, age) pairs — presence without payload;
        ``prefix`` filters server-side (both: see KVClient.scan). A
        dropped response reads as an empty plane."""
        return self._message_op(
            "elastic.store.kv.scan",
            lambda: self._retrying(
                "scan_kv", lambda: self.client.scan(
                    self.kv_scope, strict=True, keys_only=keys_only,
                    prefix=prefix)),
            absent={}, prefix=prefix)


class ElasticManager:
    """Registers this node, watches membership, decides restart/exit.

    Env protocol (parity: manager.py:107-126):
      PADDLE_ELASTIC_NP            target node count (elastic on when set)
      PADDLE_ELASTIC_JOB_ID        job key
      PADDLE_ELASTIC_TIMEOUT       seconds to hold for stragglers (default 120)
      PADDLE_ELASTIC_SERVER        host:port of the HTTP KV store (the etcd
                                   stand-in; cross-host), or a comma list
                                   of replica addresses for the quorum-
                                   replicated store (r16)
      PADDLE_ELASTIC_STORE_PATH    shared dir fallback registry (single host
                                   / shared FS)
      PADDLE_CURRENT_ENDPOINT      this node's endpoint
    """

    def __init__(self, args=None, store=None):
        self.np = int(os.environ.get("PADDLE_ELASTIC_NP", "0") or 0)
        self.job_id = os.environ.get("PADDLE_ELASTIC_JOB_ID", "default_job")
        self.timeout = int(os.environ.get("PADDLE_ELASTIC_TIMEOUT", "120"))
        self.endpoint = os.environ.get(
            "PADDLE_CURRENT_ENDPOINT", f"{socket.gethostname()}:0"
        )
        store_path = os.environ.get(
            "PADDLE_ELASTIC_STORE_PATH",
            os.path.join("/tmp", f"paddle_elastic_{self.job_id}"),
        )
        self.enable = self.np > 0
        server = os.environ.get("PADDLE_ELASTIC_SERVER")
        if store is not None:
            self.store = store
        elif server:
            self.store = _TcpStore(server, self.job_id)
        else:
            self.store = _FileStore(store_path)
        self.node_id = self.endpoint.replace(":", "_").replace("/", "_")
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._membership_at_launch: List[str] = []
        self._last_endpoints: List[str] = [self.endpoint]
        # degraded/_last_beat_ok are written by the heartbeat thread and
        # read by the trainer thread (changed()/endpoints_env()): the
        # degrade decision compares a stamp against the TTL, and a torn
        # read there flips a node into (or out of) single-node mode on
        # stale evidence
        self._state_lock = threading.Lock()
        self._last_beat_ok = time.monotonic()  # guarded-by: self._state_lock
        # store unreachable past TTL: single-node mode
        self.degraded = False  # guarded-by: self._state_lock
        # set from the SIGTERM path only (main thread): never guarded —
        # a signal handler taking a lock the interrupted frame holds
        # would self-deadlock
        self.preempted = False

    # -- registry -------------------------------------------------------
    def register(self):
        try:
            self.store.register(self.node_id, self.endpoint)
            self._membership_at_launch = self.store.nodes()
            self._last_endpoints = self.store.endpoints()
            with self._state_lock:
                self._last_beat_ok = time.monotonic()
                self.degraded = False
        except StoreUnavailable as e:
            # graceful start: training proceeds single-node; the beat thread
            # keeps probing and rejoins when the registry returns
            warnings.warn(
                f"elastic store unreachable at registration ({e}); "
                "continuing single-node, will rejoin when it returns",
                RuntimeWarning)
            with self._state_lock:
                self.degraded = True
            self._membership_at_launch = [self.node_id]
        if self._hb_thread is None:
            self._hb_thread = threading.Thread(target=self._beat, daemon=True)
            self._hb_thread.start()

    def _beat(self):
        """Heartbeat loop with full exception containment: a store outage
        flips ``degraded`` once the silence exceeds the TTL (the other nodes
        have expired us by then anyway) and the FIRST successful write after
        recovery re-registers this node (rejoin). The thread itself never
        dies of a store error."""
        while not self._stop.wait(min(2.0, self.store.ttl / 3)):
            try:
                with self._state_lock:
                    was_degraded = self.degraded
                # check-then-act is safe here: the re-register RPC must
                # not run under the lock, and the acted-on transition
                # (degraded -> False after a successful register) is
                # idempotent against a concurrent register()
                # hostrace: ok(host-toctou)
                if was_degraded:
                    self.store.register(self.node_id, self.endpoint)
                    with self._state_lock:
                        self.degraded = False
                    warnings.warn(
                        "elastic store reachable again; node re-registered",
                        RuntimeWarning)
                else:
                    self.store.heartbeat(self.node_id)
                with self._state_lock:
                    self._last_beat_ok = time.monotonic()
            except FileNotFoundError:
                try:
                    self.store.register(self.node_id, self.endpoint)
                    with self._state_lock:
                        self._last_beat_ok = time.monotonic()
                except Exception:
                    pass
            except Exception:
                # stamp-vs-TTL comparison and the degrade flip must be one
                # atomic decision against a consistent stamp
                with self._state_lock:
                    degrade = (not self.degraded
                               and time.monotonic() - self._last_beat_ok
                               > self.store.ttl)
                    if degrade:
                        self.degraded = True
                if degrade:
                    warnings.warn(
                        f"elastic store unreachable for over ttl="
                        f"{self.store.ttl}s; degrading to single-node "
                        "operation (training continues)", RuntimeWarning)

    def halt_heartbeat(self):
        """Stop beating WITHOUT deregistering — the deterministic stand-in
        for a SIGKILLed process: peers see this node's stamps go stale and
        expire it by TTL, exactly the liveness path a real abrupt death
        exercises (``exit()`` is the graceful path; this is the chaos
        plane's)."""
        self._stop.set()

    def exit(self):
        self._stop.set()
        try:
            self.store.deregister(self.node_id)
        except (StoreUnavailable, OSError) as e:
            warnings.warn(f"elastic deregister failed ({e}); node will "
                          "expire by TTL", RuntimeWarning)

    # -- membership -----------------------------------------------------
    def changed(self) -> bool:
        """Membership differs from launch. While the STORE is down this
        answers False — a registry outage must not restart training (the
        degraded node keeps working; it rejoins when the store returns)."""
        with self._state_lock:
            if self.degraded:
                return False
        try:
            return self.store.nodes() != self._membership_at_launch
        except (StoreUnavailable, OSError):
            return False

    def refresh_membership(self):
        """Re-snapshot the launch membership (after a relaunch); keeps the
        old snapshot when the store is unreachable."""
        try:
            self._membership_at_launch = self.store.nodes()
        except (StoreUnavailable, OSError):
            pass

    def endpoints_env(self) -> str:
        """Current live endpoints; falls back to the last successful
        snapshot (at minimum this node) when the store is unreachable."""
        try:
            eps = self.store.endpoints()
            if eps:
                self._last_endpoints = eps
            return ",".join(eps)
        except (StoreUnavailable, OSError):
            return ",".join(self._last_endpoints)

    def wait_for_np(self, np: Optional[int] = None) -> bool:
        """Hold until the registry has the target node count (parity:
        manager.py wait/HOLD state). Returns False on timeout.

        The poll backs off with jitter (resilience/retry.py) instead of a
        fixed 0.5s cadence: a whole pod waking up polls the registry in
        lockstep otherwise, and the stampede is worst exactly when the
        store is busiest (everyone rendezvousing after a restart)."""
        from ....resilience.retry import backoff_delays

        want = np or self.np

        def count():
            try:
                return len(self.store.nodes())
            except (StoreUnavailable, OSError):
                return 0

        delays = backoff_delays(1 << 30, base=0.1, max_delay=2.0)
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            if count() >= want:
                return True
            time.sleep(min(next(delays),
                           max(deadline - time.monotonic(), 0.0)))
        return count() >= want

    # -- preemption -----------------------------------------------------
    def install_preemption_handler(self, on_preempt: Optional[Callable] = None):
        """SIGTERM = preemption notice: snapshot then request relaunch
        (TPU-native stand-in for the reference's fault-tolerance levels)."""

        def handler(signum, frame):
            self.preempted = True
            if on_preempt is not None:
                on_preempt()
            raise SystemExit(ELASTIC_EXIT_CODE)

        signal.signal(signal.SIGTERM, handler)


def launch_elastic(cmd: List[str], max_restarts: int = 3,
                   manager: Optional[ElasticManager] = None,
                   poll_interval: float = 1.0) -> int:
    """Restart loop (parity: elastic/__init__.py:41-60).

    Runs ``cmd`` as a child; relaunches it when it exits with
    ELASTIC_EXIT_CODE or when cluster membership changes, refreshing
    DISTRIBUTED_TRAINER_ENDPOINTS each launch. Returns the final exit code.
    """
    mgr = manager or ElasticManager()
    mgr.register()
    restarts = 0
    try:
        while True:
            env = dict(os.environ)
            env["DISTRIBUTED_TRAINER_ENDPOINTS"] = mgr.endpoints_env()
            env["PADDLE_ELASTIC_RESTART_NUM"] = str(restarts)
            proc = subprocess.Popen(cmd, env=env)
            code = None
            while code is None:
                try:
                    code = proc.wait(timeout=poll_interval)
                except subprocess.TimeoutExpired:
                    if mgr.enable and mgr.changed():
                        proc.terminate()
                        try:
                            proc.wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            proc.kill()
                        code = ELASTIC_EXIT_CODE
            if code == 0:
                return 0
            # ELASTIC_EXIT_CODE always relaunches; under elastic mode ANY
            # abnormal exit does too (fault-tolerance level 1: a preempted/
            # killed worker re-registers and rejoins — reference
            # manager.py fault tolerance + watch_local_trainers restart)
            relaunchable = code == ELASTIC_EXIT_CODE or (mgr.enable and code != 0)
            if relaunchable and restarts < max_restarts:
                restarts += 1
                mgr.register()  # re-register after a kill/preemption
                mgr.refresh_membership()
                continue
            return code
    finally:
        mgr.exit()


def main():  # pragma: no cover
    """CLI: python -m paddle_tpu.distributed.fleet.elastic -- <training cmd>"""
    argv = sys.argv[1:]
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        print("usage: python -m paddle_tpu.distributed.fleet.elastic -- cmd ...",
              file=sys.stderr)
        return 2
    return launch_elastic(argv)
