"""Elastic rank collective: rendezvous + blob exchange over the KV store.

Parity: the reference's multi-node data plane is gloo/NCCL bootstrapped from
an etcd/HTTP rendezvous (fleet elastic manager + Gloo HTTP store). This
module is the TPU-pod stand-in for the *control + small-tensor* plane: rank
processes agree on (generation, rank, world) through the same elastic
:class:`~.manager._TcpStore` that tracks their heartbeats, and exchange
small payloads (gradient blobs for CPU-multiprocess data parallelism,
gathered checkpoint shards) through its sibling KV scope.

Failure model — the part the single-process r7 stack could not cover:

* Liveness is the membership scope's heartbeat TTL (server-side monotonic
  stamps). A rank that stops beating *expires*; it is never declared dead by
  a timeout alone.
* :meth:`ElasticCollective.allgather` polls for every member's payload. A
  missing payload whose owner is still alive means "slow" (keep waiting); a
  missing payload whose owner has expired raises :class:`RankFailure`
  naming the dead ranks — the trainer's signal to re-rendezvous on the
  surviving world and reshard its newest intact checkpoint.
* Rendezvous is generation-numbered and two-phase: ranks join
  ``rdv<gen>``, then every member publishes its membership VIEW and waits
  until all views agree — two survivors can never commit to different rank
  orders after a death race.

Payloads ride as base64 npz blobs (:func:`pack_arrays`/:func:`unpack_arrays`)
— plain strings through the HTTP KV protocol, no pickling.
"""
from __future__ import annotations

import base64
import io
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ElasticCollective", "RankFailure", "pack_arrays",
           "unpack_arrays"]


class RankFailure(RuntimeError):
    """One or more member ranks stopped heartbeating mid-collective."""

    def __init__(self, msg: str, dead: List[str]):
        super().__init__(msg)
        self.dead = list(dead)


def pack_arrays(tree: Dict[str, np.ndarray]) -> str:
    """{name: array} → base64 npz string (KV-store safe, no pickle)."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in tree.items()})
    return base64.b64encode(buf.getvalue()).decode("ascii")


def unpack_arrays(blob: str) -> Dict[str, np.ndarray]:
    data = np.load(io.BytesIO(base64.b64decode(blob.encode("ascii"))),
                   allow_pickle=False)
    return {k: data[k] for k in data.files}


class ElasticCollective:
    """Rank coordination over an elastic ``_TcpStore``-style registry.

    ``store`` needs the membership plane (``nodes()``, ``ttl``) and the raw
    KV plane (``put/get/delete/scan``) — :class:`~.manager._TcpStore`
    provides both (the shared-FS ``_FileStore`` does not; cross-process
    collectives need the HTTP store). Heartbeats are NOT this class's job:
    run an :class:`~.manager.ElasticManager` (or your own beat loop) so the
    membership scope stays fresh.
    """

    def __init__(self, store, node_id: str, poll: float = 0.02):
        self.store = store
        self.node_id = node_id
        self.poll = float(poll)
        self.generation = -1
        self.rank: Optional[int] = None
        self.world = 0
        self.members: List[str] = []
        self._last_ag_key: Optional[str] = None

    # -- helpers --------------------------------------------------------
    def _sleep_iter(self):
        from ....resilience.retry import backoff_delays

        return backoff_delays(1 << 30, base=self.poll, max_delay=0.25)

    def _kv_scan(self, keys_only: bool = False,
                 prefix: Optional[str] = None) -> Dict[str, tuple]:
        """Store scan with the r11 server-side filters; falls back to a
        full scan + client-side filtering for duck-typed stores that
        predate the ``keys_only``/``prefix`` options."""
        try:
            return self.store.scan(keys_only=keys_only, prefix=prefix)
        except TypeError:
            out = self.store.scan()
            if prefix:
                out = {k: v for k, v in out.items() if k.startswith(prefix)}
            return out

    def _scan_prefix(self, prefix: str, fresh: bool = False,
                     keys_only: bool = False) -> Dict[str, str]:
        """KV-plane snapshot filtered to ``prefix`` (key suffix → value).
        ``fresh`` additionally age-filters by the membership TTL — used for
        JOIN stamps, which double as liveness; data blobs are returned at
        any age (a gradient from 30s ago is still the gradient).
        ``keys_only`` skips payload transfer (suffix → None) — the poll
        loops need presence, not W gradient blobs per iteration."""
        out = {}
        scan = self._kv_scan(keys_only=keys_only, prefix=prefix)
        for k, (v, age) in scan.items():
            if k.startswith(prefix) and (not fresh or age <= self.store.ttl):
                out[k[len(prefix):]] = v
        return out

    def _parse_rdv(self, scan):
        """{gen: ({fresh join owners}, {view owner: view})} from a raw KV
        snapshot. Join stamps are liveness-filtered (a waiting rank keeps
        refreshing them); views are kept at ANY age — a published view is
        commit evidence, and a committed rank stops refreshing its stamps
        the moment it returns to training."""
        ttl = self.store.ttl
        gens: Dict[int, tuple] = {}
        for k, (v, age) in scan.items():
            for prefix, views in (("rdvview", True), ("rdv", False)):
                if not k.startswith(prefix):
                    continue
                head, _, owner = k[len(prefix):].partition(":")
                if not head.isdigit() or not owner:
                    continue
                joins, view_map = gens.setdefault(int(head), (set(), {}))
                if views:
                    view_map[owner] = v
                elif age <= ttl:
                    joins.add(owner)
                break
        return gens

    def latest_generation(self) -> int:
        """Highest generation any rank has ever tried to join (−1 when the
        store is virgin) — a (re)joining process adopts max+1 so it can
        meet the incumbents at their next re-rendezvous instead of waiting
        at a generation everyone else has left behind."""
        gens = self._parse_rdv(self._kv_scan(prefix="rdv"))
        return max(gens) if gens else -1

    # -- rendezvous -----------------------------------------------------
    def rendezvous(self, gen: int, min_ranks: int = 1,
                   timeout: float = 60.0) -> int:
        """Join generation ``gen`` (or any HIGHER generation a peer
        proposes while we wait — racing proposers must converge on one
        number, not deadlock one generation apart); block until the live
        membership has all joined and every member confirms the SAME view.
        Returns this node's rank; sets
        ``rank``/``world``/``members``/``generation``.

        Convergence after a death: the dead node sits in the membership
        scope until its TTL expires and never joins ``gen``, so the loop
        holds exactly one TTL and then commits on the survivors. A member
        that already committed is recognized by its published view (any
        age), so a slow joiner still converges after the fast ones have
        gone back to training.
        """
        deadline = time.monotonic() + timeout
        delays = self._sleep_iter()
        alive, joined = set(), set()
        while time.monotonic() < deadline:
            try:
                # prefix scan: membership views only — never the data-plane
                # gradient blobs sharing the scope
                gens = self._parse_rdv(self._kv_scan(prefix="rdv"))
                live_gens = [g for g, (j, vw) in gens.items() if j or vw]
                if live_gens and max(live_gens) > gen:
                    gen = max(live_gens)  # adopt the highest live proposal
                # (re)stamp our join: join keys are liveness-filtered, so
                # they must be refreshed while we wait
                self.store.put(f"rdv{gen}:{self.node_id}", "1")
                alive = set(self.store.nodes())
                joins, view_map = gens.get(gen, (set(), {}))
                joined = joins | set(view_map) | {self.node_id}
                cand = sorted(alive & joined)
                if (self.node_id in cand and len(cand) >= min_ranks
                        and alive <= joined):
                    view = ",".join(cand)
                    self.store.put(f"rdvview{gen}:{self.node_id}", view)
                    view_map = dict(view_map, **{self.node_id: view})
                    if all(view_map.get(m) == view for m in cand):
                        self.generation = gen
                        self.members = cand
                        self.world = len(cand)
                        self.rank = cand.index(self.node_id)
                        self._gc_generation(gen - 1)
                        return self.rank
            except OSError:
                # store briefly unreachable (replicated-store failover,
                # transient outage): the retry burst below the store layer
                # is already exhausted — keep POLLING until the rendezvous
                # deadline instead of crashing the join. Liveness is safe:
                # nobody can expire while the store everyone reads is down.
                pass
            time.sleep(min(next(delays), max(deadline - time.monotonic(), 0)))
        raise TimeoutError(
            f"rendezvous gen={gen} did not converge within {timeout}s "
            f"(node {self.node_id}, alive={sorted(alive)}, "
            f"joined={sorted(joined)})")

    def _gc_generation(self, gen: int):
        """Drop OUR keys from a finished generation (each rank cleans after
        itself; a dead rank's leftovers are harmless — new generations use
        new key prefixes)."""
        if gen < 0:
            return
        try:
            for k in self._kv_scan(keys_only=True):
                if (k.endswith(f":{self.node_id}")
                        and (k.startswith(f"rdv{gen}:")
                             or k.startswith(f"rdvview{gen}:"))):
                    self.store.delete(k)
        except Exception:
            pass  # GC is best-effort; the job-scoped store dies with the job

    # -- data plane -----------------------------------------------------
    def allgather(self, tag: str, payload: str,
                  timeout: float = 60.0) -> List[str]:
        """Publish ``payload`` under ``tag`` and return every member's
        payload in RANK ORDER (deterministic reduction order — the
        bit-identical-recovery contract). Raises :class:`RankFailure` when
        a member expires before publishing, :class:`TimeoutError` when a
        member stays alive but silent past ``timeout``."""
        if self.rank is None:
            raise RuntimeError("rendezvous before allgather")
        prefix = f"ag{self.generation}:{tag}:"
        my_key = f"{prefix}{self.rank}"
        deadline = time.monotonic() + timeout
        delays = self._sleep_iter()
        published = False
        # growth-deadlock guard pacing: first rdv scan only after the wait
        # has outlived normal straggle, then at most every 2s — the scan
        # carries every view blob, and running it per poll tick would
        # re-create the payload-per-poll load the presence-only poll above
        # exists to avoid
        guard_at = time.monotonic() + 0.5
        while True:
            try:
                # (re)publish inside the loop: a store failover can
                # swallow the first attempt's retry burst, and publishing
                # the same key/payload again is idempotent
                if not published:
                    self.store.put(my_key, payload)
                    published = True
                # poll on key PRESENCE only — every iteration of this loop
                # re-runs while a peer is slow, and shipping all W payload
                # blobs per poll would melt the single KV server exactly
                # when a rank is struggling. Payload values transfer
                # exactly once, after the round is complete (blobs are
                # never GC'd before the NEXT round completes, so the fetch
                # cannot miss).
                present = self._scan_prefix(prefix, keys_only=True)
                if all(str(r) in present for r in range(self.world)):
                    got = self._scan_prefix(prefix)
                    # GC our blob from the PREVIOUS gather — only NOW is
                    # it provably consumed: this gather completing means
                    # every peer has published this round, which it can
                    # only do after finishing the previous one. Deleting
                    # at publish time instead would yank the blob from
                    # under a slower peer still reading the previous
                    # round.
                    if self._last_ag_key not in (None, my_key):
                        try:
                            self.store.delete(self._last_ag_key)
                        except Exception:
                            pass
                    self._last_ag_key = my_key
                    return [got[str(r)] for r in range(self.world)]
                missing = [r for r in range(self.world)
                           if str(r) not in present]
                alive = set(self.store.nodes())
            except OSError:
                # store briefly unreachable (failover window): neither a
                # dead rank nor a failed gather — keep polling until the
                # allgather deadline (nobody expires while the store is
                # down for everyone)
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"allgather '{tag}' store unreachable past "
                        f"{timeout}s")
                time.sleep(min(next(delays), 0.25))
                continue
            dead = [self.members[r] for r in missing
                    if self.members[r] not in alive]
            if dead:
                raise RankFailure(
                    f"rank(s) {[self.members.index(d) for d in dead]} "
                    f"({dead}) died during allgather '{tag}' "
                    f"(gen {self.generation})", dead=dead)
            # elastic GROWTH deadlock guard: a missing member that is
            # still ALIVE may have left for a higher rendezvous
            # generation (it saw a new node register at its step
            # boundary; we checked a beat earlier and missed it). It will
            # never publish this round's payload — without this check the
            # two incumbents mutually stall: one in the new rendezvous
            # waiting for us, us here waiting for its gradient. Gated off
            # the hot path: a healthy peer is at most a few poll ticks
            # behind, so the extra rdv scan runs only once the wait has
            # outlived any normal straggle (the stall it exists to break
            # holds for the full step timeout otherwise).
            if time.monotonic() >= guard_at:
                guard_at = time.monotonic() + 2.0
                try:
                    gens = self._parse_rdv(self._kv_scan(prefix="rdv"))
                except OSError:
                    gens = {}
                moved = [self.members[r] for r in missing
                         if any(g > self.generation
                                and (self.members[r] in joins
                                     or self.members[r] in views)
                                for g, (joins, views) in gens.items())]
                if moved:
                    raise RankFailure(
                        f"rank(s) {[self.members.index(m) for m in moved]} "
                        f"({moved}) left allgather '{tag}' for a newer "
                        f"rendezvous generation (> {self.generation}) — "
                        "regrouping", dead=[])
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"allgather '{tag}' missing ranks {missing} after "
                    f"{timeout}s (all still alive — stalled, not dead)")
            time.sleep(min(next(delays), 0.25))

    def barrier(self, tag: str, timeout: float = 60.0):
        self.allgather(f"bar:{tag}", "1", timeout=timeout)

    def membership_changed(self) -> bool:
        """Live membership differs from the committed rendezvous view —
        the trainer's step-boundary scale-up/scale-down probe."""
        try:
            return sorted(self.store.nodes()) != self.members
        except Exception:
            return False
