from .http_server import KVClient, KVServer  # noqa: F401
from .replicated_store import (  # noqa: F401
    ReplicatedKVClient,
    ReplicatedKVServer,
    ReplicatedStoreCluster,
)
