from .http_server import KVClient, KVServer  # noqa: F401
