"""Quorum-replicated coordination store: leader leases, epoch fencing,
client-transparent failover.

Parity: the reference Fleet layer rests on an etcd REGISTRY
(fleet/elastic/manager.py:103) that is assumed highly available — etcd is
itself a raft quorum. Our port collapsed it into ONE
:class:`~.http_server.KVServer` at one address, which after the r11 rank
recovery and r14 replica failover left the coordination plane the last
single point of failure: kill that host and heartbeats, rendezvous, and
gradient allgather all stall at once. This module replicates the store the
same way etcd does, scaled to the codebase's idiom (HTTP + threads, no new
dependencies):

* **N replicas, one leader.** Every replica serves the full KVServer
  client protocol, but only the leader ACCEPTS it — followers answer
  ``409 {"not_leader": <hint>}`` and the client follows the hint
  (client-transparent failover; the ``_TcpStore`` retry/backoff layer
  above is unchanged).
* **Epoch-numbered leader lease.** The lease record is replicated like
  any key: the leader renews it every ``lease_ttl/3`` through the same
  quorum append path as client writes, and every accepted append refreshes
  the followers' lease deadline. A leader that cannot reach a quorum keeps
  serving only until its OWN lease deadline, then steps down.
* **Quorum acks + epoch fencing.** Writes carry ``(epoch, seq)``; the
  leader acknowledges a client only after ⌊N/2⌋+1 replicas (itself
  included) applied the record, and followers REJECT appends from a lower
  epoch — a partitioned deposed leader can keep trying, but its appends
  bounce (``stale_epoch``) and its clients get 503, never a false ack. An
  acknowledged write therefore lives on a quorum, and any electable
  successor intersects that quorum.
* **Deterministic election.** On lease expiry a survivor stands for
  ``epoch+1`` and wins with a quorum of votes. A vote is granted only to a
  candidate whose ``(last_epoch, last_seq, node_id)`` is >= the voter's
  own, so only the most-caught-up survivor (id as the tiebreak) can ever
  collect a quorum; a refused candidate that learns of a better peer
  defers instead of re-standing, so contested elections converge in a
  round or two instead of livelocking.
* **Snapshot catch-up.** A follower that answers an append with
  ``behind`` (seq gap — it missed writes while down) gets the leader's
  full state pushed (``/_install``) and the append retried: lagging
  rejoiners catch up in one transfer, not one RPC per missed write.

Failure seams (the r13 inject plane): ``store.replica.append`` fires
per-peer per-append on the leader (raise/timeout/drop = that peer lost
this append), ``store.lease.renew`` in the leader's renewal tick,
``store.replica.kill`` in every replica's monitor tick (kind ``kill`` =
this replica's deterministic SIGKILL), ``store.election.start`` /
``store.election.won`` around candidacy. Observability (r12):
``store_role`` / ``store_epoch`` / ``store_replication_lag`` gauges,
``store_failovers_total``, and a flight dump on every leader change.

Replica-plane protocol (JSON over the same HTTP server):
  POST /_replicate  {epoch, seq, op, scope?, key?, value?, age}
  POST /_vote       {epoch, last: [last_epoch, last_seq], id}
  POST /_install    full snapshot (leader → lagging follower)
  GET  /_snapshot   full snapshot (pull form)
  GET  /_status     {id, role, epoch, seq, leader} (debug/bench/tests)
"""
from __future__ import annotations

import json
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from .http_server import KVClient, _BaseHandler

__all__ = ["ReplicatedKVServer", "ReplicatedKVClient",
           "ReplicatedStoreCluster", "quorum_size"]

ROLE_FOLLOWER = "follower"
ROLE_CANDIDATE = "candidate"
ROLE_LEADER = "leader"
_ROLE_CODE = {ROLE_FOLLOWER: 0, ROLE_CANDIDATE: 1, ROLE_LEADER: 2}

#: reserved scope holding the replicated lease record
_SYS_SCOPE = "_sys"


def quorum_size(n: int) -> int:
    return n // 2 + 1


def _fire(point: str, **labels):
    from ....resilience.inject import fire

    return fire(point, **labels)


class _ReplicaHandler(_BaseHandler):
    """Per-server-bound handler (subclassed with ``server_obj`` set) —
    client plane answered only by the leader, replica plane by everyone
    (unless partitioned). Wire framing + scan rendering come from the
    shared :class:`~.http_server._BaseHandler`."""

    server_obj: "ReplicatedKVServer"

    def _reply_json(self, status: int, doc: dict):
        self._reply(status, json.dumps(doc).encode())

    def _gone(self) -> bool:
        # a killed replica answers NOTHING, including on lingering
        # keep-alive connections — the client sees a dropped connection
        # exactly like a SIGKILLed process's
        if self.server_obj.dead:
            self.close_connection = True
            return True
        return False

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n)
        try:
            return json.loads(raw.decode()) if raw else {}
        except ValueError:
            return {}

    # -- replica plane ---------------------------------------------------
    def do_POST(self):
        if self._gone():
            return
        srv = self.server_obj
        path = self.path.split("?", 1)[0]
        body = self._body()
        if srv.partitioned:
            # a partitioned replica is unreachable on the REPLICA plane
            # (peers' appends/votes never arrive); 503 reads as "no ack"
            self._reply_json(503, {"error": "partitioned"})
            return
        if path == "/_replicate":
            status, doc = srv.handle_replicate(body)
        elif path == "/_vote":
            status, doc = srv.handle_vote(body)
        elif path == "/_install":
            status, doc = srv.handle_install(body)
        else:
            status, doc = 404, {"error": "unknown"}
        self._reply_json(status, doc)

    # -- client plane ----------------------------------------------------
    def _not_leader(self):
        self._reply_json(409, {"not_leader": self.server_obj.leader_hint})

    def do_PUT(self):
        if self._gone():
            return
        scope, key = self._parts()
        if key is None:
            self._reply(400)
            return
        n = int(self.headers.get("Content-Length", 0))
        val = self.rfile.read(n).decode()
        srv = self.server_obj
        if not srv.is_leader():
            self._not_leader()
            return
        ok = srv.leader_write("put", scope, key, val)
        if ok is None:  # deposed mid-write
            self._not_leader()
        else:
            self._reply_json(200 if ok else 503,
                             {} if ok else {"error": "no_quorum"})

    def do_DELETE(self):
        if self._gone():
            return
        scope, key = self._parts()
        srv = self.server_obj
        if not srv.is_leader():
            self._not_leader()
            return
        ok = srv.leader_write("delete", scope, key, "")
        if ok is None:
            self._not_leader()
        else:
            self._reply_json(200 if ok else 503,
                             {} if ok else {"error": "no_quorum"})

    def do_GET(self):
        if self._gone():
            return
        srv = self.server_obj
        path = self.path.split("?", 1)[0]
        if path == "/_status":
            self._reply_json(200, srv.status())
            return
        if path == "/_snapshot":
            if srv.partitioned:
                self._reply_json(503, {"error": "partitioned"})
                return
            self._reply_json(200, srv.snapshot())
            return
        scope, key = self._parts()
        # reads are served by the leader only: a follower's state may lag
        # the ack point, and the lease bounds how long a deposed leader
        # can serve stale reads (the etcd model)
        if not srv.is_leader():
            self._not_leader()
            return
        bucket = srv.read_scope(scope)
        if key is None:
            self._reply(200, self._render_scan(bucket))
            return
        hit = bucket.get(key)
        if hit is None:
            self._reply(404)
            return
        self._reply(200, hit[0].encode())


class ReplicatedKVServer:
    """One replica of the quorum store.

    Construct all N with the shared ``addrs`` list (``addrs[index]`` is
    this replica; port 0 is allowed when built through
    :class:`ReplicatedStoreCluster`, which collects the bound ports before
    starting the protocol threads)."""

    def __init__(self, index: int, addrs: List[str], *,
                 lease_ttl: float = 2.0, host: str = "127.0.0.1",
                 rpc_timeout: Optional[float] = None):
        self.index = int(index)
        self.node_id = f"s{index}"
        self.lease_ttl = float(lease_ttl)
        # peer RPCs must resolve well inside a monitor tick: a hung peer
        # (vs a refused connection) cannot be allowed to stall the
        # leader's renewal loop past its own lease
        self.rpc_timeout = (float(rpc_timeout) if rpc_timeout is not None
                            else max(self.lease_ttl / 4, 0.1))
        port = int(addrs[index].rsplit(":", 1)[1])
        handler = type("_BoundReplicaHandler", (_ReplicaHandler,), {})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        handler.server_obj = self
        self.port = self._httpd.server_address[1]
        self.addrs = list(addrs)
        self.addrs[index] = f"{host}:{self.port}"
        self.addr = self.addrs[index]
        self.quorum = quorum_size(len(addrs))

        # replicated state (all under _lock)
        self._lock = threading.RLock()
        self._kv: Dict[str, Dict[str, Tuple[str, float]]] = {}  # guarded-by: self._lock
        self.epoch = 0        # guarded-by: self._lock
        self.seq = 0          # guarded-by: self._lock
        self.last_epoch = 0   # guarded-by: self._lock
        self.role = ROLE_FOLLOWER  # guarded-by: self._lock
        self.leader_hint: Optional[str] = None
        self._voted: Dict[int, Tuple] = {}  # epoch -> (last, id) granted
        self._peer_seq: Dict[str, int] = {}
        # nobody is leader at boot: half a TTL of grace for peers to come
        # up, then elect (a premature candidacy just fails and retries)
        self._lease_deadline = time.monotonic() + self.lease_ttl / 2.0
        self._defer_until = 0.0
        self._last_renew = 0.0

        self.dead = False
        self.partitioned = False
        self._stop = threading.Event()
        # serializes the append pipeline: one record is built, applied
        # and quorum-replicated (peer RPCs and all) before the next — the
        # blocking hold IS the single-writer log discipline
        self._wlock = threading.Lock()  # hostrace: blocking-ok
        self._threads: List[threading.Thread] = []
        self._peer_clients = {
            a: KVClient(a, timeout=self.rpc_timeout)
            for i, a in enumerate(self.addrs) if i != self.index}

        from ....observability.metrics import default_registry

        r = default_registry()
        self._g_role = r.gauge(
            "store_role",
            "replica role (0 follower, 1 candidate, 2 leader)", ("node",))
        self._g_epoch = r.gauge("store_epoch", "replica current epoch",
                                ("node",))
        self._g_lag = r.gauge(
            "store_replication_lag",
            "leader seq minus this peer's acked seq", ("node", "peer"))
        self._c_failovers = r.counter(
            "store_failovers_total", "leader elections won", ("node",))
        self._g_role.set(0, node=self.node_id)
        self._g_epoch.set(0, node=self.node_id)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicatedKVServer":
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        m = threading.Thread(target=self._monitor, daemon=True)
        m.start()
        self._threads.append(m)
        return self

    def _halt_http(self):
        try:
            if self._threads:  # shutdown() hangs if serve_forever never ran
                self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass

    def stop(self):
        """Graceful stop (tests' cleanup path — NOT the chaos path)."""
        self._stop.set()
        self.dead = True
        self._halt_http()

    def kill(self):
        """Abrupt death — the in-process SIGKILL: stop answering
        ANYTHING, immediately, with no goodbye. Lingering keep-alive
        handler threads drop their connections unanswered."""
        self.dead = True
        self._stop.set()
        self._halt_http()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def partition(self, on: bool = True):
        """Test/chaos hook: isolate this replica — its outbound replica
        RPCs fail and inbound replica-plane requests answer 503 (both
        directions dark, like a cut network). The CLIENT plane keeps
        answering: a partitioned stale leader still accepting writes is
        exactly the scenario epoch fencing must defeat."""
        self.partitioned = bool(on)

    # -- introspection ---------------------------------------------------
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == ROLE_LEADER and not self.dead

    def status(self) -> dict:
        with self._lock:
            return {"id": self.node_id, "role": self.role,
                    "epoch": self.epoch, "seq": self.seq,
                    "last_epoch": self.last_epoch,
                    "leader": self.leader_hint}

    def read_scope(self, scope: str) -> Dict[str, Tuple[str, float]]:
        with self._lock:
            return dict(self._kv.get(scope, {}))

    def snapshot(self) -> dict:
        """Full-state transfer document (ages, not stamps: monotonic
        clocks don't travel between processes)."""
        with self._lock:
            now = time.monotonic()
            return {
                "epoch": self.epoch, "seq": self.seq,
                "last_epoch": self.last_epoch,
                "kv": {s: {k: [v, now - ts] for k, (v, ts) in b.items()}
                       for s, b in self._kv.items()},
            }

    # -- the replicated log ----------------------------------------------
    # hostrace: requires(self._lock)
    def _apply(self, rec: dict):
        """Apply one record locally (caller holds the lock). Ages ride the
        record so the stamp a replica keeps reflects the WRITE time, not
        the replication time — heartbeat TTLs survive failover."""
        op = rec["op"]
        stamp = time.monotonic() - float(rec.get("age", 0.0))
        if op == "put":
            self._kv.setdefault(rec["scope"], {})[rec["key"]] = (
                rec["value"], stamp)
        elif op == "delete":
            self._kv.get(rec["scope"], {}).pop(rec["key"], None)
        elif op == "lease":
            info = json.loads(rec["value"])
            self.leader_hint = info["addr"]
            self._kv.setdefault(_SYS_SCOPE, {})["lease"] = (
                rec["value"], stamp)
        self.seq = int(rec["seq"])
        self.last_epoch = int(rec["epoch"])

    def handle_replicate(self, rec: dict) -> Tuple[int, dict]:
        with self._lock:
            if int(rec.get("epoch", -1)) < self.epoch:
                # FENCE: a deposed leader's append — reject, tell it why
                return 409, {"error": "stale_epoch", "epoch": self.epoch}
            if int(rec["epoch"]) > self.epoch or self.role != ROLE_FOLLOWER:
                self._step_down(int(rec["epoch"]))
            if int(rec["seq"]) <= self.seq:
                # same-position record already present. It is a safe
                # duplicate ONLY when this replica's tail was written by
                # the SAME epoch's (single) leader; a tail from an older
                # epoch may hold a locally-applied-but-never-acked record
                # at this seq (a deposed leader's phantom) — dup-acking
                # that would count divergent state toward the quorum and
                # lose an acknowledged write. Force a snapshot instead.
                if self.last_epoch == int(rec["epoch"]):
                    self._touch_lease()
                    return 200, {"seq": self.seq}
                return 409, {"error": "behind", "seq": self.seq}
            if int(rec["seq"]) != self.seq + 1:
                # missed writes while down: ask for a snapshot
                return 409, {"error": "behind", "seq": self.seq}
            # Raft log-matching: the append names the epoch of the record
            # preceding it; a mismatch means OUR tail diverged (phantom
            # records from a deposed leadership) even though the seq
            # numbers line up — snapshot, don't append on top
            prev = rec.get("prev_epoch")
            if prev is not None and int(prev) != self.last_epoch:
                return 409, {"error": "behind", "seq": self.seq}
            self._apply(rec)
            self.leader_hint = rec.get("leader", self.leader_hint)
            self._touch_lease()
            return 200, {"seq": self.seq}

    def handle_install(self, snap: dict) -> Tuple[int, dict]:
        with self._lock:
            if int(snap.get("epoch", -1)) < self.epoch:
                return 409, {"error": "stale_epoch", "epoch": self.epoch}
            # the current-epoch leader's snapshot is authoritative even
            # when OUR seq is higher: a longer local tail from an older
            # epoch is a deposed leadership's never-acked phantom state,
            # and install is exactly the repair that truncates it
            now = time.monotonic()
            self._kv = {
                s: {k: (v, now - float(age))
                    for k, (v, age) in b.items()}
                for s, b in snap["kv"].items()}
            self.seq = int(snap["seq"])
            self.last_epoch = int(snap["last_epoch"])
            self._step_down(int(snap["epoch"]))
            lease = self._kv.get(_SYS_SCOPE, {}).get("lease")
            if lease is not None:
                self.leader_hint = json.loads(lease[0])["addr"]
            self._touch_lease()
            return 200, {"seq": self.seq}

    def handle_vote(self, req: dict) -> Tuple[int, dict]:
        with self._lock:
            target = int(req["epoch"])
            cand = ((int(req["last"][0]), int(req["last"][1])),
                    str(req["id"]))
            mine = ((self.last_epoch, self.seq), self.node_id)
            refuse = {"granted": False, "epoch": self.epoch,
                      "last": [self.last_epoch, self.seq],
                      "id": self.node_id}
            if target <= self.epoch:
                return 200, refuse
            if (time.monotonic() < self._lease_deadline
                    and (self.role == ROLE_LEADER
                         or (self.role == ROLE_FOLLOWER
                             and self.leader_hint is not None))):
                # a live lease (mine as leader, or my leader's as
                # follower) outranks any candidacy — no election needed
                return 200, refuse
            if target in self._voted:
                return 200, refuse
            if cand < mine:
                # the candidate is behind me (or ties with a lower id):
                # my refusal carries my tuple so it defers to a better
                # survivor instead of burning epochs
                return 200, refuse
            self._voted[target] = cand
            # granting adopts the epoch (Raft term semantics): the old
            # leader is fenced here even before the winner's first append
            self._step_down(target)
            self.leader_hint = None
            # ... and resets the election timer: the winner gets one full
            # TTL to land its first lease append, or this voter's own
            # candidacy in the gap would bump epochs that later fence the
            # leader it just elected (churn)
            self._touch_lease()
            return 200, {"granted": True, "epoch": target}

    # -- leader paths ----------------------------------------------------
    def _post_peer(self, addr: str, path: str, doc: dict):
        """One replica-plane RPC. Returns (status, body dict) or raises
        OSError (unreachable / partitioned)."""
        if self.partitioned:
            raise ConnectionError("partitioned (outbound)")
        status, data = self._peer_clients[addr]._request(
            "POST", path, body=json.dumps(doc).encode())
        try:
            return status, (json.loads(data.decode()) if data else {})
        except ValueError:
            return status, {}

    def _append_to_peer(self, addr: str, rec: dict) -> bool:
        """Replicate one record to one peer; pushes a snapshot first when
        the peer reports it is behind. True = peer applied (ack)."""
        try:
            status, doc = self._post_peer(addr, "/_replicate", rec)
            if status == 409 and doc.get("error") == "behind":
                snap = self.snapshot()
                status, _ = self._post_peer(addr, "/_install", snap)
                if status != 200:
                    return False
                status, doc = self._post_peer(addr, "/_replicate", rec)
            if status == 409 and doc.get("error") == "stale_epoch":
                with self._lock:
                    self._step_down(int(doc.get("epoch", self.epoch)))
                return False
            if status == 200:
                with self._lock:
                    self._peer_seq[addr] = int(rec["seq"])
                    self._g_lag.set(self.seq - self._peer_seq[addr],
                                    node=self.node_id, peer=addr)
                return True
            return False
        except OSError:
            return False

    def _replicate_record(self, op: str, scope: str, key: str,
                          value: str) -> Optional[bool]:
        """Build, locally apply, and quorum-replicate one record. Returns
        True = acknowledged (quorum applied), False = no quorum (NOT
        acknowledged; the record may or may not survive — exactly the
        client contract of an unacked write), None = not leader anymore."""
        with self._wlock:
            with self._lock:
                if self.role != ROLE_LEADER or self.dead:
                    return None
                rec = {"epoch": self.epoch, "seq": self.seq + 1, "op": op,
                       "scope": scope, "key": key, "value": value,
                       "age": 0.0, "leader": self.addr,
                       # log-matching anchor: the epoch of the record this
                       # one follows (followers verify their tail matches)
                       "prev_epoch": self.last_epoch}
                self._apply(rec)
            acks = 1  # self
            for i, addr in enumerate(self.addrs):
                if i == self.index:
                    continue
                try:
                    f = _fire("store.replica.append", node=self.node_id,
                              peer=f"s{i}", op=op)
                except Exception:
                    continue  # injected transport failure: THIS peer only
                if f is not None and f.kind == "drop":
                    continue  # this peer never sees the append
                try:
                    if self._append_to_peer(addr, rec):
                        acks += 1
                except OSError:
                    pass
            if acks >= self.quorum:
                return True
            with self._lock:
                if self.role != ROLE_LEADER:
                    return None
            return False

    def leader_write(self, op: str, scope: str, key: str,
                     value: str) -> Optional[bool]:
        try:
            return self._replicate_record(op, scope, key, value)
        except Exception:
            return False

    def _renew_lease(self) -> Optional[bool]:
        with self._lock:
            epoch_now = self.epoch
        return self._replicate_record(
            "lease", _SYS_SCOPE, "lease",
            json.dumps({"id": self.node_id, "addr": self.addr,
                        "epoch": epoch_now}))

    # -- role transitions ------------------------------------------------
    def _touch_lease(self):
        self._lease_deadline = time.monotonic() + self.lease_ttl

    # hostrace: requires(self._lock)
    def _step_down(self, epoch: int):
        """Adopt ``epoch`` as a follower (caller holds the lock)."""
        was_leader = self.role == ROLE_LEADER
        self.epoch = max(self.epoch, int(epoch))
        self.role = ROLE_FOLLOWER
        self._g_role.set(0, node=self.node_id)
        self._g_epoch.set(self.epoch, node=self.node_id)
        if was_leader:
            self.leader_hint = None

    def _become_leader(self, epoch: int):
        from ....observability.flight import flight_recorder

        with self._lock:
            self.epoch = int(epoch)
            self.role = ROLE_LEADER
            self.leader_hint = self.addr
            self._peer_seq = {}
            self._g_role.set(2, node=self.node_id)
            self._g_epoch.set(self.epoch, node=self.node_id)
            seq_now = self.seq  # captured under the lock for the dump
        self._c_failovers.inc(node=self.node_id)
        _fire("store.election.won", node=self.node_id, epoch=int(epoch))
        # leader changes are exactly the moments a post-mortem needs:
        # freeze the span ring + store series (in-memory unless armed)
        flight_recorder().dump(
            "store_leader_change",
            extra={"node": self.node_id, "epoch": int(epoch),
                   "seq": seq_now})
        # the first append at the new epoch both announces the lease and
        # fences every lower epoch on a quorum
        ok = self._renew_lease()
        if ok:
            with self._lock:
                self._touch_lease()
            self._last_renew = time.monotonic()
        else:
            with self._lock:
                if self.role == ROLE_LEADER:
                    self._step_down(self.epoch)

    def _stand_for_election(self):
        with self._lock:
            if self.role == ROLE_LEADER:
                return
            self.role = ROLE_CANDIDATE
            self._g_role.set(1, node=self.node_id)
            target = self.epoch + 1
            my_last = (self.last_epoch, self.seq)
            # a candidate votes for itself — recorded so a lesser rival
            # asking at the same epoch is refused
            self._voted.setdefault(target, (my_last, self.node_id))
        _fire("store.election.start", node=self.node_id, epoch=target)
        votes = 1
        better_peer = False
        for i, addr in enumerate(self.addrs):
            if i == self.index:
                continue
            try:
                status, doc = self._post_peer(addr, "/_vote", {
                    "epoch": target, "last": list(my_last),
                    "id": self.node_id})
            except OSError:
                continue
            if status != 200:
                continue
            if doc.get("granted"):
                votes += 1
                continue
            if int(doc.get("epoch", 0)) > target:
                with self._lock:
                    self._step_down(int(doc["epoch"]))
                return
            peer_last = doc.get("last")
            if (peer_last is not None
                    and ((int(peer_last[0]), int(peer_last[1])),
                         str(doc.get("id", ""))) > (my_last, self.node_id)):
                better_peer = True
        if votes >= self.quorum:
            self._become_leader(target)
            return
        with self._lock:
            if self.role == ROLE_CANDIDATE:
                self.role = ROLE_FOLLOWER
                self._g_role.set(0, node=self.node_id)
            if better_peer:
                # a more-caught-up survivor exists: give it a full TTL to
                # win before this replica considers standing again —
                # the deterministic anti-livelock rule
                self._defer_until = time.monotonic() + self.lease_ttl
            self.epoch = max(self.epoch, target)

    # -- monitor thread --------------------------------------------------
    def _monitor(self):
        tick = max(self.lease_ttl / 5.0, 0.02)
        # stagger candidacies so simultaneous expiry does not produce N
        # simultaneous candidates; HIGHEST id soonest — on equal (epoch,
        # seq) only the highest id can win (the vote tiebreak), so letting
        # it stand first converges in one round instead of two
        stagger = (len(self.addrs) - 1 - self.index) * tick / 2.0
        while not self._stop.wait(tick):
            try:
                f = _fire("store.replica.kill", node=self.node_id)
                if f is not None and f.kind == "kill":
                    self.kill()
                    return
                if self.dead:
                    return
                with self._lock:
                    role = self.role
                    epoch_now = self.epoch
                    expired = time.monotonic() > self._lease_deadline
                    deferred = time.monotonic() < self._defer_until
                if role == ROLE_LEADER:
                    now = time.monotonic()
                    if now - self._last_renew >= self.lease_ttl / 3.0:
                        try:
                            _fire("store.lease.renew", node=self.node_id,
                                  epoch=epoch_now)
                        except Exception:
                            continue  # injected renewal failure: skip round
                        if self._renew_lease():
                            self._last_renew = now
                            with self._lock:
                                self._touch_lease()
                        # re-stamp AFTER the (blocking) renewal RPCs: the
                        # pre-renewal stamp would let a quorumless leader
                        # serve reads past its own lease by the RPC time
                        elif time.monotonic() > self._lease_deadline:
                            # could not hold a quorum for a full lease:
                            # deposed or partitioned — stop serving
                            with self._lock:
                                self._step_down(self.epoch)
                elif expired and not deferred and not self.partitioned:
                    if stagger:
                        time.sleep(stagger)
                        with self._lock:
                            if (self.role == ROLE_LEADER or time.monotonic()
                                    < self._lease_deadline):
                                continue
                    self._stand_for_election()
            except Exception:
                # the monitor is the replica's heart — it must survive
                # any single failed round (peer down mid-vote, etc.)
                pass


class ReplicatedKVClient:
    """Drop-in :class:`~.http_server.KVClient` over a replica set.

    Same method surface and strict/lenient semantics; each logical RPC is
    ONE discovery pass over the replicas — cached leader first, then
    ``NotLeader`` hints, then the rest of the list — and raises OSError
    (strict) only when the whole pass fails, so the caller's retry policy
    (``_TcpStore`` backoff + ``RetryBudget``) sees a replicated store
    exactly like a single one. Per-replica connections are kept alive
    through the underlying clients."""

    def __init__(self, addrs: List[str], timeout: float = 5.0):
        if not addrs:
            raise ValueError("need at least one replica address")
        self.addrs = [a.strip() for a in addrs if a.strip()]
        self.timeout = timeout
        self._clients = {a: KVClient(a, timeout=timeout)
                         for a in self.addrs}
        self._leader: Optional[str] = None

    @property
    def addr(self) -> str:
        return ",".join(self.addrs)

    def _candidates(self) -> List[str]:
        lead = self._leader
        rest = [a for a in self.addrs if a != lead]
        return ([lead] + rest) if lead else list(self.addrs)

    def _call(self, method: str, path: str, body: Optional[bytes] = None
              ) -> Tuple[int, bytes]:
        """One leader-discovering pass. Statuses other than 409 come from
        a replica CLAIMING leadership and are the caller's to interpret;
        409 follows the hint; transport failure moves on. Raises
        ConnectionError when no replica answered as leader."""
        tried = set()
        queue = self._candidates()
        hops = 0
        last_err: Optional[str] = None
        while queue and hops < len(self.addrs) + 3:
            addr = queue.pop(0)
            if addr in tried or addr not in self._clients:
                continue
            tried.add(addr)
            hops += 1
            try:
                status, data = self._clients[addr]._request(
                    method, path, body=body)
            except OSError as e:
                if self._leader == addr:
                    self._leader = None
                last_err = f"{addr}: {type(e).__name__}"
                continue
            if status == 409:
                if self._leader == addr:
                    self._leader = None
                try:
                    hint = json.loads(data.decode()).get("not_leader")
                except ValueError:
                    hint = None
                if hint and hint not in tried:
                    queue.insert(0, hint)
                    # a redirect target outside the configured list is
                    # still followable (a replica knows best), one hop
                    self._clients.setdefault(
                        hint, KVClient(hint, timeout=self.timeout))
                continue
            if status == 503:
                # a leader that cannot reach quorum: not a success, and
                # not worth trying followers (they would redirect back) —
                # fail the pass so the retry layer backs off
                last_err = f"{addr}: no_quorum"
                continue
            self._leader = addr
            return status, data
        raise ConnectionError(
            f"no reachable leader among {self.addr} ({last_err})")

    def close(self):
        for c in self._clients.values():
            c.close()

    # -- KVClient surface ------------------------------------------------
    def put(self, scope: str, key: str, value: str,
            strict: bool = False) -> bool:
        try:
            status, _ = self._call("PUT", f"/{scope}/{key}",
                                   body=value.encode())
            return status == 200
        except OSError:
            if strict:
                raise
            return False

    def get(self, scope: str, key: str, strict: bool = False
            ) -> Optional[str]:
        try:
            status, data = self._call("GET", f"/{scope}/{key}")
            return data.decode() if status == 200 else None
        except OSError:
            if strict:
                raise
            return None

    def delete(self, scope: str, key: str, strict: bool = False) -> bool:
        try:
            status, _ = self._call("DELETE", f"/{scope}/{key}")
            return status == 200
        except OSError:
            if strict:
                raise
            return False

    def scan(self, scope: str, strict: bool = False, keys_only: bool = False,
             prefix: Optional[str] = None) -> Dict[str, Tuple[str, float]]:
        try:
            status, data = self._call(
                "GET", KVClient._scan_path(scope, keys_only, prefix))
            if status != 200:
                return {}
            parsed = json.loads(data.decode())
            return {k: (v[0], float(v[1])) for k, v in parsed.items()}
        except (OSError, ValueError):
            if strict:
                raise
            return {}

    def leader_status(self) -> Optional[dict]:
        """{id, role, epoch, seq, leader} of the current leader, or None
        when no replica claims leadership (bench/test introspection)."""
        for addr in self._candidates():
            try:
                status, data = self._clients[addr]._request(
                    "GET", "/_status")
            except OSError:
                continue
            if status != 200:
                continue
            try:
                doc = json.loads(data.decode())
            except ValueError:
                continue
            if doc.get("role") == ROLE_LEADER:
                self._leader = addr
                doc["addr"] = addr
                return doc
        return None


class ReplicatedStoreCluster:
    """Build + run N replicas in-process (tests, bench, single host).

    Ephemeral ports: replicas are bound one by one and the discovered
    address list is shared before any protocol thread starts."""

    def __init__(self, n: int = 3, *, lease_ttl: float = 2.0,
                 host: str = "127.0.0.1"):
        if n < 1:
            raise ValueError("need at least one replica")
        addrs = [f"{host}:0"] * n
        self.servers: List[ReplicatedKVServer] = []
        for i in range(n):
            srv = ReplicatedKVServer(i, addrs, lease_ttl=lease_ttl,
                                     host=host)
            addrs[i] = srv.addr
            self.servers.append(srv)
        for srv in self.servers:
            srv.addrs = list(addrs)
            srv._peer_clients = {
                a: KVClient(a, timeout=srv.rpc_timeout)
                for j, a in enumerate(addrs) if j != srv.index}
        self.addrs = list(addrs)

    @property
    def addr_spec(self) -> str:
        """The multi-address ``_TcpStore`` spec ("a,b,c")."""
        return ",".join(self.addrs)

    def start(self) -> "ReplicatedStoreCluster":
        for srv in self.servers:
            srv.start()
        return self

    def stop(self):
        for srv in self.servers:
            srv.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def leader(self, timeout: float = 10.0) -> ReplicatedKVServer:
        """Block until exactly one live replica is leader; returns it."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [s for s in self.servers
                       if not s.dead and s.is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.02)
        raise TimeoutError("no (single) leader elected within "
                           f"{timeout}s: "
                           f"{[(s.node_id, s.role) for s in self.servers]}")

    def wait_for_leader_change(self, old_id: str,
                               timeout: float = 10.0) -> ReplicatedKVServer:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [s for s in self.servers
                       if not s.dead and s.is_leader()
                       and s.node_id != old_id]
            if leaders:
                return leaders[0]
            time.sleep(0.02)
        raise TimeoutError(f"no successor to {old_id} within {timeout}s")


def main(argv: Optional[List[str]] = None) -> int:  # pragma: no cover
    """Run ONE replica as a process (the SIGKILL chaos drills):

    python -m paddle_tpu.distributed.fleet.utils.replicated_store \\
        --index 0 --addrs 127.0.0.1:7501,127.0.0.1:7502,127.0.0.1:7503
    """
    import argparse
    import signal as _signal
    import sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--index", type=int, required=True)
    parser.add_argument("--addrs", required=True,
                        help="comma-separated replica addresses")
    parser.add_argument("--lease-ttl", type=float, default=2.0)
    args = parser.parse_args(argv)
    addrs = args.addrs.split(",")
    host, port = addrs[args.index].rsplit(":", 1)
    srv = ReplicatedKVServer(args.index, addrs, lease_ttl=args.lease_ttl,
                             host=host).start()
    print(f"READY {srv.node_id} {srv.addr}", flush=True)
    stop = threading.Event()
    _signal.signal(_signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    srv.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
