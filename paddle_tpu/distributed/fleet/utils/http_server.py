"""Tiny HTTP key-value server — the cross-host rendezvous/elastic store.

Parity: the reference's Gloo HTTP store
(/root/reference/python/paddle/distributed/fleet/utils/http_server.py — a
BaseHTTPRequestHandler KV server used for barrier/rendezvous) and the etcd
registry of the elastic manager (fleet/elastic/manager.py:103). One tiny
server process (or thread on node 0) replaces both: keys live in memory
with write timestamps so clients implement TTL-based liveness.

Protocol (scope = job id):
  PUT    /<scope>/<key>   body = value        → store + stamp
  GET    /<scope>/<key>                       → value (404 if absent)
  DELETE /<scope>/<key>                       → remove
  GET    /<scope>                             → json {key: [value, age_sec]}
"""
from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

__all__ = ["KVServer", "KVClient"]


class _BaseHandler(BaseHTTPRequestHandler):
    """Wire plumbing shared by the KV-protocol handlers (this module's
    KVServer and the replicated store's replica handler) — one place owns
    the response framing and the scan rendering."""

    # HTTP/1.1 so clients can keep connections alive across RPCs (the
    # KVClient keep-alive reuse); every response therefore MUST carry
    # Content-Length or the client would block reading to EOF
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # quiet
        pass

    def _reply(self, status: int, body: bytes = b""):
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _parts(self):
        path = self.path.split("?", 1)[0]
        parts = [p for p in path.split("/") if p]
        return (parts[0], parts[1]) if len(parts) >= 2 else (parts[0] if parts else "", None)

    def _query(self) -> Dict[str, str]:
        import urllib.parse
        if "?" not in self.path:
            return {}
        return {k: v[-1] for k, v in urllib.parse.parse_qs(
            self.path.split("?", 1)[1]).items()}

    def _render_scan(self, bucket: Dict[str, Tuple[str, float]]) -> bytes:
        """Scope-scan JSON body ({key: [value, age]}) honoring the
        ``prefix``/``keys`` query filters (see KVClient.scan)."""
        now = time.monotonic()
        q = self._query()
        pfx = q.get("prefix", "")
        if pfx:
            bucket = {k: kv for k, kv in bucket.items()
                      if k.startswith(pfx)}
        if q.get("keys") == "1":
            # presence/age only: elastic poll loops scan every iteration,
            # and shipping each rank's full gradient blob per poll turns
            # a slow peer into an O(W^2 x blob) stampede
            return json.dumps({k: [None, now - ts]
                               for k, (v, ts) in bucket.items()}).encode()
        return json.dumps({k: [v, now - ts]
                           for k, (v, ts) in bucket.items()}).encode()


class _Handler(_BaseHandler):
    # `store`/`lock` are set per-server on a subclass (KVServer.__init__) —
    # a class-level store would cross-contaminate servers in one process
    store: Dict[str, Dict[str, Tuple[str, float]]]
    lock: threading.Lock

    # flipped by KVServer.stop()/kill(): a stopped server's lingering
    # keep-alive handler threads must go SILENT (drop the connection,
    # answer nothing), or a cached client connection would keep talking
    # to a server whose listener is long closed
    dead = False

    def _gone(self) -> bool:
        if type(self).dead:
            self.close_connection = True
            return True
        return False

    def do_PUT(self):
        if self._gone():
            return
        scope, key = self._parts()
        if key is None:
            self._reply(400)
            return
        n = int(self.headers.get("Content-Length", 0))
        val = self.rfile.read(n).decode()
        # monotonic stamps: key ages drive heartbeat-TTL liveness, and a
        # wall-clock step (NTP slew/adjtime) must never fake node death or
        # resurrect an expired one
        with self.lock:
            self.store.setdefault(scope, {})[key] = (val, time.monotonic())
        self._reply(200)

    def do_GET(self):
        if self._gone():
            return
        scope, key = self._parts()
        with self.lock:
            bucket = dict(self.store.get(scope, {}))
        if key is None:
            self._reply(200, self._render_scan(bucket))
            return
        hit = bucket.get(key)
        if hit is None:
            self._reply(404)
            return
        self._reply(200, hit[0].encode())

    def do_DELETE(self):
        if self._gone():
            return
        scope, key = self._parts()
        with self.lock:
            self.store.get(scope, {}).pop(key, None)
        self._reply(200)


class KVServer:
    """In-process threaded KV server. ``with KVServer(port):`` or
    start()/stop()."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        handler = type("_BoundHandler", (_Handler,),
                       {"store": {}, "lock": threading.Lock()})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        # silence lingering keep-alive handler threads BEFORE closing the
        # listener: their next request gets a dropped connection, which a
        # reusing client treats as stale → redial → connection refused
        self._httpd.RequestHandlerClass.dead = True
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class KVClient:
    """Client for :class:`KVServer` (reference KVHandler http client role).

    Deliberately dumb: one attempt per call. With ``strict=True`` transport
    failures raise OSError so a caller's retry policy (the elastic store's
    backoff, resilience/retry.py) can distinguish "store down" from a
    legitimately absent key / empty scope; the default swallows them into
    False/None/{} for casual callers.

    Connections are kept alive and reused (bounded: one idle connection per
    THREAD — the beat thread and the collective poll loop each keep their
    own, so neither serializes behind the other's in-flight RPC). A reused
    connection the server has since closed fails the first write/read; that
    one stale case redials transparently, so a failover retry burst against
    a surviving replica costs one dial, not one SYN per RPC."""

    #: redial a kept-alive connection after this many RPCs — bounds how
    #: long one TCP stream is trusted (mirrors HTTP keep-alive max)
    MAX_CONN_REQUESTS = 1000

    def __init__(self, addr: str, timeout: float = 5.0):
        self.addr = addr  # "host:port"
        self.timeout = timeout
        self._tls = threading.local()  # per-thread cached connection

    def _conn(self):
        host, port = self.addr.rsplit(":", 1)
        return http.client.HTTPConnection(host, int(port), timeout=self.timeout)

    def close(self):
        """Drop THIS thread's cached connection (other threads' cached
        connections age out on their next stale-dial)."""
        c = getattr(self._tls, "conn", None)
        self._tls.conn = None
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def _roundtrip(self, c, method: str, path: str, body):
        c.request(method, path, body=body)
        r = c.getresponse()
        return r, r.read()

    def _request(self, method: str, path: str,
                 body: Optional[bytes] = None) -> Tuple[int, bytes]:
        """One RPC over the kept-alive connection; a STALE cached
        connection (server closed it between requests) gets exactly one
        fresh dial, a fresh dial's failure is the caller's (OSError).
        Returns (status, body bytes)."""
        c = getattr(self._tls, "conn", None)
        cached = c is not None
        self._tls.conn = None
        if c is None:
            c = self._conn()
            self._tls.uses = 0
        try:
            r, data = self._roundtrip(c, method, path, body)
        except (OSError, http.client.HTTPException) as e:
            c.close()
            if not cached:
                if isinstance(e, OSError):
                    raise
                # a malformed/torn response on a FRESH connection is a
                # transport failure too — surface it in the OSError family
                # the retry layer already handles
                raise ConnectionError(f"bad response from {self.addr}: "
                                      f"{type(e).__name__}") from e
            c = self._conn()  # dial-on-stale fallback
            self._tls.uses = 0
            try:
                r, data = self._roundtrip(c, method, path, body)
            except (OSError, http.client.HTTPException) as e2:
                c.close()
                if isinstance(e2, OSError):
                    raise
                raise ConnectionError(f"bad response from {self.addr}: "
                                      f"{type(e2).__name__}") from e2
        n = getattr(self._tls, "uses", 0) + 1
        if r.will_close or n >= self.MAX_CONN_REQUESTS:
            c.close()
            self._tls.uses = 0
        else:
            self._tls.conn = c
            self._tls.uses = n
        return r.status, data

    def put(self, scope: str, key: str, value: str, strict: bool = False) -> bool:
        try:
            status, _ = self._request("PUT", f"/{scope}/{key}",
                                      body=value.encode())
            return status == 200
        except OSError:
            if strict:
                raise
            return False

    def get(self, scope: str, key: str, strict: bool = False) -> Optional[str]:
        try:
            status, data = self._request("GET", f"/{scope}/{key}")
            return data.decode() if status == 200 else None
        except OSError:
            if strict:
                raise
            return None

    def delete(self, scope: str, key: str, strict: bool = False) -> bool:
        try:
            status, _ = self._request("DELETE", f"/{scope}/{key}")
            return status == 200
        except OSError:
            if strict:
                raise
            return False

    @staticmethod
    def _scan_path(scope: str, keys_only: bool, prefix: Optional[str]) -> str:
        import urllib.parse
        q = {}
        if keys_only:
            q["keys"] = "1"
        if prefix:
            q["prefix"] = prefix
        qs = f"?{urllib.parse.urlencode(q)}" if q else ""
        return f"/{scope}{qs}"

    def scan(self, scope: str, strict: bool = False, keys_only: bool = False,
             prefix: Optional[str] = None) -> Dict[str, Tuple[str, float]]:
        """{key: (value, age_seconds)} for the whole scope. ``keys_only``
        returns (None, age) pairs — presence/liveness without shipping
        values; ``prefix`` filters keys server-side."""
        try:
            status, data = self._request(
                "GET", self._scan_path(scope, keys_only, prefix))
            if status != 200:
                return {}
            parsed = json.loads(data.decode())
            return {k: (v[0], float(v[1])) for k, v in parsed.items()}
        except (OSError, ValueError):
            if strict:
                raise
            return {}
