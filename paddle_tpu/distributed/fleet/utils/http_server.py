"""Tiny HTTP key-value server — the cross-host rendezvous/elastic store.

Parity: the reference's Gloo HTTP store
(/root/reference/python/paddle/distributed/fleet/utils/http_server.py — a
BaseHTTPRequestHandler KV server used for barrier/rendezvous) and the etcd
registry of the elastic manager (fleet/elastic/manager.py:103). One tiny
server process (or thread on node 0) replaces both: keys live in memory
with write timestamps so clients implement TTL-based liveness.

Protocol (scope = job id):
  PUT    /<scope>/<key>   body = value        → store + stamp
  GET    /<scope>/<key>                       → value (404 if absent)
  DELETE /<scope>/<key>                       → remove
  GET    /<scope>                             → json {key: [value, age_sec]}
"""
from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

__all__ = ["KVServer", "KVClient"]


class _Handler(BaseHTTPRequestHandler):
    # `store`/`lock` are set per-server on a subclass (KVServer.__init__) —
    # a class-level store would cross-contaminate servers in one process
    store: Dict[str, Dict[str, Tuple[str, float]]]
    lock: threading.Lock

    def log_message(self, *args):  # quiet
        pass

    def _parts(self):
        path = self.path.split("?", 1)[0]
        parts = [p for p in path.split("/") if p]
        return (parts[0], parts[1]) if len(parts) >= 2 else (parts[0] if parts else "", None)

    def _query(self) -> Dict[str, str]:
        import urllib.parse
        if "?" not in self.path:
            return {}
        return {k: v[-1] for k, v in urllib.parse.parse_qs(
            self.path.split("?", 1)[1]).items()}

    def do_PUT(self):
        scope, key = self._parts()
        if key is None:
            self.send_response(400)
            self.end_headers()
            return
        n = int(self.headers.get("Content-Length", 0))
        val = self.rfile.read(n).decode()
        # monotonic stamps: key ages drive heartbeat-TTL liveness, and a
        # wall-clock step (NTP slew/adjtime) must never fake node death or
        # resurrect an expired one
        with self.lock:
            self.store.setdefault(scope, {})[key] = (val, time.monotonic())
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        scope, key = self._parts()
        with self.lock:
            bucket = dict(self.store.get(scope, {}))
        if key is None:
            now = time.monotonic()
            q = self._query()
            pfx = q.get("prefix", "")
            if pfx:
                bucket = {k: kv for k, kv in bucket.items()
                          if k.startswith(pfx)}
            if q.get("keys") == "1":
                # presence/age only: elastic poll loops scan every
                # iteration, and shipping each rank's full gradient blob
                # per poll turns a slow peer into an O(W^2 x blob) stampede
                body = json.dumps(
                    {k: [None, now - ts]
                     for k, (v, ts) in bucket.items()}).encode()
            else:
                body = json.dumps(
                    {k: [v, now - ts] for k, (v, ts) in bucket.items()}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        hit = bucket.get(key)
        if hit is None:
            self.send_response(404)
            self.end_headers()
            return
        body = hit[0].encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        scope, key = self._parts()
        with self.lock:
            self.store.get(scope, {}).pop(key, None)
        self.send_response(200)
        self.end_headers()


class KVServer:
    """In-process threaded KV server. ``with KVServer(port):`` or
    start()/stop()."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        handler = type("_BoundHandler", (_Handler,),
                       {"store": {}, "lock": threading.Lock()})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class KVClient:
    """Client for :class:`KVServer` (reference KVHandler http client role).

    Deliberately dumb: one attempt per call. With ``strict=True`` transport
    failures raise OSError so a caller's retry policy (the elastic store's
    backoff, resilience/retry.py) can distinguish "store down" from a
    legitimately absent key / empty scope; the default swallows them into
    False/None/{} for casual callers."""

    def __init__(self, addr: str, timeout: float = 5.0):
        self.addr = addr  # "host:port"
        self.timeout = timeout

    def _conn(self):
        host, port = self.addr.rsplit(":", 1)
        return http.client.HTTPConnection(host, int(port), timeout=self.timeout)

    def put(self, scope: str, key: str, value: str, strict: bool = False) -> bool:
        try:
            c = self._conn()
            c.request("PUT", f"/{scope}/{key}", body=value.encode())
            ok = c.getresponse().status == 200
            c.close()
            return ok
        except OSError:
            if strict:
                raise
            return False

    def get(self, scope: str, key: str, strict: bool = False) -> Optional[str]:
        try:
            c = self._conn()
            c.request("GET", f"/{scope}/{key}")
            r = c.getresponse()
            out = r.read().decode() if r.status == 200 else None
            c.close()
            return out
        except OSError:
            if strict:
                raise
            return None

    def delete(self, scope: str, key: str, strict: bool = False) -> bool:
        try:
            c = self._conn()
            c.request("DELETE", f"/{scope}/{key}")
            ok = c.getresponse().status == 200
            c.close()
            return ok
        except OSError:
            if strict:
                raise
            return False

    def scan(self, scope: str, strict: bool = False, keys_only: bool = False,
             prefix: Optional[str] = None) -> Dict[str, Tuple[str, float]]:
        """{key: (value, age_seconds)} for the whole scope. ``keys_only``
        returns (None, age) pairs — presence/liveness without shipping
        values; ``prefix`` filters keys server-side."""
        try:
            import urllib.parse
            q = {}
            if keys_only:
                q["keys"] = "1"
            if prefix:
                q["prefix"] = prefix
            qs = f"?{urllib.parse.urlencode(q)}" if q else ""
            c = self._conn()
            c.request("GET", f"/{scope}{qs}")
            r = c.getresponse()
            if r.status != 200:
                c.close()
                return {}
            data = json.loads(r.read().decode())
            c.close()
            return {k: (v[0], float(v[1])) for k, v in data.items()}
        except (OSError, ValueError):
            if strict:
                raise
            return {}
