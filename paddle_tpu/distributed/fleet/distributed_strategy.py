"""DistributedStrategy.

Parity: /root/reference/python/paddle/distributed/fleet/base/
distributed_strategy.py (protobuf-backed, framework/distributed_strategy.proto
message DistributedStrategy:176 with ~45 toggle+config properties: amp:403,
recompute:515, sharding:827, pipeline:1014, tensor_parallel:1078,
hybrid_configs:1133, localsgd:1167, dgc:1283, gradient_merge:1369, lars:1428,
lamb:1490, elastic:1549, auto:1565, a_sync:281).

TPU-native: a plain serializable config tree (JSON instead of prototxt — XLA
has no protobuf IR to share with). Every reference toggle is present; ones
with no TPU meaning are accepted and recorded so reference configs load
unchanged, and `effective()` reports how each lowers onto the mesh.
"""
from __future__ import annotations

import copy
import json
from typing import Any, Dict

__all__ = ["DistributedStrategy"]

_DEFAULTS: Dict[str, Any] = {
    # execution
    "a_sync": False,
    "a_sync_configs": {"k_steps": -1, "max_merge_var_num": 1, "send_queue_size": 16,
                       "independent_recv_thread": False, "thread_pool_size": 1,
                       "send_wait_times": 1, "runtime_split_send_recv": False},
    # amp
    "amp": False,
    "amp_configs": {"init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
                    "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0, "decr_ratio": 0.5,
                    "use_dynamic_loss_scaling": True, "use_pure_fp16": False,
                    "use_fp16_guard": True, "custom_white_list": [], "custom_black_list": [],
                    "custom_black_varnames": [], "dtype": "float16"},
    # recompute
    "recompute": False,
    "recompute_configs": {"checkpoints": [], "enable_offload": False, "checkpoint_shape": []},
    # pipeline
    "pipeline": False,
    "pipeline_configs": {"micro_batch_size": 1, "accumulate_steps": 1, "schedule_mode": "1F1B",
                         "p2p_cache_shape": True},
    # tensor parallel (static-mode config)
    "tensor_parallel": False,
    "tensor_parallel_configs": {"tensor_parallel_degree": 1, "tensor_init_seed": -1},
    # sharding (ZeRO)
    "sharding": False,
    "sharding_configs": {"sharding_segment_strategy": "segment_broadcast_MB",
                         "segment_broadcast_MB": 32.0, "segment_anchors": None,
                         "sharding_degree": 8, "mp_degree": 1, "dp_degree": 1,
                         "hybrid_dp": False, "gradient_merge_acc_step": 1,
                         "optimize_offload": False, "stage": 1,
                         "pp_degree": 1, "pp_allreduce_in_optimize": False,
                         "optimize_cast": False},
    # hybrid (dygraph-mode degrees)
    "hybrid_configs": {"dp_degree": -1, "mp_degree": 1, "pp_degree": 1,
                       "sharding_degree": 1, "sep_degree": 1, "ep_degree": 1},
    # gradient merge
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    # localsgd
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "adaptive_localsgd": False,
    "adaptive_localsgd_configs": {"init_k_steps": 1, "begin_step": 1},
    # dgc
    "dgc": False,
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1, "sparsity": [0.999]},
    # lars / lamb
    "lars": False,
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005, "epsilon": 0,
                     "exclude_from_weight_decay": []},
    "lamb": False,
    "lamb_configs": {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []},
    # misc toggles
    "fp16_allreduce": False,
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
    "fuse_grad_size_in_TFLOPS": 50,
    "nccl_comm_num": 1,
    "sync_nccl_allreduce": True,
    "use_hierarchical_allreduce": False,
    "hierarchical_allreduce_inter_nranks": 1,
    "sync_batch_norm": False,
    "fuse_all_optimizer_ops": False,
    "without_graph_optimization": False,
    "asp": False,
    "elastic": False,
    "auto": False,
    "semi_auto": False,
    "heter_ccl_mode": False,
    "cudnn_exhaustive_search": False,
    "cudnn_batchnorm_spatial_persistent": False,
    "conv_workspace_size_limit": 512,
    "find_unused_parameters": False,
    "last_comm_group_size_MB": 1,
    "qat": False,
    "qat_configs": {},
}


class DistributedStrategy:
    def __init__(self):
        self.__dict__["_cfg"] = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, name):
        cfg = self.__dict__["_cfg"]
        if name in cfg:
            return cfg[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        cfg = self.__dict__["_cfg"]
        if name not in cfg:
            raise ValueError(f"unknown DistributedStrategy field {name!r}")
        if name.endswith("_configs"):
            if not isinstance(value, dict):
                raise TypeError(f"{name} must be a dict")
            merged = dict(cfg[name])
            for k, v in value.items():
                merged[k] = v
            cfg[name] = merged
        else:
            cfg[name] = value

    # serialization (parity: save_to_prototxt/load_from_prototxt :146,164)
    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self._cfg)

    def save_to_prototxt(self, output: str):
        with open(output, "w") as f:
            json.dump(self._cfg, f, indent=2, default=str)

    def load_from_prototxt(self, pb_file: str):
        with open(pb_file) as f:
            loaded = json.load(f)
        for k, v in loaded.items():
            if k in self._cfg:
                self._cfg[k] = v

    def __repr__(self):
        on = [k for k, v in self._cfg.items() if v is True]
        return f"DistributedStrategy(enabled={on})"

    # TPU lowering summary -----------------------------------------------
    def effective(self) -> Dict[str, str]:
        """How each enabled toggle lowers onto the TPU mesh."""
        out = {}
        if self.amp:
            out["amp"] = f"dtype policy {self.amp_configs['dtype']} via paddle_tpu.amp"
        if self.recompute:
            out["recompute"] = "jax.checkpoint on declared segments"
        if self.pipeline:
            out["pipeline"] = "pp mesh axis + microbatch schedule"
        if self.sharding:
            out["sharding"] = f"ZeRO stage {self.sharding_configs['stage']} via fsdp axis sharding"
        if self.hybrid_configs["mp_degree"] > 1:
            out["mp"] = "weights sharded over 'mp' axis"
        if self.dgc:
            out["dgc"] = "top-k gradient compression before dp reduce"
        if self.localsgd:
            out["localsgd"] = "periodic param sync instead of per-step reduce"
        return out
