"""Fleet — the distributed-training front door.

Parity: /root/reference/python/paddle/distributed/fleet/base/fleet_base.py
(fleet.init:164, distributed_optimizer, minimize:1343 with the
MetaOptimizerFactory chain :1433-1466) and role_maker.py.

TPU-native: ``init`` builds the HybridCommunicateGroup (installing the global
mesh) from strategy.hybrid_configs. ``distributed_optimizer`` returns a
HybridParallelOptimizer that applies the strategy chain (amp → recompute →
sharding → dp) as transformations of ONE jitted train step — the
meta-optimizer pass pipeline collapses into function composition + sharding
annotations instead of program rewriting.
"""
from __future__ import annotations

import os
from typing import Optional

from ..env import ParallelEnv, get_rank, get_world_size, init_parallel_env
from ..topology import HybridCommunicateGroup
from .distributed_strategy import DistributedStrategy

__all__ = ["Fleet", "fleet"]


class RoleMakerBase:
    """Parity shim for PaddleCloudRoleMaker/UserDefinedRoleMaker — on TPU the
    runtime rendezvous replaces Gloo HTTP-store role negotiation
    (reference role_maker.py:35 class Gloo)."""

    def __init__(self, is_collective: bool = True, **kwargs):
        self._is_collective = is_collective
        self._env = ParallelEnv()

    def worker_num(self):
        return self._env.world_size

    def worker_index(self):
        return self._env.rank

    def is_worker(self):
        return True

    def is_server(self):
        return False


PaddleCloudRoleMaker = RoleMakerBase
UserDefinedRoleMaker = RoleMakerBase


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._user_defined_optimizer = None
        self._initialized = False

    # ------------------------------------------------------------------
    def init(self, role_maker=None, is_collective: bool = True, strategy: Optional[DistributedStrategy] = None):
        init_parallel_env()
        self._role_maker = role_maker or RoleMakerBase(is_collective)
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        world = get_world_size()
        import jax

        n_dev = len(jax.devices()) if world == 1 else world
        dp = hc["dp_degree"]
        mp, pp, sh, sep = hc["mp_degree"], hc["pp_degree"], hc["sharding_degree"], hc.get("sep_degree", 1)
        if dp == -1:
            denom = mp * pp * sh * sep
            dp = max(1, n_dev // denom)
        self._hcg = HybridCommunicateGroup(
            dp_degree=dp, mp_degree=mp, pp_degree=pp, sharding_degree=sh, sep_degree=sep
        )
        if self._strategy.tensor_parallel_configs.get("tensor_init_seed", -1) != -1:
            from ...random import get_rng_state_tracker

            tracker = get_rng_state_tracker()
            tracker.reset()
            seed = self._strategy.tensor_parallel_configs["tensor_init_seed"]
            tracker.add("global_seed", seed)
            tracker.add(tracker.MODEL_PARALLEL_RNG, seed + 1 + self._hcg.get_model_parallel_rank())
        self._initialized = True
        return self

    def is_initialized(self):
        return self._initialized

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        if self._hcg is None:
            raise RuntimeError("fleet.init() has not been called")
        return self._hcg

    @property
    def strategy(self):
        return self._strategy

    # worker info ------------------------------------------------------
    def worker_num(self):
        return get_world_size()

    def worker_index(self):
        return get_rank()

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return get_rank() == 0

    def worker_endpoints(self, to_string=False):
        eps = ParallelEnv().trainer_endpoints
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from ..collective import barrier

        barrier()

    def server_num(self):
        """PS servers don't exist on the TPU build (parity: fleet.server_num
        — the embedding-table role is mesh-sharded, README out-of-scope)."""
        return 0

    def init_worker(self):
        """PS worker init is a no-op on the collective TPU build."""

    def init_server(self, *args, **kwargs):
        raise RuntimeError(
            "parameter-server mode is out of scope on the TPU build "
            "(README); use collective training over the mesh")

    def run_server(self):
        raise RuntimeError(
            "parameter-server mode is out of scope on the TPU build "
            "(README); use collective training over the mesh")

    def stop_worker(self):
        """No persistent PS workers to stop (collective mode)."""

    def save_persistables(self, executor, dirname, main_program=None, mode=0):
        """Parity: fleet.save_persistables — static program-state save."""
        from ...static.compat import save as static_save

        if main_program is None:
            from ...static.program import default_main_program

            main_program = default_main_program()
        import os

        static_save(main_program, os.path.join(dirname, "fleet_ckpt"))

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True, mode=0):
        """Parity: fleet.save_inference_model — name strings are resolved to
        the program's feed Variables, then exported via StableHLO."""
        import os

        from ...static import save_inference_model as sim
        from ...static.program import default_main_program

        prog = main_program or default_main_program()
        by_name = dict(getattr(prog, "feed_vars", {}))
        feed_vars = [by_name[n] if isinstance(n, str) else n
                     for n in (feeded_var_names or [])]
        if not feed_vars:
            raise ValueError("feeded_var_names must name at least one feed")
        sim(os.path.join(dirname, "model"), feed_vars, target_vars, executor)

    # model/optimizer wrapping ----------------------------------------
    def distributed_model(self, model):
        """Parity: fleet.distributed_model — wraps by parallel mode."""
        from ..meta_parallel.pipeline_parallel import PipelineLayer, PipelineParallel
        from ..parallel import DataParallel
        from ..topology import ParallelMode

        hcg = self.get_hybrid_communicate_group()
        if isinstance(model, PipelineLayer) or hcg.get_pipe_parallel_world_size() > 1:
            return PipelineParallel(model, hcg, self._strategy)
        if hcg.get_model_parallel_world_size() > 1:
            from ..meta_parallel.tensor_parallel import TensorParallel

            return TensorParallel(model, hcg, strategy=self._strategy)
        return DataParallel(model, self._strategy)

    def distributed_optimizer(self, optimizer, strategy: Optional[DistributedStrategy] = None):
        if strategy is not None:
            self._strategy = strategy
        self._user_defined_optimizer = optimizer

        # strategy-selected meta-optimizers (parity: MetaOptimizerFactory
        # chain, fleet_base.py:1433 — each _can_apply'd rewrite wraps/replaces
        # the user optimizer before the hybrid wrapper)
        s = self._strategy
        if s is not None and getattr(s, "dgc", False):
            from ..meta_optimizers import DGCMomentum
            from ...optimizer.optimizers import Momentum

            if isinstance(optimizer, Momentum) and not isinstance(optimizer, DGCMomentum):
                cfg = dict(getattr(s, "dgc_configs", {}) or {})
                optimizer = DGCMomentum(
                    learning_rate=optimizer._learning_rate,
                    momentum=optimizer._momentum,
                    parameters=optimizer._parameter_list,
                    use_nesterov=optimizer._use_nesterov,
                    rampup_begin_step=cfg.get("rampup_begin_step", 0),
                    rampup_step=cfg.get("rampup_step", 1),
                    sparsity=cfg.get("sparsity", [0.999]),
                    weight_decay=optimizer._weight_decay_coeff or None,
                    grad_clip=optimizer._grad_clip,
                )
        if s is not None and getattr(s, "localsgd", False):
            from ..meta_optimizers import LocalSGDOptimizer

            cfg = dict(getattr(s, "localsgd_configs", {}) or {})
            optimizer = LocalSGDOptimizer(
                optimizer, k_steps=cfg.get("k_steps", 1),
                begin_step=cfg.get("begin_step", 1),
            )
        elif s is not None and getattr(s, "adaptive_localsgd", False):
            from ..meta_optimizers import AdaptiveLocalSGDOptimizer

            cfg = dict(getattr(s, "adaptive_localsgd_configs", {}) or {})
            optimizer = AdaptiveLocalSGDOptimizer(
                optimizer, init_k_steps=cfg.get("init_k_steps", 1),
                begin_step=cfg.get("begin_step", 1),
            )

        from ..meta_parallel.hybrid_optimizer import HybridParallelOptimizer

        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    # checkpoint surface lives above (save_persistables / save_inference_model)

    @property
    def util(self):
        """Shared UtilBase (reference exposes a module-level singleton)."""
        if not hasattr(self, "_util"):
            self._util = UtilBase()
        return self._util

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        """Dygraph parity path: backward + hybrid step."""
        opt = self._user_defined_optimizer
        loss.backward()
        opt.step()
        return None, []


fleet = Fleet()


class UtilBase:
    """fleet.util parity (reference fleet/base/util_factory.py): small
    cross-worker helpers over the collective API."""

    def all_reduce(self, input, mode="sum"):  # noqa: A002
        import numpy as np

        from ..collective import all_reduce as _ar
        from ...tensor import Tensor

        import jax.numpy as jnp

        t = input if isinstance(input, Tensor) else Tensor(jnp.asarray(np.asarray(input)))
        from ..group import ReduceOp

        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX, "min": ReduceOp.MIN}[mode]
        return _ar(t, op=op)

    def barrier(self):
        from ..collective import barrier

        barrier()

    def all_gather(self, input):  # noqa: A002
        import numpy as np

        import jax.numpy as jnp

        from ..collective import all_gather as _ag
        from ...tensor import Tensor

        t = input if isinstance(input, Tensor) else Tensor(jnp.asarray(np.asarray(input)))
        out = []
        _ag(out, t)
        return out

    def get_file_shard(self, files):
        """Contiguous even split of a file list across workers (reference
        util_factory.get_file_shard: blocks, remainder to the first ranks)."""
        from ..env import get_rank, get_world_size

        n, r = get_world_size(), get_rank()
        base, rem = divmod(len(files), n)
        begin = r * base + min(r, rem)
        end = begin + base + (1 if r < rem else 0)
        return list(files[begin:end])

    def print_on_rank(self, message, rank_id=0):
        from ..env import get_rank

        if get_rank() == rank_id:
            print(message)

