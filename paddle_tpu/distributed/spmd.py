"""SPMD execution helpers — the bridge between the dygraph API and
mesh-parallel XLA programs.

Parity role: this file replaces the reference's entire executor-side
distributed machinery — ParallelExecutor SSA graphs
(/root/reference/paddle/fluid/framework/parallel_executor.cc:639), the
meta-optimizer program rewrites, and comm-op insertion. One ``shard_map``
over the global mesh + XLA GSPMD does all of it at compile time.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..tensor import Tensor
from .env import get_mesh

P = PartitionSpec

__all__ = ["P", "PartitionSpec", "run_on_mesh", "shard_array", "sanitize_spec", "with_sharding_constraint", "shard_tensor_to", "replicate"]


def run_on_mesh(fn: Callable, in_specs, out_specs, mesh: Optional[Mesh] = None, jit: bool = True):
    """shard_map ``fn`` over the (global) mesh. Inside ``fn``, the
    paddle_tpu.distributed collectives resolve their group axis names."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("no global mesh; call distributed.init_mesh or fleet.init first")
    mapped = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    return jax.jit(mapped) if jit else mapped


def shard_array(x, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """Place an array/Tensor on the mesh with the given PartitionSpec."""
    mesh = mesh or get_mesh()
    arr = x._data if isinstance(x, Tensor) else x
    sharded = jax.device_put(arr, NamedSharding(mesh, spec))
    if isinstance(x, Tensor):
        x._set_data(sharded)
        return x
    return sharded


def replicate(x, mesh: Optional[Mesh] = None):
    return shard_array(x, P(), mesh)


def sanitize_spec(spec: PartitionSpec, mesh) -> PartitionSpec:
    """Drop spec axes the mesh doesn't have (e.g. 'mp' annotations on a
    dp-only mesh) so any model runs under any topology."""
    axes = set(mesh.shape)
    dims = []
    for d in spec:
        if d is None:
            dims.append(None)
        elif isinstance(d, str):
            dims.append(d if d in axes else None)
        else:
            kept = tuple(a for a in d if a in axes)
            dims.append(kept if kept else None)
    return PartitionSpec(*dims)


def with_sharding_constraint(x, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """In-jit resharding hint (≙ auto_parallel shard_tensor annotation).

    Axes the mesh lacks are dropped from the spec (and the call is a no-op
    without a mesh) so model code can annotate unconditionally and still
    run under any topology.
    """
    mesh = mesh or get_mesh()
    if mesh is None:
        return x
    spec = sanitize_spec(spec, mesh)
    arr = x._data if isinstance(x, Tensor) else x
    out = jax.lax.with_sharding_constraint(arr, NamedSharding(mesh, spec))
    return Tensor(out) if isinstance(x, Tensor) else out


def shard_tensor_to(tensor, mesh, placements):
    """dist.shard_tensor parity shim (auto_parallel/interface.py:295)."""
    return shard_array(tensor, placements if isinstance(placements, PartitionSpec) else P(*placements), mesh)
