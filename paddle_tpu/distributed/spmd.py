"""SPMD execution helpers — the bridge between the dygraph API and
mesh-parallel XLA programs.

Parity role: this file replaces the reference's entire executor-side
distributed machinery — ParallelExecutor SSA graphs
(/root/reference/paddle/fluid/framework/parallel_executor.cc:639), the
meta-optimizer program rewrites, and comm-op insertion. One ``shard_map``
over the global mesh + XLA GSPMD does all of it at compile time.
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..tensor import Tensor
from .env import get_mesh

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _jax_shard_map
except ImportError:  # older jax: experimental module
    from jax.experimental.shard_map import shard_map as _jax_shard_map

# the replication-check kwarg was renamed check_rep -> check_vma; detect
# which one this jax spells so every call site can say check_vma
_VMA_KW = next((k for k in ("check_vma", "check_rep")
                if k in inspect.signature(_jax_shard_map).parameters), None)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False, **kw):
    """Version-portable ``jax.shard_map``: accepts the current ``check_vma``
    spelling and forwards it as whatever this jax calls it."""
    if _VMA_KW is not None:
        kw[_VMA_KW] = check_vma
    return _jax_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)


P = PartitionSpec

__all__ = ["P", "PartitionSpec", "run_on_mesh", "shard_array", "sanitize_spec", "with_sharding_constraint", "shard_tensor_to", "replicate", "shard_map"]


def run_on_mesh(fn: Callable, in_specs, out_specs, mesh: Optional[Mesh] = None, jit: bool = True):
    """shard_map ``fn`` over the (global) mesh. Inside ``fn``, the
    paddle_tpu.distributed collectives resolve their group axis names."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("no global mesh; call distributed.init_mesh or fleet.init first")
    mapped = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    return jax.jit(mapped) if jit else mapped


def shard_array(x, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """Place an array/Tensor on the mesh with the given PartitionSpec."""
    mesh = mesh or get_mesh()
    arr = x._data if isinstance(x, Tensor) else x
    sharded = jax.device_put(arr, NamedSharding(mesh, spec))
    if isinstance(x, Tensor):
        x._set_data(sharded)
        return x
    return sharded


def replicate(x, mesh: Optional[Mesh] = None):
    return shard_array(x, P(), mesh)


def sanitize_spec(spec: PartitionSpec, mesh) -> PartitionSpec:
    """Drop spec axes the mesh doesn't have (e.g. 'mp' annotations on a
    dp-only mesh) so any model runs under any topology."""
    axes = set(mesh.shape)
    dims = []
    for d in spec:
        if d is None:
            dims.append(None)
        elif isinstance(d, str):
            dims.append(d if d in axes else None)
        else:
            kept = tuple(a for a in d if a in axes)
            dims.append(kept if kept else None)
    return PartitionSpec(*dims)


def with_sharding_constraint(x, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """In-jit resharding hint (≙ auto_parallel shard_tensor annotation).

    Axes the mesh lacks are dropped from the spec (and the call is a no-op
    without a mesh) so model code can annotate unconditionally and still
    run under any topology.
    """
    mesh = mesh or get_mesh()
    if mesh is None:
        return x
    spec = sanitize_spec(spec, mesh)
    arr = x._data if isinstance(x, Tensor) else x
    out = jax.lax.with_sharding_constraint(arr, NamedSharding(mesh, spec))
    return Tensor(out) if isinstance(x, Tensor) else out


def shard_tensor_to(tensor, mesh, placements):
    """dist.shard_tensor parity shim (auto_parallel/interface.py:295)."""
    return shard_array(tensor, placements if isinstance(placements, PartitionSpec) else P(*placements), mesh)
