"""Process groups and ReduceOp.

Parity: the reference's comm-group model — ``new_group`` / ``Group``
(python/paddle/distributed/collective.py:120 Group, :209 new_group) where a
group wraps an NCCL ring (``ring_id``).

TPU-native: a Group wraps a **mesh axis name** (or an explicit rank list) on
the global jax device mesh. Where the reference exchanges nccl ids over TCP
(c_gen_nccl_id_op.cc) and creates comms per ring
(platform/collective_helper.cc:102), here XLA materializes the collective
over the named axis at compile time — there is no id exchange and no stream
management.
"""
from __future__ import annotations

from typing import List, Optional

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "destroy_process_group"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communication group = a named mesh axis (TPU) and the rank list."""

    _next_id = 0

    def __init__(self, ranks: Optional[List[int]] = None, axis_name: Optional[str] = None, id: Optional[int] = None):  # noqa: A002
        if id is None:
            Group._next_id += 1
            id = Group._next_id  # noqa: A001
        self.id = id
        self.axis_name = axis_name
        self.ranks = ranks if ranks is not None else []

    @property
    def nranks(self) -> int:
        if self.ranks:
            return len(self.ranks)
        if self.axis_name is not None:
            from .env import _axis_size

            return _axis_size(self.axis_name)
        # default (world) group: every device of the installed mesh is a
        # rank (single-controller SPMD); fall back to the process world
        from .env import get_mesh, get_world_size

        mesh = get_mesh()
        if mesh is not None:
            return int(mesh.size)
        return get_world_size()

    @property
    def world_size(self) -> int:
        return self.nranks

    def get_group_rank(self, rank: int) -> int:
        if not self.ranks:
            return rank
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def name(self):
        return f"group_{self.id}" if self.axis_name is None else self.axis_name

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis_name}, ranks={self.ranks})"


_groups = {}
_default_group: Optional[Group] = None


def _set_default_group(g: Group):
    global _default_group
    _default_group = g
    _groups[0] = g


def get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        _default_group = Group(id=0, axis_name=None)
        _groups[0] = _default_group
    return _default_group


def new_group(ranks: Optional[List[int]] = None, backend=None, axis_name: Optional[str] = None) -> Group:
    """Parity: paddle.distributed.new_group. ``axis_name`` is the TPU-native
    extension: bind the group to a mesh axis for use inside shard_map."""
    g = Group(ranks=ranks, axis_name=axis_name)
    _groups[g.id] = g
    return g


def get_group(gid: int) -> Optional[Group]:
    return _groups.get(gid)


def destroy_process_group(group: Optional[Group] = None):
    global _default_group
    if group is None:
        _groups.clear()
        _default_group = None
    else:
        _groups.pop(group.id, None)
