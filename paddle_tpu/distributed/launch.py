"""Launcher CLI: ``python -m paddle_tpu.distributed.launch train.py args...``

Parity: /root/reference/python/paddle/distributed/fleet/launch.py (:611
launch region) + launch_utils.py (:466 start_local_trainers, :490-501 env
protocol, watch_local_trainers child monitoring). The env contract
(PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS) is preserved so reference launch scripts port
unchanged; device selection uses TPU visible chips.

TPU-native notes: on a TPU pod each HOST runs one process that owns its local
chips (single-controller-per-host), so nproc_per_node defaults to 1 with all
local chips visible — unlike the reference's one-proc-per-GPU. The elastic
path (restart on membership change) is in paddle_tpu.distributed.elastic.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "get_cluster_from_args", "start_local_trainers", "watch_local_trainers", "terminate_local_procs"]


class TrainerProc:
    def __init__(self, proc, rank, log_fn=None):
        self.proc = proc
        self.rank = rank
        self.log_fn = log_fn


def find_free_ports(num: int) -> List[int]:
    import socket

    ports = []
    socks = []
    for _ in range(num):
        s = socket.socket()
        s.bind(("", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def get_cluster_from_args(args):
    ips = args.ips.split(",")
    nproc = args.nproc_per_node
    ports = find_free_ports(nproc) if len(ips) == 1 else [args.start_port + i for i in range(nproc)]
    endpoints = []
    for ip in ips:
        for p in ports:
            endpoints.append(f"{ip}:{p}")
    return endpoints


def start_local_trainers(endpoints: List[str], node_rank: int, nproc_per_node: int,
                         training_script: str, training_script_args: List[str],
                         log_dir: Optional[str] = None, envs=None) -> List[TrainerProc]:
    procs = []
    world = len(endpoints)
    for local_rank in range(nproc_per_node):
        rank = node_rank * nproc_per_node + local_rank
        env = dict(os.environ)
        env.update(envs or {})
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "FLAGS_selected_tpus": str(local_rank),
        })
        cmd = [sys.executable, "-u", training_script] + list(training_script_args)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            fout = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
            proc = subprocess.Popen(cmd, env=env, stdout=fout, stderr=subprocess.STDOUT)
        else:
            fout = None
            proc = subprocess.Popen(cmd, env=env)
        procs.append(TrainerProc(proc, rank, fout))
    return procs


def watch_local_trainers(procs: List[TrainerProc]) -> bool:
    """Returns True while all children are healthy; raises on abnormal exit
    (parity: launch_utils.py watch_local_trainers)."""
    alive = False
    for tp in procs:
        ret = tp.proc.poll()
        if ret is None:
            alive = True
        elif ret != 0:
            terminate_local_procs(procs)
            raise RuntimeError(f"trainer rank {tp.rank} exited with code {ret}")
    return alive


def terminate_local_procs(procs: List[TrainerProc]):
    for tp in procs:
        if tp.proc.poll() is None:
            tp.proc.terminate()
    deadline = time.time() + 10
    for tp in procs:
        try:
            tp.proc.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            tp.proc.kill()
        if tp.log_fn:
            tp.log_fn.close()


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--ips", default="127.0.0.1", help="comma-separated host ips")
    p.add_argument("--nproc_per_node", type=int,
                   default=int(os.getenv("PADDLE_TPU_NPROC_PER_NODE", "1")))
    p.add_argument("--node_rank", type=int, default=int(os.getenv("PADDLE_NODE_RANK", "0")))
    p.add_argument("--start_port", type=int, default=6070)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--elastic_level", type=int, default=-1)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv=None):
    args = _parse_args(argv)
    endpoints = get_cluster_from_args(args)
    procs = start_local_trainers(
        endpoints, args.node_rank, args.nproc_per_node,
        args.training_script, args.training_script_args, args.log_dir,
    )

    def handler(signum, frame):
        terminate_local_procs(procs)
        sys.exit(1)

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
    try:
        while watch_local_trainers(procs):
            time.sleep(1)
    finally:
        terminate_local_procs(procs)


if __name__ == "__main__":
    launch()
