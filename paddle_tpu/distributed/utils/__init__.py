"""paddle.distributed.utils parity — MoE all-to-all primitives.

Parity: the reference's ``global_scatter``/``global_gather`` ops
(/root/reference/paddle/fluid/operators/collective/global_scatter_op.cc:19-28,
global_scatter_op.cu.cc; python surface
/root/reference/python/paddle/distributed/utils.py) — the expert-parallel
dispatch pair that routes rows of ``x`` to the ranks owning each expert and
back.

TPU-native redesign: the reference sends *variable* per-expert row counts
(local_count/global_count) over NCCL. XLA requires static shapes, so the
TPU-native form is the GShard capacity-padded layout: ``x`` is
``[n_expert_global * capacity, d]`` ordered by global expert id, and the
exchange is one ``lax.all_to_all`` over the 'ep' mesh axis. ``local_count`` /
``global_count`` are accepted for API parity and may be used for masking by
callers; the exchange itself is count-free.
"""
from __future__ import annotations

from typing import Optional

from jax import lax

from ...ops._primitive import unwrap as _unwrap
from ...tensor import Tensor
from ..collective import _axis_bound as _bound
from ..group import Group, get_default_group

__all__ = ["global_scatter", "global_gather"]

EP_AXIS = "ep"


def _axis(group: Optional[Group]):
    if group is not None and group.axis_name:
        return group.axis_name
    return EP_AXIS


def _exchange(x, axis_name):
    """One tiled all_to_all on the leading (global-expert) dimension.

    Input rows on each shard are grouped by destination rank (outer) —
    ``[world * rows_per_rank, d]``; output rows are grouped by source rank.
    This single collective is both global_scatter and global_gather (the op
    is an involution up to the grouping dimension's meaning).
    """
    n = lax.axis_size(axis_name)
    if x.shape[0] % n != 0:
        raise ValueError(
            f"global_scatter/gather input leading dim {x.shape[0]} must be "
            f"divisible by the expert-parallel world size {n} "
            f"(capacity-padded layout)"
        )
    return lax.all_to_all(
        x.reshape((n, x.shape[0] // n) + x.shape[1:]),
        axis_name, split_axis=0, concat_axis=0, tiled=True,
    ).reshape(x.shape)


def global_scatter(x, local_count=None, global_count=None, group: Optional[Group] = None, use_calc_stream: bool = True):
    """Route expert-grouped rows to the ranks owning each expert."""
    arr = _unwrap(x)
    axis_name = _axis(group)
    if _bound(axis_name):
        out = _exchange(arr, axis_name)
        return Tensor(out) if isinstance(x, Tensor) else out
    g = group or get_default_group()
    if g is None or g.nranks <= 1:
        return x
    raise RuntimeError("eager global_scatter over a >1 group requires a mesh context")


def global_gather(x, local_count=None, global_count=None, group: Optional[Group] = None, use_calc_stream: bool = True):
    """Inverse of :func:`global_scatter` — return expert outputs to the ranks
    that dispatched the corresponding tokens."""
    return global_scatter(x, global_count, local_count, group, use_calc_stream)
