"""paddle_tpu.distributed — collectives, mesh, fleet, parallel wrappers.

Parity: python/paddle/distributed/ in the reference (collective.py comm API,
fleet/, launch, spawn, ParallelEnv) re-grounded on one jax.sharding.Mesh.
"""
from . import fleet  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import utils  # noqa: F401
from . import launch as launch_module  # noqa: F401
from .collective import (  # noqa: F401
    all_gather_object,
    irecv,
    isend,
    all_gather,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    split_group_axis,
    wait,
)
from .env import (  # noqa: F401
    ParallelEnv,
    clear_mesh,
    get_mesh,
    get_rank,
    get_world_size,
    init_mesh,
    init_parallel_env,
    set_mesh,
)
from .group import Group, ReduceOp, destroy_process_group, get_group, new_group  # noqa: F401
from .parallel import DataParallel, scale_loss  # noqa: F401
from .parallel_trainer import ParallelTrainer  # noqa: F401
from .spmd import (  # noqa: F401
    P,
    PartitionSpec,
    replicate,
    run_on_mesh,
    shard_array,
    shard_tensor_to,
    with_sharding_constraint,
)
from .topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode  # noqa: F401

# auto-parallel front door (parity: auto_parallel/interface.py shard_tensor).
# The full ProcessMesh/shard_tensor/shard_op/Engine surface lives in
# distributed.auto_parallel; this top-level alias keeps the mesh+placements
# convenience form working.
from . import auto_parallel  # noqa: F401,E402

shard_tensor = shard_tensor_to


def spawn(func, args=(), nprocs: int = -1, join: bool = True, **kwargs):
    """Parity: paddle.distributed.spawn (spawn.py). Multi-process spawn with
    the launcher env contract."""
    import multiprocessing as mp
    import os

    from .launch import find_free_ports

    if nprocs == -1:
        nprocs = 1
    ports = find_free_ports(nprocs)
    endpoints = [f"127.0.0.1:{p}" for p in ports]

    def _target(rank):
        os.environ.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        })
        func(*args)

    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_target, args=(r,)) for r in range(nprocs)]
    for p in procs:
        p.start()
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned process exited with {p.exitcode}")
    return procs


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """Parity: paddle.distributed.split (collective.py:1233) — builds
    row/column-parallel linear or vocab-parallel embedding."""
    from .meta_parallel import ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding

    if operation == "linear":
        in_f, out_f = size
        if axis == 0:
            layer = RowParallelLinear(in_f, out_f, weight_attr=weight_attr, bias_attr=bias_attr)
        else:
            layer = ColumnParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                         gather_output=gather_out, bias_attr=bias_attr)
        return layer(x)
    if operation == "embedding":
        n, d = size
        layer = VocabParallelEmbedding(n, d, weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported operation {operation}")


def get_backend() -> str:
    return "xla"  # the only backend: XLA collectives over ICI/DCN


is_initialized = lambda: True  # noqa: E731
