"""Analytic auto-parallel planner — the cost-model role the reference fills
with python/paddle/distributed/auto_parallel/cost_model.py + planner.py
(profiling-based per-op costs feeding a strategy search).

TPU-native redesign: instead of profiling per-op costs on a ProgramDesc
graph, the planner scores (dp, mp, pp, ZeRO-stage, microbatch) candidates
with the standard TPU scaling model (jax-ml.github.io/scaling-book):

- compute:  6 * N * tokens_per_device / peak_flops
- dp comm:  2 * grad_bytes / ici_bw (ring allreduce ≈ 2x payload)
- mp comm:  2 allreduces of the activation block per layer per microbatch
- pp:       bubble factor (pp-1)/(m + pp - 1) multiplies compute
- memory:   params + grads + optimizer state (ZeRO divides by dp) +
            activation working set (with/without remat)

Every candidate that fits HBM is kept with its full cost/memory breakdown
(`Plan.candidates`) so users get DIAGNOSTICS, not just a winner — the gap
VERDICT r3 called out for the annotation-only front door.

Since planner v2 (``paddle_tpu.analysis.plan``) this constant model is the
**fast-path prior and fallback**: :func:`plan_strategy_v2` runs the
static-analysis-driven search — every candidate's actual trainer step is
lowered to a ShapeDtypeStruct jaxpr and priced by the liveness peak-HBM
estimator + roofline cost model — and uses the constants below only to
order the lowering queue and to price candidates the host cannot lower
(pp pipelines, meshes wider than the local device count).
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Optional

__all__ = ["ModelStats", "Plan", "Candidate", "plan_strategy",
           "plan_strategy_v2",
           "GRAD_FACTOR_ALIASED", "GRAD_FACTOR_HELD",
           "ACT_BYTES_PER_ELEMENT_LAYER", "OVERLAP_TAX",
           "ALLREDUCE_RING_FACTOR"]

# ---------------------------------------------------------------------------
# calibrated model constants — exposed by name so the analysis layer's
# planner-drift cross-check (analysis/memory.planner_drift_findings, r10)
# and future re-calibrations reference ONE definition:
#
#: grad bytes as a fraction of param bytes when the jitted step's donated
#: buffers + fused update alias the grad storage (ADVICE r5 #2)
GRAD_FACTOR_ALIASED = 0.5
#: ... and when a separate accumulator survives the step (gradient
#: accumulation / pipeline microbatching / non-fused optimizers)
GRAD_FACTOR_HELD = 1.0
#: live activation bytes per element per layer at bf16 — bounded by the
#: 760m-b8-no-remat config FITTING (≤ 10.5); XLA fusion keeps fewer live
#: intermediates than the naive 18/element transformer count
ACT_BYTES_PER_ELEMENT_LAYER = 10
#: fraction of comm time NOT hidden under compute (imperfect overlap)
OVERLAP_TAX = 0.2
#: ring allreduce moves ~2x the payload across the slowest link
ALLREDUCE_RING_FACTOR = 2


@dataclasses.dataclass
class ModelStats:
    """What the cost model needs to know about the network."""

    n_params: int
    n_layers: int
    hidden: int
    seq_len: int
    param_bytes: int = 4       # f32 masters
    moment_bytes: int = 4      # 2 Adam moments of this dtype (total = 2x)
    act_bytes: int = 2         # bf16 activations

    @classmethod
    def from_gpt_config(cls, cfg, seq_len: Optional[int] = None,
                        moment_dtype: str = "float32"):
        h, l, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
        n = 12 * l * h * h + v * h + getattr(
            cfg, "max_position_embeddings", 0) * h
        return cls(n_params=int(n), n_layers=int(l), hidden=int(h),
                   seq_len=int(seq_len or getattr(cfg, "max_position_embeddings", 1024)),
                   moment_bytes=2 if "b" in moment_dtype else 4)


@dataclasses.dataclass
class Candidate:
    dp: int
    mp: int
    pp: int
    zero_stage: int
    microbatches: int
    recompute: bool
    mem_bytes: float
    step_time_s: float
    mem_breakdown: dict
    time_breakdown: dict

    @property
    def axes(self) -> dict:
        out = {}
        if self.pp > 1:
            out["pp"] = self.pp
        if self.mp > 1:
            out["mp"] = self.mp
        if self.dp > 1:
            out["sharding" if self.zero_stage >= 1 else "dp"] = self.dp
        return out or {"dp": 1}


@dataclasses.dataclass
class Plan:
    best: Candidate
    candidates: List[Candidate]

    def explain(self) -> str:
        """Human-readable diagnostics table (the reference planner logs its
        search; completion here = showing every scored candidate)."""
        lines = ["dp mp pp zero m remat   mem(GB)  step(ms)  fits"]
        for c in sorted(self.candidates, key=lambda c: c.step_time_s):
            lines.append(
                f"{c.dp:2d} {c.mp:2d} {c.pp:2d} {c.zero_stage:4d} "
                f"{c.microbatches:1d} {str(c.recompute):5s} "
                f"{c.mem_bytes / 1e9:8.2f} {c.step_time_s * 1e3:9.2f}  yes")
        return "\n".join(lines)


def plan_strategy_v2(cfg, n_devices: int, global_batch: int, **kwargs):
    """The v2 front door: static-analysis-driven search over lowered
    candidate steps (see :func:`paddle_tpu.analysis.plan.plan_gpt` for the
    full keyword surface — device spec, budget, moment dtype,
    ``max_lowered``).  Takes a :class:`~paddle_tpu.models.gpt.GPTConfig`
    (the search lowers real model programs, so the analytic
    :class:`ModelStats` summary is not enough) and returns an
    :class:`~paddle_tpu.analysis.plan.PlanV2`."""
    from ...analysis.plan import plan_gpt

    return plan_gpt(cfg, n_devices, global_batch, **kwargs)


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def plan_strategy(stats: ModelStats, n_devices: int, global_batch: int,
                  hbm_bytes: float = 16e9, peak_flops: float = 197e12,
                  ici_bytes_per_s: float = 4.5e10,
                  mfu_guess: float = 0.55,
                  accumulate_steps: int = 1,
                  fused_grad_buffers: bool = True) -> Plan:
    """Enumerate (dp, mp, pp, zero, microbatch, remat) candidates, drop the
    ones whose memory model exceeds ``hbm_bytes``, and rank the rest by
    modeled step time. Raises with the full infeasible table when nothing
    fits (so the user sees WHY).

    ``accumulate_steps``/``fused_grad_buffers`` gate the grad-memory factor
    (ADVICE r5 #2): the calibrated 0.5x grad bytes hold only when the
    jitted step's donated buffers + fused update alias the grad storage —
    a single fused step with no held accumulator. Gradient accumulation
    (user-level ``accumulate_steps`` > 1, or a pipeline candidate's
    microbatch loop, whose grad tree persists across the scan) and
    non-fused optimizer paths keep a SEPARATE full grad buffer: 1.0x."""
    n = stats.n_params
    cands: List[Candidate] = []
    infeasible: List[str] = []
    for mp in _divisors(n_devices):
        if stats.hidden % mp:
            continue
        for pp in _divisors(n_devices // mp):
            if stats.n_layers % pp:
                continue
            dp = n_devices // (mp * pp)
            if global_batch % dp:
                continue
            for zero in ((0, 1, 2, 3) if dp > 1 else (0,)):
                # every combination is realizable: flat meshes via
                # ParallelTrainer (GSPMD + fsdp), pp > 1 via the pipeline
                # step (ZeRO-2 slots / sharding_stage=3 params)
                for m in (1, 2, 4) if pp > 1 else (1,):
                    if (global_batch // dp) % m:
                        continue
                    # pp > 1 always holds a grad accumulator across the
                    # tick scan (any m); pp == 1 aliases only when the
                    # step is a single fused microbatch
                    aliased = (fused_grad_buffers
                               and int(accumulate_steps) <= 1 and pp == 1)
                    for recompute in (False, True):
                        c = _score(stats, n, dp, mp, pp, zero, m, recompute,
                                   global_batch, hbm_bytes, peak_flops,
                                   ici_bytes_per_s, mfu_guess,
                                   grad_factor=(GRAD_FACTOR_ALIASED
                                                if aliased
                                                else GRAD_FACTOR_HELD))
                        if c.mem_bytes <= hbm_bytes:
                            cands.append(c)
                        else:
                            infeasible.append(
                                f"dp{dp} mp{mp} pp{pp} zero{zero} m{m} "
                                f"remat={recompute}: "
                                f"{c.mem_bytes / 1e9:.1f} GB > "
                                f"{hbm_bytes / 1e9:.1f} GB")
    if not cands:
        raise ValueError(
            "no parallel strategy fits HBM; infeasible candidates:\n"
            + "\n".join(infeasible[:20]))
    best = min(cands, key=lambda c: c.step_time_s)
    return Plan(best=best, candidates=cands)


def _score(stats, n, dp, mp, pp, zero, m, recompute, global_batch,
           hbm_bytes, peak_flops, ici_bw, mfu_guess,
           grad_factor=GRAD_FACTOR_ALIASED):
    shard = mp * pp           # param split over model axes
    b_local = global_batch // dp
    b_micro = b_local // m
    t = stats.seq_len
    h = stats.hidden
    layers_local = stats.n_layers // pp

    # --- memory model (bytes/device), constants CALIBRATED against the
    # repo's own single-chip measurements (benchmarks/sweep_r5.jsonl +
    # sweep_r3/r4, see test_auto_parallel TestPlannerValidation):
    #  - grads: ``grad_factor`` x the param bytes — 0.5x when donated
    #    buffers + the fused update alias the grad storage (the measured
    #    1.3B b4 remat config runs in 5.3 GB params + 5.3 GB moments +
    #    remat activations; a full f32 grad copy would not fit), 1.0x
    #    when a separate accumulator survives the step (gradient
    #    accumulation / pipeline microbatching / non-fused optimizers —
    #    ADVICE r5 #2)
    #  - activations: 10 bytes/element/layer at bf16 — bounded by
    #    760m-b8-no-remat FITTING (≤ 10.5) and XLA fusion keeping fewer
    #    live intermediates than the naive 18/element transformer count
    p_shard = n / shard
    params = p_shard * stats.param_bytes
    if zero >= 3:
        params /= dp
    grads = grad_factor * p_shard * stats.param_bytes / (dp if zero >= 2 else 1)
    moments = 2 * p_shard * stats.moment_bytes / (dp if zero >= 1 else 1)
    act_per_layer = (ACT_BYTES_PER_ELEMENT_LAYER * b_micro * t * (h / mp)
                     * stats.act_bytes)
    live_layers = 2 if recompute else layers_local
    acts = act_per_layer * live_layers * (1 if pp == 1 else min(m, pp))
    mem = params + grads + moments + acts

    # --- time model (seconds/step) ---
    tokens_dev = (global_batch * t) / dp
    flops = 6 * n / shard * tokens_dev * (4 / 3 if recompute else 1)
    compute = flops / (peak_flops * mfu_guess)
    bubble = (pp - 1) / (m + pp - 1) if pp > 1 else 0.0
    compute = compute / (1 - bubble) if bubble < 1 else float("inf")
    dp_comm = (ALLREDUCE_RING_FACTOR * p_shard * stats.param_bytes
               / ici_bw) if dp > 1 else 0.0
    mp_comm = (4 * layers_local * m * b_micro * t * (h / 1) * stats.act_bytes
               / ici_bw) if mp > 1 else 0.0
    zero3_comm = (ALLREDUCE_RING_FACTOR * p_shard * stats.param_bytes
                  / ici_bw) if zero >= 3 else 0.0
    step = max(compute, dp_comm + mp_comm + zero3_comm) \
        + OVERLAP_TAX * (dp_comm + mp_comm + zero3_comm)
    return Candidate(
        dp=dp, mp=mp, pp=pp, zero_stage=zero, microbatches=m,
        recompute=recompute, mem_bytes=mem, step_time_s=step,
        mem_breakdown={"params": params, "grads": grads, "moments": moments,
                       "activations": acts},
        time_breakdown={"compute": compute, "dp_comm": dp_comm,
                        "mp_comm": mp_comm, "zero3_comm": zero3_comm,
                        "bubble": bubble},
    )
