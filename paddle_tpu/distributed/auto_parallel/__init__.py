"""Semi-automatic parallelism front door.

Parity: python/paddle/distributed/auto_parallel (reference interface.py —
ProcessMesh:71, shard_tensor:295, shard_op; completion.py dist-attr
propagation:410; partitioner.py SPMD program split:39; reshard.py:480).

TPU-native redesign: this subsystem IS jax's GSPMD. ProcessMesh wraps
``jax.sharding.Mesh``; ``shard_tensor`` annotations become NamedShardings
(inside jit: ``with_sharding_constraint``); the reference's completion pass
(dist-attr propagation through the graph), Partitioner (per-rank program
split) and reshard.py (send/recv insertion) are exactly what XLA's sharding
propagation + SPMD partitioner do during compilation, so they need no code
here — ``parallelize`` just jits the program with in/out shardings.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...tensor import Tensor

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "Engine",
           "ModelStats", "Plan", "plan_strategy", "plan_strategy_v2"]


def __getattr__(name):
    if name in ("ModelStats", "Plan", "Candidate", "plan_strategy",
                "plan_strategy_v2"):
        from . import planner

        return getattr(planner, name)
    raise AttributeError(name)


class ProcessMesh:
    """Parity: auto_parallel ProcessMesh (interface.py:71) — an N-D array of
    process ranks with named dimensions; backed by a jax Mesh."""

    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 parent=None):
        arr = np.asarray(mesh)
        self.topology = list(arr.shape)
        self.processes = [int(x) for x in arr.ravel()]
        self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devs = np.asarray(jax.devices())
        if arr.size > devs.size:
            raise ValueError(
                f"ProcessMesh wants {arr.size} processes, have {devs.size} devices"
            )
        self._jax_mesh = Mesh(
            devs[np.asarray(self.processes)].reshape(arr.shape),
            tuple(self.dim_names),
        )

    @property
    def shape(self):
        return list(self.topology)

    @property
    def ndim(self):
        return len(self.topology)

    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def __repr__(self):
        return f"ProcessMesh(shape={self.topology}, dim_names={self.dim_names})"


def _spec_from(process_mesh: ProcessMesh, dims_mapping_or_names) -> P:
    """Accept either reference-style dims_mapping (list of mesh-dim indices
    per tensor axis, -1 = replicated) or axis-name placements."""
    entries = []
    for d in dims_mapping_or_names:
        if d is None or d == -1:
            entries.append(None)
        elif isinstance(d, int):
            entries.append(process_mesh.dim_names[d])
        else:
            entries.append(d)
    return P(*entries)


def shard_tensor(x, process_mesh: ProcessMesh = None, shard_spec=None,
                 dist_attr=None):
    """Annotate ``x`` with a sharding (parity: interface.py shard_tensor:295).

    ``shard_spec``: per-axis mesh dim name / index / None. Outside jit the
    array is re-placed immediately; inside jit this lowers to a sharding
    constraint that GSPMD propagates.
    """
    if dist_attr is not None:  # legacy dict form {"process_mesh":…, "dims_mapping":…}
        process_mesh = dist_attr.get("process_mesh", process_mesh)
        shard_spec = dist_attr.get("dims_mapping", shard_spec)
    if process_mesh is None or shard_spec is None:
        raise ValueError("shard_tensor needs a process_mesh and shard_spec")
    mesh = process_mesh.jax_mesh()
    spec = _spec_from(process_mesh, shard_spec)
    sharding = NamedSharding(mesh, spec)

    if isinstance(x, Tensor):
        # route through a taped primitive so autograd flows THROUGH the
        # re-placement (device_put is differentiable; its vjp is identity)
        from ...ops._primitive import primitive

        @primitive(name="shard_tensor")
        def _shard(t):
            if isinstance(t, jax.core.Tracer):
                return jax.lax.with_sharding_constraint(t, sharding)
            return jax.device_put(t, sharding)

        return _shard(x)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def shard_op(op_fn, process_mesh: ProcessMesh = None, in_shard_specs=None,
             out_shard_specs=None):
    """Annotate an op's inputs/outputs (parity: interface.py shard_op).
    Returns a wrapped callable applying the constraints."""

    def wrapped(*args, **kwargs):
        if process_mesh is not None and in_shard_specs is not None:
            args = tuple(
                shard_tensor(a, process_mesh, s) if s is not None else a
                for a, s in zip(args, list(in_shard_specs) + [None] * len(args))
            )
        out = op_fn(*args, **kwargs)
        if process_mesh is not None and out_shard_specs is not None:
            outs = out if isinstance(out, (tuple, list)) else (out,)
            outs = tuple(
                shard_tensor(o, process_mesh, s) if s is not None else o
                for o, s in zip(outs, list(out_shard_specs) + [None] * len(outs))
            )
            out = outs if isinstance(out, (tuple, list)) else outs[0]
        return out

    return wrapped


class Engine:
    """Minimal auto-parallel Engine (parity: the v2.2+ AutoParallelizer /
    Engine orchestration, parallelizer.py:27): jit a train step whose
    parameters and data follow their shard_tensor annotations — XLA's
    sharding propagation performs the reference's completion+partition+
    reshard passes at compile time."""

    def __init__(self, model, loss_fn, optimizer, process_mesh: ProcessMesh):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = process_mesh
        self.plan = None

    @classmethod
    def auto(cls, model, loss_fn, optimizer, *, global_batch: int,
             seq_len: Optional[int] = None, n_devices: Optional[int] = None,
             hbm_bytes: float = 16e9):
        """Cost-model-planned Engine (the reference planner/cost_model role,
        auto_parallel/cost_model.py): picks (dp, mp, pp, ZeRO, remat)
        analytically and builds the mesh. ``engine.plan.explain()`` shows
        every scored candidate."""
        from .planner import ModelStats, plan_strategy

        cfg = getattr(getattr(model, "gpt", model), "config", None)
        if cfg is None:
            raise ValueError("Engine.auto needs a model with a .config "
                             "(GPT family); pass ModelStats to "
                             "plan_strategy directly otherwise")
        stats = ModelStats.from_gpt_config(cfg, seq_len=seq_len)
        n_dev = n_devices or len(jax.devices())
        plan = plan_strategy(stats, n_dev, global_batch, hbm_bytes=hbm_bytes)
        axes = plan.best.axes
        dims = list(axes.keys())
        shape = [axes[d] for d in dims]
        pm = ProcessMesh(np.arange(int(np.prod(shape))).reshape(shape), dims)
        eng = cls(model, loss_fn, optimizer, pm)
        eng.plan = plan
        return eng

    def fit_step(self):
        """Build the executor that REALIZES the plan: ParallelTrainer for
        flat (dp/mp/sharding) meshes — GSPMD shards mp-annotated params
        automatically — or the ppermute pipeline step when the plan chose
        pp > 1 (with its ZeRO-2 slots / ZeRO-3 sharded stage params)."""
        from ..env import set_mesh
        from ..parallel_trainer import ParallelTrainer

        set_mesh(self.mesh.jax_mesh())
        names = self.mesh.dim_names
        best = self.plan.best if self.plan is not None else None
        if best is not None and best.pp > 1:
            from ...models.gpt import GPTForPretraining
            from ..meta_parallel.pipeline_schedule import (
                build_gpt_pipeline_step,
            )

            if not isinstance(self.model, GPTForPretraining):
                raise NotImplementedError(
                    "planned pp > 1 needs the GPT pipeline step; wrap your "
                    "model as a PipelineModule or re-plan with pp=1 "
                    "(pass n_devices/hbm accordingly)")
            stepfn = build_gpt_pipeline_step(
                self.model, self.optimizer,
                microbatches=best.microbatches,
                sharding_stage=3 if best.zero_stage >= 3 else 2)
            stepfn.step = stepfn  # trainer-interface alias
            return stepfn
        # model axes must NEVER be used as the batch axis: dp falls back to
        # None (single-replica) when the plan is pure model parallelism
        dp_axis = next((n for n in names if n in ("dp", "sharding")), None)
        fsdp = None
        if best is not None and best.zero_stage >= 3 and "sharding" in names:
            fsdp = "sharding"
        return ParallelTrainer(
            self.model, self.loss_fn, self.optimizer,
            dp_axis=dp_axis, fsdp_axis=fsdp,
        )
