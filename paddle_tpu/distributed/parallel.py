"""DataParallel.

Parity: ``paddle.DataParallel`` (fluid/dygraph/parallel.py:389) + the C++
``Reducer`` gradient-bucketing engine
(/root/reference/paddle/fluid/imperative/reducer.cc — InitializeGroups,
MarkVarReady, FusedAllReduceSchedule).

TPU-native redesign: **there is no reducer.** Under SPMD, parameters are
replicated over the 'dp' mesh axis and the batch is sharded; XLA inserts one
fused all-reduce for every gradient at compile time, already bucketed and
overlapped with the backward pass — which is exactly what the 1122-line C++
Reducer hand-builds at runtime. DataParallel therefore:
- installs input sharding (batch over 'dp') via a forward pre-hook,
- constrains parameters to replicated,
- exposes the reference surface (scale_loss, no_sync, state_dict passthrough).
The cross-rank gradient sync the reference does eagerly is what pjit's
compiled backward does implicitly; the eager fallback (`apply_collective_grads`)
pmeans grads inside a shard_map for the few users who train un-jitted.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax

from ..nn.layer import Layer
from ..tensor import Tensor
from .env import get_mesh
from .group import Group
from .spmd import P, shard_array, with_sharding_constraint

__all__ = ["DataParallel", "scale_loss"]


def scale_loss(loss, world_size: Optional[int] = None):
    """Parity: parallel.py scale_loss — 1/nranks scaling before backward."""
    if world_size is None:
        from .env import get_world_size

        world_size = get_world_size()
    if world_size <= 1:
        return loss
    return loss / world_size


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int = 25,
                 last_comm_buffer_size: int = 1, find_unused_parameters: bool = False,
                 group: Optional[Group] = None):
        super().__init__()
        self._layers = layers
        self._group = group
        self._grad_sync_enabled = True
        mesh = get_mesh()
        self._dp_axis = (group.axis_name if group else None) or (
            "dp" if mesh is not None and "dp" in mesh.shape else None
        )
        if mesh is not None and self._dp_axis is not None:
            # replicate parameters across dp (jax array placement)
            for _, p in layers.named_parameters():
                if not isinstance(p._data, jax.core.Tracer):
                    shard_array(p, P())

    def forward(self, *inputs, **kwargs):
        mesh = get_mesh()
        if mesh is not None and self._dp_axis is not None:
            sharded = []
            for x in inputs:
                if isinstance(x, Tensor) and x.ndim >= 1 and not isinstance(x._data, jax.core.Tracer):
                    sharded.append(shard_array(x, P(self._dp_axis)))
                else:
                    sharded.append(x)
            inputs = tuple(sharded)
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Parity: DataParallel.no_sync — grads accumulate locally. Under
        SPMD this is only meaningful for the eager shard_map path."""
        prev = self._grad_sync_enabled
        self._grad_sync_enabled = False
        try:
            yield
        finally:
            self._grad_sync_enabled = prev

    def apply_collective_grads(self):
        """Eager fallback ≙ fused_allreduce_gradients
        (fleet/utils/hybrid_parallel_util.py:118): pmean every .grad over dp.
        No-op when world is 1 or grads already synced by a jitted step."""
        if not self._grad_sync_enabled:
            return
        mesh = get_mesh()
        if mesh is None or self._dp_axis is None or mesh.shape.get(self._dp_axis, 1) <= 1:
            return
        from .spmd import run_on_mesh

        grads = [p.grad for p in self._layers.parameters() if p.grad is not None]
        if not grads:
            return
        axis = self._dp_axis

        def pmean_all(*gs):
            return tuple(jax.lax.pmean(g, axis) for g in gs)

        spec = tuple(P() for _ in grads)
        fn = run_on_mesh(pmean_all, in_specs=spec, out_specs=spec)
        outs = fn(*[g._data for g in grads])
        for g, o in zip(grads, outs):
            g._set_data(o)

    # surface passthrough ------------------------------------------------
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def scale_loss(self, loss):
        return loss  # SPMD pmean handles scaling; kept for API parity

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__.get("_sub_layers", {}).get("_layers"), name)
