"""paddle_tpu.distribution — probability distributions.

Parity: python/paddle/distribution.py in the reference (__all__:39 —
Distribution, Uniform, Normal, Categorical; sample/entropy/log_prob/probs/
kl_divergence surface), which lowers to distribution ops
(uniform_random/gaussian_random kernels).

TPU-native redesign: sampling draws from the framework's seeded global PRNG
(paddle_tpu.random.split_key) so results are reproducible under paddle.seed
and TP-rank aware; densities are pure jnp expressions that fuse under jit.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .ops._primitive import unwrap, wrap
from .random import split_key
from .tensor import Tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical", "kl_divergence"]


def _arr(v, dtype=jnp.float32):
    if isinstance(v, Tensor):
        return v._data
    return jnp.asarray(np.asarray(v), dtype)


class Distribution:
    """Abstract base (reference distribution.py Distribution)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) with broadcastable batch shape."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.low.shape, self.high.shape)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self._batch
        u = jax.random.uniform(split_key(), shape, jnp.float32)
        return wrap(self.low + u * (self.high - self.low))

    def entropy(self):
        return wrap(jnp.broadcast_to(jnp.log(self.high - self.low), self._batch))

    def log_prob(self, value):
        v = unwrap(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return wrap(jnp.where(inside, lp, -jnp.inf))

    def probs(self, value):
        return wrap(jnp.exp(unwrap(self.log_prob(value))))


class Normal(Distribution):
    """N(loc, scale^2) with broadcastable batch shape."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(self.loc.shape, self.scale.shape)

    @property
    def mean(self):
        return wrap(jnp.broadcast_to(self.loc, self._batch))

    @property
    def variance(self):
        return wrap(jnp.broadcast_to(self.scale * self.scale, self._batch))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self._batch
        z = jax.random.normal(split_key(), shape, jnp.float32)
        return wrap(self.loc + z * self.scale)

    def entropy(self):
        ent = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return wrap(jnp.broadcast_to(ent, self._batch))

    def log_prob(self, value):
        v = unwrap(value)
        var = self.scale * self.scale
        return wrap(-((v - self.loc) ** 2) / (2 * var)
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def probs(self, value):
        return wrap(jnp.exp(unwrap(self.log_prob(value))))

    def kl_divergence(self, other):
        """KL(self || other) between two Normals (reference kl formula)."""
        if not isinstance(other, Normal):
            raise TypeError("kl_divergence expects another Normal")
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return wrap(0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio)))


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference Categorical —
    constructed from `logits`, sampling proportional to softmax)."""

    def __init__(self, logits, name=None):
        self.logits = _arr(logits)

    def _probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        return wrap(jax.random.categorical(
            split_key(), self.logits, shape=tuple(shape) + self.logits.shape[:-1]))

    def entropy(self):
        p = self._probs()
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return wrap(-(p * logp).sum(-1))

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        idx = unwrap(value).astype(jnp.int32)
        if logp.ndim == 1:
            return wrap(jnp.take(logp, idx))
        return wrap(jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return wrap(jnp.exp(unwrap(self.log_prob(value))))

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise TypeError("kl_divergence expects another Categorical")
        p = self._probs()
        return wrap((p * (jax.nn.log_softmax(self.logits, -1)
                          - jax.nn.log_softmax(other.logits, -1))).sum(-1))


def kl_divergence(p: Distribution, q: Distribution):
    """Dispatching KL (reference paddle.distribution.kl_divergence)."""
    return p.kl_divergence(q)
