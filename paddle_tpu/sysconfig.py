"""paddle.sysconfig parity: include/lib dirs of the native core."""
import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    return os.path.join(_PKG, "core", "native")


def get_lib() -> str:
    return os.path.join(_PKG, "core", "native")
