"""paddle_tpu.jit — static-graph acceleration + model export.

Parity: the reference's @to_static / jit.save / jit.load stack
(/root/reference/python/paddle/fluid/dygraph/jit.py:529,901 and the 25-file
AST transpiler in fluid/dygraph/dygraph_to_static/).

TPU-native redesign: there is no AST transpiler. ``to_static`` traces the
eager function ONCE per input signature with jax.jit (XLA compiles and caches
it); autograd still works — the whole compiled forward becomes a single tape
node via jax.vjp. Python control flow must be trace-compatible (jax
semantics: use lax.cond/scan for data-dependent branches) — this constraint
replaces the reference's ProgramTranslator machinery and is what makes the
result a single fused XLA program instead of an op-by-op interpreter loop.

``save``/``load`` export the traced function as serialized StableHLO
(jax.export) + a params archive — the pdmodel/pdiparams equivalent.
"""
from .static_function import StaticFunction, to_static, not_to_static  # noqa: F401
from .save_load import load, save, TranslatedLayer  # noqa: F401
from .input_spec import InputSpec  # noqa: F401

__all__ = ["to_static", "not_to_static", "StaticFunction", "save", "load", "InputSpec", "TranslatedLayer"]
