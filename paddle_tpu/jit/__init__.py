"""paddle_tpu.jit — static-graph acceleration + model export.

Parity: the reference's @to_static / jit.save / jit.load stack
(/root/reference/python/paddle/fluid/dygraph/jit.py:529,901 and the 25-file
AST transpiler in fluid/dygraph/dygraph_to_static/).

TPU-native redesign: there is no AST transpiler. ``to_static`` traces the
eager function ONCE per input signature with jax.jit (XLA compiles and caches
it); autograd still works — the whole compiled forward becomes a single tape
node via jax.vjp. Python control flow must be trace-compatible (jax
semantics: use lax.cond/scan for data-dependent branches) — this constraint
replaces the reference's ProgramTranslator machinery and is what makes the
result a single fused XLA program instead of an op-by-op interpreter loop.

``save``/``load`` export the traced function as serialized StableHLO
(jax.export) + a params archive — the pdmodel/pdiparams equivalent.
"""
from .dy2static import checked  # noqa: F401
from .static_function import StaticFunction, to_static, not_to_static  # noqa: F401
from .save_load import load, save, TranslatedLayer  # noqa: F401
from .input_spec import InputSpec  # noqa: F401

__all__ = ["to_static", "not_to_static", "StaticFunction", "save", "load", "InputSpec", "TranslatedLayer", "checked"]


class TracedLayer:
    """Parity: fluid.dygraph.TracedLayer — trace a dygraph layer into a
    static (jitted) callable with save_inference_model support. Here tracing
    IS to_static, so the class wraps a StaticFunction of the layer."""

    def __init__(self, layer, static_fn, example_inputs):
        self._layer = layer
        self._fn = static_fn
        self._example_inputs = example_inputs

    @staticmethod
    def trace(layer, inputs):
        from .static_function import to_static

        fn = to_static(lambda *xs: layer(*xs))
        outs = fn(*inputs)
        return outs, TracedLayer(layer, fn, inputs)

    def __call__(self, *inputs):
        return self._fn(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        from .input_spec import InputSpec
        from .save_load import save as jit_save

        # derive the spec from the traced example inputs
        spec = [InputSpec(shape=list(t.shape), dtype=str(t.dtype))
                for t in self._example_inputs]
        jit_save(self._layer, path, input_spec=spec)


def set_code_level(level=100):
    """Parity: paddle.jit.set_code_level — the AST-transpiler debug dial.
    This build traces instead of transpiling; the call records the level."""
    import logging

    logging.getLogger("paddle_tpu.jit").setLevel(logging.DEBUG if level else logging.INFO)


def set_verbosity(level=0, also_to_stdout=False):
    """Parity: paddle.jit.set_verbosity."""
    import logging

    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level else logging.WARNING)
