"""StaticFunction — the @to_static engine.

Parity: fluid/dygraph/dygraph_to_static/program_translator.py
(StaticFunction.__call__:302, ConcreteProgram cached by CacheKey:144).
TPU-native: a ConcreteProgram is a jax.jit-compiled pure function; CacheKey is
(input shapes/dtypes, static-arg values, training flag). Autograd
integration: the whole compiled forward is one tape Node (jax.vjp over the
pure function), so ``loss.backward()`` after a jitted forward costs exactly
XLA's fused backward pass — there is no per-op interpreter loop on the TPU.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import tape
from ..random import get_rng_state, set_rng_state, split_key
from ..tensor import Tensor

__all__ = ["StaticFunction", "to_static", "not_to_static"]


def _is_tensor(x):
    return isinstance(x, Tensor)


def _is_traced_leaf(x):
    return isinstance(x, (Tensor, jax.Array, np.ndarray))


class StaticFunction:
    """Callable wrapper that traces/compiles per input signature."""

    def __init__(self, fn: Callable, input_spec=None, layer=None):
        self._fn = fn
        self._input_spec = input_spec
        self._layer = layer
        self._cache: Dict[Any, Tuple] = {}
        try:
            functools.wraps(fn)(self)
        except Exception:
            pass

    @property
    def _bound_layer(self):
        if self._layer is not None:
            return self._layer
        return getattr(self._fn, "__self__", None)

    def __get__(self, instance, owner):
        # support decorating methods: bind to the instance as layer
        if instance is None:
            return self
        bound = StaticFunction(self._fn.__get__(instance, owner), self._input_spec)
        return bound

    def __call__(self, *args, **kwargs):
        flat, treedef = jax.tree_util.tree_flatten(args, is_leaf=_is_tensor)
        traced_pos = [i for i, x in enumerate(flat) if _is_traced_leaf(x)]
        arrays = [
            flat[i]._data if _is_tensor(flat[i]) else jnp.asarray(flat[i]) for i in traced_pos
        ]
        static_leaves = tuple(
            (i, repr(x)) for i, x in enumerate(flat) if not _is_traced_leaf(x)
        )
        kwargs_static = tuple(sorted((k, repr(v)) for k, v in kwargs.items()))
        layer = self._bound_layer
        training = layer.training if layer is not None else True
        key = (
            tuple((tuple(a.shape), str(a.dtype)) for a in arrays),
            treedef,
            static_leaves,
            kwargs_static,
            training,
        )

        entry = self._cache.get(key)
        if entry is None:
            entry = self._build(flat, treedef, traced_pos, kwargs)
            self._cache[key] = entry
        jitted, cell = entry

        if layer is not None:
            param_tensors = dict(layer.named_parameters())
            buffer_tensors = dict(layer.named_buffers())
        else:
            param_tensors, buffer_tensors = {}, {}
        params_tree = {n: p._data for n, p in param_tensors.items()}
        buffers_tree = {n: b._data for n, b in buffer_tensors.items()}
        rng_key = split_key()

        need_grad = tape.is_grad_enabled() and (
            any(not p.stop_gradient for p in param_tensors.values())
            or any(
                _is_tensor(flat[i]) and not flat[i].stop_gradient for i in traced_pos
            )
        )

        if not need_grad:
            out_arrays, new_buffers = jitted(params_tree, buffers_tree, rng_key, *arrays)
            self._write_buffers(buffer_tensors, new_buffers)
            outs = [Tensor(a) for a in out_arrays]
            return jax.tree_util.tree_unflatten(cell["out_treedef"], outs)

        diff_names = [
            n for n, p in param_tensors.items()
            if not p.stop_gradient and jnp.issubdtype(p._data.dtype, jnp.inexact)
        ]
        diff_arr_idx = [
            j for j, i in enumerate(traced_pos)
            if _is_tensor(flat[i]) and not flat[i].stop_gradient
            and jnp.issubdtype(arrays[j].dtype, jnp.inexact)
        ]
        nondiff_params = {n: a for n, a in params_tree.items() if n not in diff_names}

        def diff_fn(diff_params, *diff_xs):
            full = dict(nondiff_params)
            full.update(diff_params)
            xs = list(arrays)
            for j, a in zip(diff_arr_idx, diff_xs):
                xs[j] = a
            return jitted(full, buffers_tree, rng_key, *xs)

        diff_params = {n: params_tree[n] for n in diff_names}
        diff_xs = [arrays[j] for j in diff_arr_idx]
        out_arrays, vjp_fn, new_buffers = jax.vjp(diff_fn, diff_params, *diff_xs, has_aux=True)
        self._write_buffers(buffer_tensors, new_buffers)

        input_tensors = [param_tensors[n] for n in diff_names] + [
            flat[traced_pos[j]] for j in diff_arr_idx
        ]

        def tape_vjp(out_cots):
            cots = out_cots if isinstance(out_cots, tuple) else (out_cots,)
            dparams, *dxs = vjp_fn(tuple(cots))
            return tuple(dparams[n] for n in diff_names) + tuple(dxs)

        n_params = len(diff_names)

        def pure_positional(*arrs):
            """Re-differentiable form for create_graph: the same jitted pure
            call over positional (param..., x...) arrays (double grad
            re-enters jax.vjp of this)."""
            dp = {n: a for n, a in zip(diff_names, arrs[:n_params])}
            return diff_fn(dp, *arrs[n_params:])

        node = tape.Node(
            tape_vjp,
            input_tensors,
            [(a.shape, a.dtype) for a in out_arrays],
            name=f"jit:{getattr(self._fn, '__name__', 'fn')}",
            pure_fn=pure_positional,
            has_aux=True,  # diff_fn returns (out_arrays, new_buffers)
            tuple_out=True,
        )
        outs = []
        for pos, a in enumerate(out_arrays):
            t = Tensor(a, stop_gradient=False)
            t._node = node
            t._out_idx = pos
            outs.append(t)
        return jax.tree_util.tree_unflatten(cell["out_treedef"], outs)

    def _build(self, flat_template, treedef, traced_pos, kwargs):
        from .dy2static import convert_function

        layer = self._bound_layer
        # dygraph-to-static AST pass: tensor-dependent if/while become
        # lax.cond/lax.while_loop (reference program_translator.py:768)
        fn = convert_function(self._fn)
        cell: Dict[str, Any] = {}
        static_flat = [
            None if i in set(traced_pos) else x for i, x in enumerate(flat_template)
        ]

        def pure(params_tree, buffers_tree, rng_key, *xs):
            saved = get_rng_state()
            set_rng_state(rng_key)
            try:
                with tape.no_grad():
                    flat2 = list(static_flat)
                    for i, x in zip(traced_pos, xs):
                        flat2[i] = Tensor(x)
                    args = jax.tree_util.tree_unflatten(treedef, flat2)
                    if layer is not None:
                        out, new_buffers = layer.functional_call_with_state(
                            params_tree, buffers_tree, *args, _call_fn=fn, **kwargs
                        )
                    else:
                        out = fn(*args, **kwargs)
                        new_buffers = {}
            finally:
                set_rng_state(saved)
            out_flat, out_treedef = jax.tree_util.tree_flatten(out, is_leaf=_is_tensor)
            cell["out_treedef"] = out_treedef
            out_arrays = tuple(
                o._data if _is_tensor(o) else jnp.asarray(o) for o in out_flat
            )
            return out_arrays, new_buffers

        return jax.jit(pure), cell

    @staticmethod
    def _write_buffers(buffer_tensors, new_buffers):
        for n, arr in new_buffers.items():
            if n in buffer_tensors:
                buffer_tensors[n]._set_data(arr)

    @property
    def code(self):
        import inspect

        return inspect.getsource(self._fn)

    def rollback(self):
        return self._fn


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    """Decorator / wrapper. ``build_strategy`` accepted for parity, unused —
    XLA owns fusion decisions (reference BuildStrategy, pybind.cc:2692)."""

    def deco(fn):
        from ..nn.layer import Layer

        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, layer=fn)
            object.__setattr__(fn, "forward", sf)
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    fn._not_to_static = True
    return fn
