"""dygraph-to-static AST conversion: data-dependent Python ``if``/``while``
on Tensors become ``lax.cond`` / ``lax.while_loop`` under ``@to_static``.

Parity: the reference's 25-file AST transpiler
(/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:768 ProgramTranslator + ifelse/loop transformers).
TPU-native scope: a deliberately minimal, CONSERVATIVE pass —

- an ``if``/``while`` is rewritten only when its body is expressible as a
  pure closure: simple name assignments, no return/break/continue/yield.
  Anything else keeps the original Python statement (which still works for
  concrete predicates and raises jax's tracer error for traced ones).
- rewritten constructs dispatch at RUN time: concrete predicates take the
  plain Python path (bit-identical semantics), traced predicates lower to
  ``lax.cond``/``lax.while_loop``.

This covers the reference dygraph_to_static test shapes (tensor-valued
if/else assignment, counting/accumulating while loops) without attempting
the full transpiler; unconvertible control flow keeps a teachable error.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Set

__all__ = ["convert_function", "pd_cond", "pd_while"]


# ---------------------------------------------------------------------------
# runtime dispatch helpers (injected as globals into converted functions)
# ---------------------------------------------------------------------------
def _is_traced(x):
    import jax

    return isinstance(x, jax.core.Tracer)


def _pred_value(pred):
    from ..tensor import Tensor

    return pred._data if isinstance(pred, Tensor) else pred


class _Undefined:
    """Sentinel for names possibly unbound at the control-flow site
    (reference dygraph_to_static UndefinedVar role). Merely holding it is
    fine (the original code would simply leave the name unbound); USING it
    raises the UnboundLocalError the untransformed code would have raised."""

    __slots__ = ()

    def __repr__(self):
        return "<pd-undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "variable was left undefined by the untaken branch of a "
            "converted if/else (assign it on both paths)")

    __bool__ = __add__ = __radd__ = __sub__ = __rsub__ = _raise
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _raise
    __getitem__ = __iter__ = __len__ = __float__ = __int__ = _raise
    __call__ = __array__ = __matmul__ = __neg__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = _raise


UNDEFINED = _Undefined()


def pd_cond(pred, true_fn, false_fn, args=()):
    """if/else dispatch: Python for concrete preds, lax.cond for traced."""
    import numpy as np

    p = _pred_value(pred)
    if not _is_traced(p):
        return true_fn(*args) if bool(np.asarray(p).reshape(())) else false_fn(*args)
    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor

    cell = {}

    def wrap(fn):
        def f(_):
            out = fn(*args)
            flat, tree = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            cell.setdefault("tree", tree)
            arrs = []
            for x in flat:
                if isinstance(x, _Undefined):
                    raise ValueError(
                        "a tensor-dependent if/else leaves a variable "
                        "undefined on one branch; assign it on both paths "
                        "(lax.cond requires matching branch outputs)")
                arrs.append(x._data if isinstance(x, Tensor) else jnp.asarray(x))
            return tuple(arrs)

        return f

    res = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                       wrap(true_fn), wrap(false_fn), ())
    from ..tensor import Tensor as T

    return jax.tree_util.tree_unflatten(cell["tree"], [T(a) for a in res])


def pd_while(cond_fn, body_fn, init):
    """while dispatch: Python loop for concrete conds, lax.while_loop for
    traced. ``init`` is the tuple of loop-carried values (all tensor-like);
    their shapes/dtypes must be loop-invariant on the traced path."""
    import numpy as np

    from ..tensor import Tensor

    p0 = _pred_value(cond_fn(*init))
    if not _is_traced(p0):
        vals = tuple(init)
        while bool(np.asarray(_pred_value(cond_fn(*vals))).reshape(())):
            vals = tuple(body_fn(*vals))
        return vals
    import jax
    import jax.numpy as jnp

    def unwrap_all(vals):
        return tuple(v._data if isinstance(v, Tensor) else jnp.asarray(v)
                     for v in vals)

    def wrap_all(arrs):
        return tuple(Tensor(a) for a in arrs)

    def c(carry):
        return jnp.reshape(_pred_value(cond_fn(*wrap_all(carry))), ()).astype(bool)

    def b(carry):
        return unwrap_all(body_fn(*wrap_all(carry)))

    out = jax.lax.while_loop(c, b, unwrap_all(init))
    return wrap_all(out)


# ---------------------------------------------------------------------------
# the AST pass
# ---------------------------------------------------------------------------
def _assigned_names(stmts: List[ast.stmt]) -> Optional[Set[str]]:
    """Names simply assigned in the statement list; None = unconvertible."""
    names: Set[str] = set()
    for st in ast.walk(ast.Module(body=list(stmts), type_ignores=[])):
        if isinstance(st, (ast.Return, ast.Break, ast.Continue, ast.Yield,
                           ast.YieldFrom, ast.Global, ast.Nonlocal,
                           ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Try, ast.With, ast.Raise)):
            return None
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Tuple) and all(
                        isinstance(e, ast.Name) for e in t.elts):
                    names.update(e.id for e in t.elts)
                else:
                    return None
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(st.target, ast.Name):
                names.add(st.target.id)
            else:
                return None
        elif isinstance(st, ast.NamedExpr):
            if isinstance(st.target, ast.Name):
                names.add(st.target.id)
            else:
                return None
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            t = st.target
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, ast.Tuple) and all(
                    isinstance(e, ast.Name) for e in t.elts):
                names.update(e.id for e in t.elts)
            else:
                return None
    return names


def _loaded_names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _load_counts(node: ast.AST):
    from collections import Counter

    return Counter(n.id for n in ast.walk(node)
                   if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load))


def _fn_locals(fdef) -> Set[str]:
    """All names that are locals of the function (args + any assignment)."""
    out = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args
                           + fdef.args.kwonlyargs)}
    if fdef.args.vararg:
        out.add(fdef.args.vararg.arg)
    if fdef.args.kwarg:
        out.add(fdef.args.kwarg.arg)
    for n in ast.walk(fdef):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)) \
                and n is not fdef:
            out.add(n.name)
    return out


def _capture_prelude(params, tag):
    """try: tmp = name / except: tmp = UNDEFINED — capture current values
    (possibly unbound) to pass into the extracted closures by value."""
    stmts, tmps = [], []
    for i, p in enumerate(params):
        tmp = f"__pd_v{tag}_{i}"
        tmps.append(tmp)
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[ast.Name(id=tmp, ctx=ast.Store())],
                             value=ast.Name(id=p, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[ast.Name(id="NameError", ctx=ast.Load()),
                                     ast.Name(id="UnboundLocalError", ctx=ast.Load())],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(targets=[ast.Name(id=tmp, ctx=ast.Store())],
                                 value=ast.Name(id="__pd_undef__", ctx=ast.Load()))])],
            orelse=[], finalbody=[]))
    return stmts, tmps


def _fn_args(params):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                         kwonlyargs=[], kw_defaults=[], defaults=[])


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self, fn_locals: Set[str], fn_load_counts=None):
        self.counter = 0
        self.converted = 0
        self.fn_locals = fn_locals
        self.fn_load_counts = fn_load_counts or {}

    def _name(self, kind):
        self.counter += 1
        return f"__pd_{kind}_{self.counter}"

    # -- if/else → pd_cond ---------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        t_names = _assigned_names(node.body)
        f_names = _assigned_names(node.orelse) if node.orelse else set()
        if t_names is None or f_names is None:
            return node  # unconvertible construct: keep plain Python
        # liveness: only names READ outside this if-subtree become outputs
        # (a branch-local loop temp stays internal — matching the reference
        # transformer's return-name analysis)
        inner = _load_counts(node)
        outs = sorted(n for n in (t_names | f_names)
                      if self.fn_load_counts.get(n, 0) > inner.get(n, 0))
        loaded = set()
        for st in node.body + (node.orelse or []):
            loaded |= _loaded_names(st)
        # pass by value every name the branches read or write that is a
        # local of the enclosing function — avoids UnboundLocalError when a
        # branch both reads and assigns the same name
        params = sorted(set(outs) | (loaded & self.fn_locals))
        tn, fn_ = self._name("true"), self._name("false")
        self.counter += 1
        prelude, tmps = _capture_prelude(params, self.counter)

        def branch(name, body):
            ret = ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=o, ctx=ast.Load()) for o in outs],
                ctx=ast.Load()))
            return ast.FunctionDef(
                name=name, args=_fn_args(params),
                body=(list(body) or [ast.Pass()]) + [ret],
                decorator_list=[])

        call = ast.Call(
            func=ast.Name(id="__pd_cond__", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tn, ctx=ast.Load()),
                  ast.Name(id=fn_, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=t, ctx=ast.Load()) for t in tmps],
                            ctx=ast.Load())],
            keywords=[])
        if outs:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=o, ctx=ast.Store()) for o in outs],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        self.converted += 1
        return [branch(tn, node.body), branch(fn_, node.orelse or []),
                *prelude, assign]

    # -- while → pd_while ----------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            return node
        body_names = _assigned_names(node.body)
        if body_names is None:
            return node
        # carry = every name the loop mutates; read-only enclosing locals
        # stay closure captures (loop-invariant)
        carried = sorted(body_names)
        if not carried:
            return node
        cn, bn = self._name("while_cond"), self._name("while_body")
        self.counter += 1
        prelude, tmps = _capture_prelude(carried, self.counter)
        cond_def = ast.FunctionDef(
            name=cn, args=_fn_args(carried),
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=c, ctx=ast.Load()) for c in carried],
            ctx=ast.Load()))
        body_def = ast.FunctionDef(
            name=bn, args=_fn_args(carried),
            body=list(node.body) + [body_ret], decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__pd_while__", ctx=ast.Load()),
            args=[ast.Name(id=cn, ctx=ast.Load()),
                  ast.Name(id=bn, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=t, ctx=ast.Load()) for t in tmps],
                            ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=c, ctx=ast.Store()) for c in carried],
                ctx=ast.Store())],
            value=call)
        self.converted += 1
        return [cond_def, body_def, *prelude, assign]


@functools.lru_cache(maxsize=256)
def _convert_cached(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []  # drop @to_static etc.
    tr = _ControlFlowTransformer(_fn_locals(fdef), _load_counts(fdef))
    tr.visit(tree)
    if tr.converted == 0:
        return None
    ast.fix_missing_locations(tree)
    code = compile(tree, f"<dy2static:{fn.__qualname__}>", "exec")
    glb = dict(fn.__globals__)
    glb["__pd_cond__"] = pd_cond
    glb["__pd_while__"] = pd_while
    glb["__pd_undef__"] = UNDEFINED
    # closures: rebuild free variables from the original function
    if fn.__closure__:
        for name, cellv in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                # the freevar SHADOWS any same-named module global, exactly
                # as in the original function's scope
                glb[name] = cellv.cell_contents
            except ValueError:
                pass
    ns = {}
    exec(code, glb, ns)  # noqa: S102 — compiling the user's own source
    new_fn = ns[fdef.name]
    new_fn.__wrapped_by_dy2static__ = fn
    return new_fn


def convert_function(fn: Callable) -> Callable:
    """AST-convert ``fn`` (best effort). Returns the original function when
    nothing was converted or the source is unavailable."""
    if getattr(fn, "_not_to_static", False):
        return fn
    target = fn
    bound_self = getattr(fn, "__self__", None)
    if bound_self is not None:
        target = fn.__func__
    converted = _convert_cached(target)
    if converted is None:
        return fn
    if bound_self is not None:
        return converted.__get__(bound_self, type(bound_self))
    return converted
