"""dygraph-to-static AST conversion: data-dependent Python control flow
on Tensors becomes ``lax.cond`` / ``lax.while_loop`` under ``@to_static``.

Parity: the reference's 25-file AST transpiler
(/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:768 ProgramTranslator + per-construct transformers:
ifelse_transformer, loop_transformer, break_continue_transformer,
return_transformer). TPU-native scope — a CONSERVATIVE layered pass:

1. ``for i in range(...)`` loops lower to ``while`` with an explicit trip
   count (loop_transformer role) so tensor-dependent bounds/carries trace.
2. early ``return`` anywhere becomes a (done-flag, value) pair threaded
   through the function; loops gain ``not done`` in their condition and
   trailing statements are guarded (return_transformer role).
3. ``break``/``continue`` become per-loop flags: following statements are
   guarded, the loop condition gains ``not broken``, and ``else:`` on a
   loop runs under ``not broken`` (break_continue_transformer role).
4. the remaining ``if``/``while`` statements with pure-assignment bodies
   extract to closures that dispatch at RUN time: concrete predicates take
   the plain Python path (bit-identical semantics), traced predicates
   lower to ``lax.cond``/``lax.while_loop``.

Anything still unconvertible keeps the original Python statement (which
works for concrete predicates and raises jax's teachable tracer error for
traced ones).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Set

__all__ = ["convert_function", "pd_cond", "pd_while", "checked"]


# ---------------------------------------------------------------------------
# runtime dispatch helpers (injected as globals into converted functions)
# ---------------------------------------------------------------------------
def _is_traced(x):
    import jax

    return isinstance(x, jax.core.Tracer)


def _pred_value(pred):
    from ..tensor import Tensor

    return pred._data if isinstance(pred, Tensor) else pred


class _Undefined:
    """Sentinel for names possibly unbound at the control-flow site
    (reference dygraph_to_static UndefinedVar role). Merely holding it is
    fine (the original code would simply leave the name unbound); USING it
    raises the UnboundLocalError the untransformed code would have raised."""

    __slots__ = ()

    def __repr__(self):
        return "<pd-undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "variable was left undefined by the untaken branch of a "
            "converted if/else (assign it on both paths)")

    __bool__ = __add__ = __radd__ = __sub__ = __rsub__ = _raise
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _raise
    __getitem__ = __iter__ = __len__ = __float__ = __int__ = _raise
    __call__ = __array__ = __matmul__ = __neg__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = _raise


UNDEFINED = _Undefined()


def _improper(v):
    return v is None or isinstance(v, _Undefined)


def _probe_structs(fn, args):
    """Structure/aval discovery for a pure closure returning a tuple, via
    jax.eval_shape — no ops are emitted into the enclosing trace. Improper
    (None/undefined) positions are recorded out-of-band in ``kinds`` (the
    python side of the trace runs concretely)."""
    import jax

    from ..tensor import Tensor

    kinds = {}

    def leaf(x):
        return isinstance(x, (Tensor, _Undefined)) or x is None

    def enc():
        out = fn(*args)
        res = []
        for i, v in enumerate(out):
            if _improper(v):
                kinds[i] = "none" if v is None else "undef"
                res.append(None)
                continue
            leaves, tree = jax.tree_util.tree_flatten(v, is_leaf=leaf)
            if any(_improper(x) for x in leaves):
                raise ValueError(
                    "a container output of converted control flow holds an "
                    "undefined element; assign it on all paths")
            res.append(jax.tree_util.tree_unflatten(
                tree, [x._data if isinstance(x, Tensor) else x
                       for x in leaves]))
        return tuple(res)

    structs = jax.eval_shape(enc)
    return structs, kinds


def _copy_value(v):
    """Fresh containers around every list reachable through list/tuple/dict
    nesting (leaves — tensors, arrays, scalars — are shared, not copied).
    Container TYPES survive: namedtuples rebuild via their constructor,
    dict subclasses via ``.copy()`` + per-key assignment (preserving e.g.
    defaultdict's factory and Counter's counts). A subclass we cannot
    rebuild safely is passed through unchanged (the pre-r6 behavior)."""
    try:
        if isinstance(v, list):
            out = [_copy_value(x) for x in v]
            return out if type(v) is list else type(v)(out)
        if isinstance(v, tuple):
            if hasattr(v, "_fields"):  # namedtuple
                return type(v)(*(_copy_value(x) for x in v))
            out = tuple(_copy_value(x) for x in v)
            return out if type(v) is tuple else type(v)(out)
        if isinstance(v, dict):
            if type(v) is dict:
                return {k: _copy_value(x) for k, x in v.items()}
            out = v.copy()  # keeps type + metadata (default_factory, …)
            for k in out:
                out[k] = _copy_value(out[k])
            return out
    except Exception:
        return v
    return v


def _copy_list_args(args):
    """Fresh copies of list-valued args AT ANY NESTING LEVEL (inside
    tuples/dicts too, ADVICE r5 #3) — traced control flow invokes
    branch/body closures several times (probe + trace), and in-place list
    appends inside must not accumulate across calls."""
    return tuple(_copy_value(a) for a in args)


def pd_cond(pred, true_fn, false_fn, args=(), soft=()):
    """if/else dispatch: Python for concrete preds, lax.cond for traced.

    ``soft``: output POSITIONS (indices into the branch-return tuple) owned
    by the transformer's own threading variables (return value/flags).
    When such a position is None/undefined on one branch, it unifies as
    zeros of the other branch's avals — sound because the guard discipline
    never reads the value unless the flag says its branch assigned it.
    User variables (non-soft) keep the loud error."""
    import numpy as np

    p = _pred_value(pred)
    if not _is_traced(p):
        return true_fn(*args) if bool(np.asarray(p).reshape(())) else false_fn(*args)
    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor

    st_t, kinds_t = _probe_structs(true_fn, _copy_list_args(args))
    st_f, kinds_f = _probe_structs(false_fn, _copy_list_args(args))
    n = len(st_t)
    # per position: either a constant (improper on both sides), or a
    # ref subtree whose leaves go through lax.cond
    const_out, ref_tree, n_leaves = {}, {}, {}
    for i in range(n):
        imp_t, imp_f = i in kinds_t, i in kinds_f
        if imp_t and imp_f:
            # kinds disagreeing (None on one branch, unbound on the other)
            # keep the loud-on-use sentinel: the runtime branch is unknown,
            # and silently binding None would mask a use-before-assign
            const_out[i] = (None if kinds_t[i] == kinds_f[i] == "none"
                            else UNDEFINED)
            continue
        if imp_t or imp_f:
            if i not in soft:
                raise ValueError(
                    "a tensor-dependent if/else leaves a variable "
                    "undefined on one branch; assign it on both paths "
                    "(lax.cond requires matching branch outputs)")
            good = st_f[i] if imp_t else st_t[i]
        else:
            lt, tt = jax.tree_util.tree_flatten(st_t[i])
            lf, tf = jax.tree_util.tree_flatten(st_f[i])
            if tt != tf or [(x.shape, x.dtype) for x in lt] != [
                    (x.shape, x.dtype) for x in lf]:
                raise ValueError(
                    "tensor-dependent if/else branches produce different "
                    "structures/shapes for the same variable (lax.cond "
                    "requires matching branch outputs)")
            good = st_t[i]
        leaves, tree = jax.tree_util.tree_flatten(good)
        ref_tree[i] = tree
        n_leaves[i] = len(leaves)

    keep = sorted(ref_tree)
    protos = {
        i: [jnp.zeros(s.shape, s.dtype)
            for s in jax.tree_util.tree_flatten(
                st_f[i] if i in kinds_t else st_t[i])[0]]
        for i in keep
    }

    def leaf(x):
        return isinstance(x, (Tensor, _Undefined)) or x is None

    def wrap(fn):
        def f(_):
            out = fn(*_copy_list_args(args))
            arrs = []
            for i in keep:
                v = out[i]
                if _improper(v):
                    arrs.extend(protos[i])
                    continue
                leaves, _t = jax.tree_util.tree_flatten(v, is_leaf=leaf)
                arrs.extend(x._data if isinstance(x, Tensor)
                            else jnp.asarray(x) for x in leaves)
            return tuple(arrs)

        return f

    res = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                       wrap(true_fn), wrap(false_fn), ())
    out, it = [], iter(res)
    for i in range(n):
        if i in const_out:
            out.append(const_out[i])
        else:
            leaves = [Tensor(next(it)) for _ in range(n_leaves[i])]
            out.append(jax.tree_util.tree_unflatten(ref_tree[i], leaves))
    return tuple(out)


def pd_not(x):
    """``not x`` that stays traceable (guards emitted by the return /
    break-continue transformers)."""
    p = _pred_value(x)
    if _is_traced(p):
        import jax.numpy as jnp

        return jnp.logical_not(p)
    import numpy as np

    return not bool(np.asarray(p).reshape(()))


def pd_and(a, b):
    """Eager-but-traceable ``a and b`` for transformed loop conditions."""
    pa, pb = _pred_value(a), _pred_value(b)
    if _is_traced(pa) or _is_traced(pb):
        import jax.numpy as jnp

        return jnp.logical_and(pa, pb)
    import numpy as np

    return bool(np.asarray(pa).reshape(())) and bool(np.asarray(pb).reshape(()))


def pd_or(a, b):
    pa, pb = _pred_value(a), _pred_value(b)
    if _is_traced(pa) or _is_traced(pb):
        import jax.numpy as jnp

        return jnp.logical_or(pa, pb)
    import numpy as np

    return bool(np.asarray(pa).reshape(())) or bool(np.asarray(pb).reshape(()))


def pd_list_append(lst, value):
    """``lst.append(v)`` in assignment form (reference list_transformer
    role, list_transformer.py:1): rewriting the statement to
    ``lst = __pd_list_append__(lst, v)`` makes the list an *assigned* name,
    so the if/while converters carry it as a pytree output — a traced-
    predicate branch appending to a list works through ``lax.cond`` (both
    branches must append compatible shapes, jax's structure check is the
    teachable error). Appends that GROW a ``lax.while_loop`` carry still
    raise jax's structure mismatch — XLA has no dynamic arrays (the
    reference's LoDTensorArray relies on its dynamic executor)."""
    # mutate IN PLACE and return the same object: `b = a; a.append(x)`
    # keeps b aliased exactly as in the untransformed code. The traced
    # control-flow paths (pd_cond/pd_while) shallow-copy list args per
    # branch invocation so repeated probe/trace calls don't double-append.
    lst.append(value)
    return lst


def pd_print(*args, **kw):
    """print() that renders VALUES under trace (reference
    print_transformer → Print op): traced args go through
    jax.debug.print, concrete ones through plain print."""
    vals = [_pred_value(a) for a in args]
    if any(_is_traced(v) for v in vals):
        import jax

        fmt = " ".join("{}" for _ in vals)
        jax.debug.print(fmt, *vals, **{k: v for k, v in kw.items()
                                       if k in ("ordered",)})
        return None
    return print(*args, **kw)


def pd_assert(test, msg=None):
    """assert that survives tracing (reference assert_transformer →
    Assert op): concrete predicates keep PYTHON truthiness (``bool(x)`` —
    an empty list fails, exactly like the untransformed assert); traced
    ones check all elements at run time (the reference Assert op's
    all-elements semantics).

    Traced-failure semantics depend on how the caller runs the program:

    * Under :func:`checked` (``paddle_tpu.jit.checked``) the assert lowers
      to ``jax.experimental.checkify.check`` — a **synchronous** checked
      error: ``err.throw()`` raises exactly at the assert's program point,
      like the reference Assert op halting the executor.
    * Otherwise it falls back to ``jax.debug.callback``, whose failure
      surfaces **asynchronously**: under jit the AssertionError is raised
      from the runtime when the host callback drains (at block/readback
      time), so ops AFTER the assert may already have run. This matches
      jax's execution model — there is no synchronous host abort inside a
      compiled program without checkify functionalization.
    """
    p = _pred_value(test)
    if not _is_traced(p):
        if not bool(test):
            raise AssertionError(msg if msg is not None else "")
        return None
    import jax
    import jax.numpy as jnp

    message = msg if msg is not None else "Assert failed on traced predicate"
    if _in_checked():
        # synchronous checked-error path: a bare checkify.check staged
        # OUTSIDE a checkify functionalization fails at LOWERING time (after
        # this frame returned), so the check is only emitted under
        # :func:`checked`'s explicit functionalization flag
        from jax.experimental import checkify

        # checkify treats the message as a .format template: escape braces
        # so literal "{0,1}"-style messages don't raise at throw() time
        safe = str(message).replace("{", "{{").replace("}", "}}")
        checkify.check(jnp.asarray(p).reshape(-1).all(), safe)
        return None

    def _check(ok):
        import numpy as np

        if not bool(np.asarray(ok).reshape(-1).all()):
            raise AssertionError(message)

    jax.debug.callback(_check, p)
    return None


import threading as _threading

_checkify_state = _threading.local()


def _in_checked() -> bool:
    """True while :func:`checked` is driving the trace — the only context
    where staging a bare ``checkify.check`` is sound."""
    return getattr(_checkify_state, "active", False)


def checked(fn):
    """Wrap ``fn`` so traced ``assert``/:func:`pd_assert` failures raise
    SYNCHRONOUSLY at the assert's program point (reference Assert-op
    executor semantics), via ``jax.experimental.checkify``.

    ``checked(fn)(*args)`` functionalizes user checks, runs the program,
    and calls ``err.throw()`` before returning — a failed assert raises
    ``checkify.JaxRuntimeError`` at the call site with the assert's
    message; nothing after the failed check is observable. Composes with
    jit (``checked(jitted_fn)`` re-functionalizes through the call) and
    with ``to_static`` conversion (asserts become pd_assert first)."""
    import functools

    from jax.experimental import checkify

    cfn = checkify.checkify(convert_function(fn))

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        prev = _in_checked()
        _checkify_state.active = True
        try:
            err, out = cfn(*args, **kwargs)
        finally:
            _checkify_state.active = prev
        err.throw()
        return out

    return wrapper


def pd_range_len(start, stop, step):
    """Trip count of range(start, stop, step), traceable."""
    s, e, st = (_pred_value(v) for v in (start, stop, step))
    if not any(_is_traced(v) for v in (s, e, st)):
        return len(range(int(s), int(e), int(st)))
    import jax.numpy as jnp

    up = (e - s + st - 1) // st
    down = (s - e + (-st) - 1) // (-st)
    return jnp.maximum(0, jnp.where(st > 0, up, down))


def pd_while(cond_fn, body_fn, init, soft=()):
    """while dispatch: Python loop for concrete conds, lax.while_loop for
    traced. ``init`` is the tuple of loop-carried values (all tensor-like);
    their shapes/dtypes must be loop-invariant on the traced path.

    ``soft``: carry positions owned by the transformer's threading
    variables (return value/flags). A soft carry that is None/undefined at
    loop entry takes zeros of the aval the body assigns it (the guard
    discipline never reads it before the flag says it was set)."""
    import numpy as np

    from ..tensor import Tensor

    p0 = _pred_value(cond_fn(*init))
    if not _is_traced(p0):
        # concrete path — but a carry can BECOME traced mid-loop (e.g. a
        # break flag set inside a converted tensor-if): re-check each
        # iteration and hand the remaining iterations to lax.while_loop
        vals = tuple(init)
        while True:
            c = _pred_value(cond_fn(*vals))
            if _is_traced(c):
                return pd_while(cond_fn, body_fn, vals, soft)
            if not bool(np.asarray(c).reshape(())):
                return vals
            vals = tuple(body_fn(*vals))
    import jax
    import jax.numpy as jnp

    init = list(init)
    const_pos = {}
    bad = [i for i, v in enumerate(init) if _improper(v)]
    if bad:
        if any(i not in soft for i in bad):
            raise ValueError(
                "a tensor-dependent while carries a variable that is "
                "undefined at loop entry; assign it before the loop")
        # aval discovery via eval_shape (no ops emitted into the trace)
        structs, kinds = _probe_structs(body_fn, _copy_list_args(tuple(init)))
        for i in bad:
            if i in kinds:
                const_pos[i] = init[i]  # never assigned a tensor: constant
                continue
            leaves, _tree = jax.tree_util.tree_flatten(structs[i])
            if len(leaves) != 1:
                raise ValueError(
                    "a while-carried return value must be a single tensor "
                    "(return a tuple AFTER the loop instead)")
            init[i] = Tensor(jnp.zeros(leaves[0].shape, leaves[0].dtype))

    keep = [i for i in range(len(init)) if i not in const_pos]

    def rebuild(arrs):
        it = iter(arrs)
        return tuple(const_pos[i] if i in const_pos else Tensor(next(it))
                     for i in range(len(init)))

    def unwrap_keep(vals):
        return tuple(
            vals[i]._data if isinstance(vals[i], Tensor)
            else jnp.asarray(vals[i]) for i in keep)

    def c(carry):
        return jnp.reshape(_pred_value(cond_fn(*rebuild(carry))), ()).astype(bool)

    def b(carry):
        return unwrap_keep(body_fn(*rebuild(carry)))

    out = jax.lax.while_loop(c, b, unwrap_keep(init))
    return rebuild(out)


# ---------------------------------------------------------------------------
# the AST pass
# ---------------------------------------------------------------------------
def _is_capture_prelude_try(st: ast.Try) -> bool:
    """Recognize our generated try/except shapes: the _capture_prelude
    (__pd_v* tmp, iteration-local) and the for-lowering target guard
    (name = name / except: name = start — the name is re-assigned by the
    loop advance anyway, so neither contributes a carry here)."""
    if not (len(st.body) == 1 and isinstance(st.body[0], ast.Assign)
            and isinstance(st.body[0].targets[0], ast.Name)):
        return False
    tgt = st.body[0].targets[0].id
    if tgt.startswith("__pd_v"):
        return True
    # target guard: try: n = n
    return (isinstance(st.body[0].value, ast.Name)
            and st.body[0].value.id == tgt)


def _assigned_names(stmts: List[ast.stmt]) -> Optional[Set[str]]:
    """Names simply assigned in the statement list; None = unconvertible.

    Scope-aware: function defs (both user closures and the artifacts our
    own if-conversion leaves behind — closure defs + capture preludes) are
    allowed but contribute NO carried names, because they are re-bound
    every iteration before use."""
    names: Set[str] = set()

    def visit_block(body) -> bool:
        return all(visit_stmt(s) for s in body)

    def visit_stmt(st) -> bool:
        if isinstance(st, (ast.Return, ast.Break, ast.Continue,
                           ast.Global, ast.Nonlocal, ast.AsyncFunctionDef,
                           ast.With, ast.AsyncWith, ast.Raise,
                           ast.AsyncFor)):
            return False
        # yields at THIS scope level make the body a generator → bail
        for n in _walk_scope(st):
            if isinstance(n, (ast.Yield, ast.YieldFrom)):
                return False
        if isinstance(st, ast.FunctionDef):
            return True  # iteration-local binding; nothing carried
        if isinstance(st, ast.Try):
            return _is_capture_prelude_try(st)
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    if not t.id.startswith("__pd_v"):
                        names.add(t.id)
                elif isinstance(t, ast.Tuple) and all(
                        isinstance(e, ast.Name) for e in t.elts):
                    names.update(e.id for e in t.elts)
                else:
                    return False
            return True
        if isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(st.target, ast.Name):
                names.add(st.target.id)
                return True
            return False
        if isinstance(st, ast.If):
            return visit_block(st.body) and visit_block(st.orelse)
        if isinstance(st, (ast.While,)):
            return not st.orelse and visit_block(st.body)
        if isinstance(st, ast.For):
            t = st.target
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, ast.Tuple) and all(
                    isinstance(e, ast.Name) for e in t.elts):
                names.update(e.id for e in t.elts)
            else:
                return False
            return not st.orelse and visit_block(st.body)
        if isinstance(st, (ast.Expr, ast.Pass, ast.Assert, ast.Delete,
                           ast.Import, ast.ImportFrom)):
            # walrus targets inside expressions are carries
            for n in _walk_scope(st):
                if isinstance(n, ast.NamedExpr):
                    if isinstance(n.target, ast.Name):
                        names.add(n.target.id)
                    else:
                        return False
            return True
        return False

    if not visit_block(list(stmts)):
        return None
    # walrus expressions nested in convertible statements' tests/values
    for st in stmts:
        for n in _walk_scope(st):
            if isinstance(n, ast.NamedExpr) and isinstance(n.target, ast.Name):
                names.add(n.target.id)
    return names


def _loaded_names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _load_counts(node: ast.AST):
    from collections import Counter

    return Counter(n.id for n in ast.walk(node)
                   if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load))


def _fn_locals(fdef) -> Set[str]:
    """All names that are locals of the function (args + any assignment)."""
    out = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args
                           + fdef.args.kwonlyargs)}
    if fdef.args.vararg:
        out.add(fdef.args.vararg.arg)
    if fdef.args.kwarg:
        out.add(fdef.args.kwarg.arg)
    for n in ast.walk(fdef):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)) \
                and n is not fdef:
            out.add(n.name)
    return out


def _capture_prelude(params, tag):
    """try: tmp = name / except: tmp = UNDEFINED — capture current values
    (possibly unbound) to pass into the extracted closures by value."""
    stmts, tmps = [], []
    for i, p in enumerate(params):
        tmp = f"__pd_v{tag}_{i}"
        tmps.append(tmp)
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[ast.Name(id=tmp, ctx=ast.Store())],
                             value=ast.Name(id=p, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[ast.Name(id="NameError", ctx=ast.Load()),
                                     ast.Name(id="UnboundLocalError", ctx=ast.Load())],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(targets=[ast.Name(id=tmp, ctx=ast.Store())],
                                 value=ast.Name(id="__pd_undef__", ctx=ast.Load()))])],
            orelse=[], finalbody=[]))
    return stmts, tmps


def _fn_args(params):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                         kwonlyargs=[], kw_defaults=[], defaults=[])


_SCOPE_STOPS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _walk_scope(node):
    """Walk without descending into nested function/class scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, _SCOPE_STOPS):
                continue
            stack.append(c)


def _assign(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=value)


def _call(fn_name, args):
    return ast.Call(func=ast.Name(id=fn_name, ctx=ast.Load()),
                    args=args, keywords=[])


def _name(n):
    return ast.Name(id=n, ctx=ast.Load())


class _Unsupported(Exception):
    pass


class _ForRangeLowering(ast.NodeTransformer):
    """``for i in range(...)`` → explicit-trip-count ``while`` (reference
    loop_transformer): tensor-dependent bounds and loop carries then trace
    through the while machinery. The index/target assignments run BEFORE
    the user body so a transformed ``continue`` cannot skip the advance."""

    def __init__(self):
        self.n = 0
        self.changed = False

    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3
                and isinstance(node.target, ast.Name)):
            return node
        self.n += 1
        self.changed = True
        k = self.n
        start = it.args[0] if len(it.args) >= 2 else ast.Constant(0)
        stop = it.args[1] if len(it.args) >= 2 else it.args[0]
        step = it.args[2] if len(it.args) == 3 else ast.Constant(1)
        v_start, v_stop, v_step = (f"__pd_start{k}", f"__pd_stop{k}",
                                   f"__pd_step{k}")
        v_idx, v_trip = f"__pd_idx{k}", f"__pd_trip{k}"
        pre = [
            _assign(v_start, start), _assign(v_stop, stop),
            _assign(v_step, step), _assign(v_idx, ast.Constant(0)),
            _assign(v_trip, _call("__pd_range_len__",
                                  [_name(v_start), _name(v_stop),
                                   _name(v_step)])),
            # the target is (re)assigned at the top of every iteration; this
            # try-guard only gives the while carry a defined value/dtype
            # WITHOUT clobbering a pre-existing binding (empty-range python
            # semantics keep the old value)
            ast.Try(
                body=[_assign(node.target.id, _name(node.target.id))],
                handlers=[ast.ExceptHandler(
                    type=ast.Tuple(
                        elts=[_name("NameError"), _name("UnboundLocalError")],
                        ctx=ast.Load()),
                    name=None,
                    body=[_assign(node.target.id, _name(v_start))])],
                orelse=[], finalbody=[]),
        ]
        advance = [
            _assign(node.target.id, ast.BinOp(
                left=_name(v_start), op=ast.Add(),
                right=ast.BinOp(left=_name(v_idx), op=ast.Mult(),
                                right=_name(v_step)))),
            _assign(v_idx, ast.BinOp(left=_name(v_idx), op=ast.Add(),
                                     right=ast.Constant(1))),
        ]
        w = ast.While(
            test=ast.Compare(left=_name(v_idx), ops=[ast.Lt()],
                             comparators=[_name(v_trip)]),
            body=advance + node.body, orelse=node.orelse)
        return pre + [w]


_RET_VAL, _RET_FLAG = "__pd_ret_val", "__pd_ret_done"


def _transform_returns(fdef) -> bool:
    """Early returns → (done-flag, value) threading (reference
    return_transformer). Returns True when the function was rewritten;
    raises _Unsupported for constructs we refuse to guard (with/try
    containing a return)."""
    body = fdef.body
    early = False
    for n in _walk_scope(fdef):
        if isinstance(n, ast.Return) and n not in body[-1:]:
            early = True
            break
    if not early:
        return False

    def rewrite_block(stmts):
        out, may = [], False
        for i, st in enumerate(stmts):
            if isinstance(st, ast.Return):
                out.append(_assign(_RET_VAL, st.value or ast.Constant(None)))
                out.append(_assign(_RET_FLAG, ast.Constant(True)))
                return out, True  # rest of the block is dead
            st, st_may = rewrite_stmt(st)
            out.append(st)
            if st_may:
                rest, _ = rewrite_block(stmts[i + 1:])
                if rest:
                    out.append(ast.If(
                        test=_call("__pd_not__", [_name(_RET_FLAG)]),
                        body=rest, orelse=[]))
                return out, True
        return out, may

    def rewrite_stmt(st):
        if isinstance(st, ast.If):
            st.body, m1 = rewrite_block(st.body)
            st.orelse, m2 = rewrite_block(st.orelse) if st.orelse else ([], False)
            return st, m1 or m2
        if isinstance(st, ast.While):
            st.body, m = rewrite_block(st.body)
            if m:
                st.test = _call("__pd_and__",
                                [_call("__pd_not__", [_name(_RET_FLAG)]),
                                 st.test])
            return st, m
        if isinstance(st, ast.For):
            st.body, m = rewrite_block(st.body)
            if m:
                # python-level for: escape concretely (a traced return flag
                # inside a plain for is unconvertible by design)
                st.body.append(ast.If(test=_name(_RET_FLAG),
                                      body=[ast.Break()], orelse=[]))
            return st, m
        if any(isinstance(n, ast.Return) for n in _walk_scope(st)):
            raise _Unsupported("return inside with/try is not convertible")
        return st, False

    new_body, _ = rewrite_block(body)
    fdef.body = ([_assign(_RET_FLAG, ast.Constant(False)),
                  _assign(_RET_VAL, ast.Constant(None))]
                 + new_body
                 + [ast.Return(value=_name(_RET_VAL))])
    return True


def _direct_break_continue(stmts):
    """Break/Continue nodes belonging to THIS loop level (not nested
    loops)."""
    has_b = has_c = False
    stack = list(stmts)
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Break):
            has_b = True
        elif isinstance(n, ast.Continue):
            has_c = True
        elif isinstance(n, (ast.While, ast.For) + _SCOPE_STOPS):
            continue  # nested loop owns its own break/continue
        else:
            stack.extend(ast.iter_child_nodes(n))
    return has_b, has_c


class _BreakContinueTransformer(ast.NodeTransformer):
    """break/continue → guard flags (reference
    break_continue_transformer): statements after a (possibly conditional)
    break/continue are wrapped in ``if not flag``, the while condition
    gains ``not broken``, and a loop ``else`` runs under ``not broken``."""

    def __init__(self):
        self.n = 0
        self.changed = False

    def visit_While(self, node: ast.While):
        self.generic_visit(node)  # inner loops first
        has_b, has_c = _direct_break_continue(node.body)
        if not (has_b or has_c):
            return node
        self.n += 1
        self.changed = True
        brk, cont = f"__pd_brk{self.n}", f"__pd_cont{self.n}"
        flags = ([_name(brk)] if has_b else []) + ([_name(cont)] if has_c else [])

        def guard_test():
            t = flags[0]
            for f in flags[1:]:
                t = _call("__pd_or__", [t, f])
            return _call("__pd_not__", [t])

        def guard_block(stmts):
            out = []
            for i, st in enumerate(stmts):
                if isinstance(st, ast.Break):
                    out.append(_assign(brk, ast.Constant(True)))
                    return out, True
                if isinstance(st, ast.Continue):
                    out.append(_assign(cont, ast.Constant(True)))
                    return out, True
                st, may = guard_stmt(st)
                out.append(st)
                if may:
                    rest, _ = guard_block(stmts[i + 1:])
                    if rest:
                        out.append(ast.If(test=guard_test(), body=rest,
                                          orelse=[]))
                    return out, True
            return out, False

        def guard_stmt(st):
            if isinstance(st, ast.If):
                st.body, m1 = guard_block(st.body)
                st.orelse, m2 = (guard_block(st.orelse) if st.orelse
                                 else ([], False))
                return st, m1 or m2
            # nested loops own their break/continue; other statements can't
            return st, False

        body, _ = guard_block(node.body)
        node.body = ([_assign(cont, ast.Constant(False))] if has_c else []) + body
        out = []
        if has_c:
            # pre-loop init: the flag is re-set each iteration, but the
            # while conversion carries it, so it must be bound before entry
            out.append(_assign(cont, ast.Constant(False)))
        if has_b:
            out.append(_assign(brk, ast.Constant(False)))
            node.test = _call("__pd_and__",
                              [_call("__pd_not__", [_name(brk)]), node.test])
        orelse = node.orelse
        node.orelse = []
        out.append(node)
        if orelse:
            if has_b:
                out.append(ast.If(test=_call("__pd_not__", [_name(brk)]),
                                  body=orelse, orelse=[]))
            else:
                out.extend(orelse)  # never broken → else always runs
        return out


class _StatementTransformer(ast.NodeTransformer):
    """Pre-pass for statement-level rewrites (reference list_transformer /
    print_transformer / assert_transformer roles):

    - ``name.append(v)`` → ``name = __pd_list_append__(name, v)`` for
      names local to the CURRENT scope, so list mutation becomes an
      assignment the control-flow converters can carry as a pytree output.
      A nested function mutating an ENCLOSING scope's list is left alone —
      the rewrite would turn the closure mutation into an unbound local.
    - ``print(...)`` statements → ``__pd_print__(...)`` (value rendering
      under trace).
    - ``assert t[, msg]`` → ``__pd_assert__(t, msg)``.

    Applied per scope (each FunctionDef with its own locals); nested
    FunctionDefs are skipped and visited by their own pass.
    """

    def __init__(self, fn_locals: Set[str]):
        self.fn_locals = fn_locals
        self.changed = False

    def visit_FunctionDef(self, node: ast.FunctionDef):
        return node  # nested scopes get their own pass with their locals

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Expr(self, node: ast.Expr):
        self.generic_visit(node)
        call = node.value
        if not isinstance(call, ast.Call):
            return node
        # name.append(v)
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "append"
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id in self.fn_locals
                and len(call.args) == 1 and not call.keywords):
            name = call.func.value.id
            self.changed = True
            return ast.Assign(
                targets=[ast.Name(id=name, ctx=ast.Store())],
                value=_call("__pd_list_append__",
                            [ast.Name(id=name, ctx=ast.Load()),
                             call.args[0]]))
        # print(...)
        if (isinstance(call.func, ast.Name) and call.func.id == "print"
                and not call.keywords):
            self.changed = True
            return ast.Expr(value=_call("__pd_print__", list(call.args)))
        return node

    def visit_Assert(self, node: ast.Assert):
        self.generic_visit(node)
        self.changed = True
        args = [node.test]
        if node.msg is not None:
            args.append(node.msg)
        return ast.Expr(value=_call("__pd_assert__", args))


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self, fn_locals: Set[str], root=None):
        self.counter = 0
        self.converted = 0
        self.fn_locals = fn_locals
        # liveness is computed against the CURRENT tree at each visit:
        # inner conversions add loads (capture preludes, guard tests), so a
        # pre-transform snapshot would under-count and drop outputs
        self.root = root

    def _name(self, kind):
        self.counter += 1
        return f"__pd_{kind}_{self.counter}"

    # -- if/else → pd_cond ---------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        t_names = _assigned_names(node.body)
        f_names = _assigned_names(node.orelse) if node.orelse else set()
        if t_names is None or f_names is None:
            return node  # unconvertible construct: keep plain Python
        # liveness: only names READ outside this if-subtree become outputs
        # (a branch-local loop temp stays internal — matching the reference
        # transformer's return-name analysis)
        inner = _load_counts(node)
        outer = _load_counts(self.root) if self.root is not None else inner
        outs = sorted(n for n in (t_names | f_names)
                      if outer.get(n, 0) > inner.get(n, 0))
        loaded = set()
        for st in node.body + (node.orelse or []):
            loaded |= _loaded_names(st)
        # pass by value every name the branches read or write that is a
        # local of the enclosing function — avoids UnboundLocalError when a
        # branch both reads and assigns the same name
        params = sorted(set(outs) | (loaded & self.fn_locals))
        tn, fn_ = self._name("true"), self._name("false")
        self.counter += 1
        prelude, tmps = _capture_prelude(params, self.counter)

        def branch(name, body):
            ret = ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=o, ctx=ast.Load()) for o in outs],
                ctx=ast.Load()))
            return ast.FunctionDef(
                name=name, args=_fn_args(params),
                body=(list(body) or [ast.Pass()]) + [ret],
                decorator_list=[])

        soft = tuple(i for i, o in enumerate(outs) if o.startswith("__pd_"))
        call = ast.Call(
            func=ast.Name(id="__pd_cond__", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tn, ctx=ast.Load()),
                  ast.Name(id=fn_, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=t, ctx=ast.Load()) for t in tmps],
                            ctx=ast.Load()),
                  ast.Constant(soft)],
            keywords=[])
        if outs:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=o, ctx=ast.Store()) for o in outs],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        self.converted += 1
        return [branch(tn, node.body), branch(fn_, node.orelse or []),
                *prelude, assign]

    # -- while → pd_while ----------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            return node
        body_names = _assigned_names(node.body)
        if body_names is None:
            return node
        # carry = every name the loop mutates; read-only enclosing locals
        # stay closure captures (loop-invariant)
        carried = sorted(body_names)
        if not carried:
            return node
        cn, bn = self._name("while_cond"), self._name("while_body")
        self.counter += 1
        prelude, tmps = _capture_prelude(carried, self.counter)
        cond_def = ast.FunctionDef(
            name=cn, args=_fn_args(carried),
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=c, ctx=ast.Load()) for c in carried],
            ctx=ast.Load()))
        body_def = ast.FunctionDef(
            name=bn, args=_fn_args(carried),
            body=list(node.body) + [body_ret], decorator_list=[])
        soft = tuple(i for i, c_ in enumerate(carried)
                     if c_.startswith("__pd_"))
        call = ast.Call(
            func=ast.Name(id="__pd_while__", ctx=ast.Load()),
            args=[ast.Name(id=cn, ctx=ast.Load()),
                  ast.Name(id=bn, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=t, ctx=ast.Load()) for t in tmps],
                            ctx=ast.Load()),
                  ast.Constant(soft)],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=c, ctx=ast.Store()) for c in carried],
                ctx=ast.Store())],
            value=call)
        self.converted += 1
        return [cond_def, body_def, *prelude, assign]


# constructs whose converted form silently diverges from eager semantics
_MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "pop", "remove", "clear", "update",
    "setdefault", "add", "discard", "popitem", "set_value", "add_",
    "copy_", "scatter_", "fill_", "zero_",
})


def _strictness_scan(fn, fdef):
    """dy2static strictness (analysis rule ``dy2static-strictness``):
    detect constructs the converted function cannot honor — writes to
    module globals / nonlocal cells (the converted code executes against a
    COPY of the enclosing scopes, so the write would be lost) and mutation
    of closure-captured containers/Tensors (traced control flow invokes
    branch/body closures several times — probe + trace — so in-place
    effects on captured state double-apply).  Returns a reason string, or
    None when the function is clean."""
    code = getattr(fn, "__code__", None)  # jitted callables have no __code__
    freevars = set(code.co_freevars) if code is not None else set()
    # the double-apply hazard exists only INSIDE converted control flow
    # (probe + trace each invoke the branch/body closures); straight-line
    # closure mutation executes once per trace exactly as plain tracing
    # would, so it must keep converting
    in_cf = set()
    for cf in ast.walk(fdef):
        if isinstance(cf, (ast.If, ast.While, ast.For)):
            for sub in ast.walk(cf):
                in_cf.add(id(sub))
    for node in ast.walk(fdef):
        if isinstance(node, ast.Global):
            return f"writes to global(s) {', '.join(node.names)}"
        if isinstance(node, ast.Nonlocal):
            # only writes that ESCAPE the converted function are hazardous;
            # a nonlocal binding a cell internal to this function converts
            # together with it and stays correct
            escaping = [n for n in node.names if n in freevars]
            if escaping:
                return f"writes to nonlocal(s) {', '.join(escaping)}"
            continue
        if id(node) not in in_cf:
            continue
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            base = node.func.value
            if (isinstance(base, ast.Name) and base.id in freevars
                    and node.func.attr in _MUTATING_METHODS):
                return (f"mutates closure-captured '{base.id}' via "
                        f".{node.func.attr}() (line {node.lineno})")
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                inner = t
                while isinstance(inner, (ast.Subscript, ast.Attribute)):
                    inner = inner.value
                if (t is not inner and isinstance(inner, ast.Name)
                        and inner.id in freevars):
                    return (f"mutates closure-captured '{inner.id}' "
                            f"(line {t.lineno})")
    return None


def _warn_unconvertible(fn, reason):
    """Surface an unconvertible construct as a structured AnalysisWarning
    (instead of the pre-r9 silent fallback to tracing)."""
    from ..analysis.findings import Finding, Severity, warn_finding

    code = getattr(fn, "__code__", None)
    qn = getattr(fn, "__qualname__", getattr(fn, "__name__", repr(fn)))
    warn_finding(Finding(
        rule="dy2static-strictness", severity=Severity.MEDIUM,
        message=(f"@to_static: {qn} {reason}; dy2static "
                 "conversion is disabled for this function and it falls "
                 "back to plain tracing (tensor-dependent control flow "
                 "inside will raise jax's tracer error instead of lowering "
                 "to lax.cond/while_loop)"),
        entry_point=qn,
        source=(f"{code.co_filename}:{code.co_firstlineno} ({qn})"
                if code is not None else ""),
        details={"reason": reason},
    ), stacklevel=4)


@functools.lru_cache(maxsize=256)
def _convert_cached(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    hazard = _strictness_scan(fn, fdef)
    if hazard is not None:
        _warn_unconvertible(fn, hazard)
        return None
    fdef.decorator_list = []  # drop @to_static etc.
    # pre-passes (ordered): statement rewrites (append/print/assert) →
    # for-range lowering → return threading → break/continue flags; then
    # the closure-extracting if/while pass. Nested function declarations
    # (reference program_translator.py:768) are converted as their OWN
    # scopes, innermost first — each gets its own return threading and
    # control-flow pass with its own locals; by the time an outer scope is
    # processed, inner raw control flow is already lowered to calls.
    pre_changed = False
    lower = _ForRangeLowering()
    lower.visit(tree)
    pre_changed |= lower.changed

    # AsyncFunctionDef included: an async def passes the fdef type check
    # above, and without it here the per-scope passes would silently skip
    # the whole function (ADVICE r5 #4)
    scopes = [n for n in ast.walk(fdef)
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    converted_total = 0
    for scope in reversed(scopes):  # ast.walk lists outer first
        stmts = _StatementTransformer(_fn_locals(scope))
        scope.body = [stmts.visit(st) for st in scope.body]
        pre_changed |= stmts.changed
        try:
            pre_changed |= _transform_returns(scope)
        except _Unsupported as e:
            if scope is fdef:
                # structured fallback (pre-r9 this was silent)
                _warn_unconvertible(fn, f"uses an unsupported construct: {e}")
                return None  # keep the original function untouched
            continue  # leave just this nested fn unconverted
        bc = _BreakContinueTransformer()
        bc.visit(scope)
        pre_changed |= bc.changed
        tr = _ControlFlowTransformer(_fn_locals(scope), root=scope)
        # visit the scope's BODY statements (visiting the FunctionDef node
        # itself would re-enter nested defs already converted)
        scope.body = [st for part in scope.body
                      for st in (lambda r: r if isinstance(r, list) else [r])(
                          tr.visit(part))]
        converted_total += tr.converted
    if converted_total == 0 and not pre_changed:
        return None
    ast.fix_missing_locations(tree)
    code = compile(tree, f"<dy2static:{fn.__qualname__}>", "exec")
    glb = dict(fn.__globals__)
    glb["__pd_cond__"] = pd_cond
    glb["__pd_while__"] = pd_while
    glb["__pd_undef__"] = UNDEFINED
    glb["__pd_not__"] = pd_not
    glb["__pd_and__"] = pd_and
    glb["__pd_or__"] = pd_or
    glb["__pd_range_len__"] = pd_range_len
    glb["__pd_list_append__"] = pd_list_append
    glb["__pd_print__"] = pd_print
    glb["__pd_assert__"] = pd_assert
    # closures: rebuild free variables from the original function
    if fn.__closure__:
        for name, cellv in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                # the freevar SHADOWS any same-named module global, exactly
                # as in the original function's scope
                glb[name] = cellv.cell_contents
            except ValueError:
                pass
    ns = {}
    exec(code, glb, ns)  # noqa: S102 — compiling the user's own source
    new_fn = ns[fdef.name]
    new_fn.__wrapped_by_dy2static__ = fn
    return new_fn


def convert_function(fn: Callable) -> Callable:
    """AST-convert ``fn`` (best effort). Returns the original function when
    nothing was converted or the source is unavailable."""
    if getattr(fn, "_not_to_static", False):
        return fn
    target = fn
    bound_self = getattr(fn, "__self__", None)
    if bound_self is not None:
        target = fn.__func__
    converted = _convert_cached(target)
    if converted is None:
        return fn
    if bound_self is not None:
        return converted.__get__(bound_self, type(bound_self))
    return converted
