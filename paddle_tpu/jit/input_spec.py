"""InputSpec — declared input signature for tracing.

Parity: python/paddle/static/input.py InputSpec in the reference. A None dim
means "polymorphic": we trace per concrete size and cache (XLA requires
static shapes; the cache gives the same UX).
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..dtype import convert_dtype

__all__ = ["InputSpec"]


class InputSpec:
    def __init__(self, shape: Sequence[Optional[int]], dtype="float32", name: Optional[str] = None):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor._data.shape), str(tensor._data.dtype), name)

    def compatible_with(self, arr) -> bool:
        if len(arr.shape) != len(self.shape):
            return False
        return all(s == -1 or s == a for s, a in zip(self.shape, arr.shape))
