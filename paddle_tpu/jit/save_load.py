"""jit.save / jit.load — deployable model export.

Parity: the reference's jit.save → .pdmodel (ProgramDesc) + .pdiparams
(/root/reference/python/paddle/fluid/dygraph/jit.py:529 save, :901 load,
dygraph/io.py INFER_MODEL_SUFFIX).

TPU-native: the serialized program IR is StableHLO via jax.export (versioned,
cross-release stable) instead of ProgramDesc protobuf; parameters are stored
as an .npz archive. ``load`` returns a ``TranslatedLayer`` whose forward
executes the deserialized StableHLO — runnable without the original Python
model code, exactly like the reference's TranslatedLayer.
"""
from __future__ import annotations

import io as _io
import json
import os
import pickle
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dtype import to_jax_dtype
from ..nn.layer import Layer
from ..tensor import Tensor
from .input_spec import InputSpec
from .static_function import StaticFunction

__all__ = ["save", "load", "TranslatedLayer"]

MODEL_SUFFIX = ".pdmodel"  # serialized StableHLO
PARAMS_SUFFIX = ".pdiparams"  # npz of params+buffers
META_SUFFIX = ".pdmeta"  # json metadata


def _example_arrays(input_spec: Sequence[InputSpec]):
    """Concrete arrays for tracing the cache path (batch=-1 -> 1)."""
    arrs = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            arrs.append(spec._data)
            continue
        shape = tuple(1 if s == -1 else s for s in spec.shape)
        arrs.append(jnp.zeros(shape, to_jax_dtype(spec.dtype)))
    return arrs


def _symbolic_specs(input_spec: Sequence[InputSpec]):
    """ShapeDtypeStructs with symbolic dims for every -1 (jax.export shape
    polymorphism — the reference keeps -1 dims symbolic in ProgramDesc too)."""
    n_sym = 0
    names = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            continue
        for s in spec.shape:
            if s == -1:
                names.append(f"d{n_sym}")
                n_sym += 1
    syms = list(jax.export.symbolic_shape(",".join(names))) if names else []
    it = iter(syms)
    out = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            out.append(jax.ShapeDtypeStruct(tuple(spec._data.shape), spec._data.dtype))
            continue
        shape = tuple(next(it) if s == -1 else s for s in spec.shape)
        out.append(jax.ShapeDtypeStruct(shape, to_jax_dtype(spec.dtype)))
    return out


def save(layer, path: str, input_spec: Optional[Sequence] = None, **config):
    """Export ``layer`` (or a StaticFunction) for deployment."""
    if isinstance(layer, Layer):
        fwd = layer.forward
        sf = fwd if isinstance(fwd, StaticFunction) else StaticFunction(fwd, layer=layer)
        if input_spec is None and getattr(sf, "_input_spec", None) is not None:
            input_spec = sf._input_spec
        if input_spec is None:
            raise ValueError("jit.save of a Layer requires input_spec")
        params = {n: p._data for n, p in layer.named_parameters()}
        buffers = {n: b._data for n, b in layer.named_buffers()}
        was_training = layer.training
        layer.eval()
        try:
            arrays = _example_arrays(input_spec)
            flat_template = list(arrays)
            entry = sf._build(flat_template, jax.tree_util.tree_structure(
                tuple(Tensor(a) for a in arrays), is_leaf=lambda x: isinstance(x, Tensor)
            ), list(range(len(arrays))), {})
            jitted, cell = entry
            key = jax.random.key(0)
            specs = _symbolic_specs(input_spec)
            param_specs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype) for n, a in params.items()}
            buffer_specs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype) for n, a in buffers.items()}
            key_spec = jax.ShapeDtypeStruct(key.shape, key.dtype)
            exported = jax.export.export(jitted)(param_specs, buffer_specs, key_spec, *specs)
        finally:
            if was_training:
                layer.train()
        blob = exported.serialize()
        param_names = sorted(params)
        buffer_names = sorted(buffers)
        with open(path + PARAMS_SUFFIX, "wb") as f:
            np.savez(
                f,
                **{f"p:{n}": np.asarray(params[n]) for n in param_names},
                **{f"b:{n}": np.asarray(buffers[n]) for n in buffer_names},
            )
        with open(path + MODEL_SUFFIX, "wb") as f:
            f.write(blob)
        meta = {
            "params": param_names,
            "buffers": buffer_names,
            "input_shapes": [list(np.asarray(a).shape) for a in arrays],
            "input_dtypes": [str(a.dtype) for a in arrays],
        }
        with open(path + META_SUFFIX, "w") as f:
            json.dump(meta, f)
        return
    raise TypeError(f"jit.save expects a Layer, got {type(layer)}")


class TranslatedLayer(Layer):
    """A loaded, code-free model (parity: dygraph/io.py TranslatedLayer)."""

    def __init__(self, exported, params, buffers):
        super().__init__()
        self._exported = exported
        from ..nn.layer import Parameter

        self._loaded_params = {}
        for n, arr in params.items():
            p = Parameter(arr)
            p.name = n
            self.add_parameter(n.replace(".", "__"), p)
            self._loaded_params[n] = p
        self._loaded_buffers = {}
        for n, arr in buffers.items():
            t = self.register_buffer(n.replace(".", "__"), Tensor(jnp.asarray(arr)))
            self._loaded_buffers[n] = t

    def forward(self, *inputs):
        arrays = [i._data if isinstance(i, Tensor) else jnp.asarray(i) for i in inputs]
        params = {n: p._data for n, p in self._loaded_params.items()}
        buffers = {n: b._data for n, b in self._loaded_buffers.items()}
        key = jax.random.key(0)
        out_arrays, _new_buffers = self._exported.call(params, buffers, key, *arrays)
        outs = [Tensor(a) for a in out_arrays]
        return outs[0] if len(outs) == 1 else tuple(outs)


def load(path: str):
    with open(path + MODEL_SUFFIX, "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path + META_SUFFIX) as f:
        meta = json.load(f)
    data = np.load(path + PARAMS_SUFFIX)
    params = {n: jnp.asarray(data[f"p:{n}"]) for n in meta["params"]}
    buffers = {n: jnp.asarray(data[f"b:{n}"]) for n in meta["buffers"]}
    return TranslatedLayer(exported, params, buffers)
