"""jit.save / jit.load — deployable model export.

Parity: the reference's jit.save → .pdmodel (ProgramDesc) + .pdiparams
(/root/reference/python/paddle/fluid/dygraph/jit.py:529 save, :901 load,
dygraph/io.py INFER_MODEL_SUFFIX).

TPU-native: the serialized program IR is StableHLO via jax.export (versioned,
cross-release stable) instead of ProgramDesc protobuf; parameters are stored
as an .npz archive. ``load`` returns a ``TranslatedLayer`` whose forward
executes the deserialized StableHLO — runnable without the original Python
model code, exactly like the reference's TranslatedLayer.
"""
from __future__ import annotations

import io as _io
import json
import os
import pickle
from typing import Optional, Sequence

import jax
import jax.export  # noqa: F401  (0.4.x: jax.export is NOT auto-imported —
#                    bare `jax.export.export` raises AttributeError there)
import jax.numpy as jnp
import numpy as np

from ..dtype import to_jax_dtype
from ..nn.layer import Layer
from ..tensor import Tensor
from .input_spec import InputSpec
from .static_function import StaticFunction

__all__ = ["save", "load", "TranslatedLayer"]

MODEL_SUFFIX = ".pdmodel"  # serialized StableHLO
PARAMS_SUFFIX = ".pdiparams"  # npz of params+buffers
META_SUFFIX = ".pdmeta"  # json metadata


def _example_arrays(input_spec: Sequence[InputSpec]):
    """Concrete arrays for tracing the cache path (batch=-1 -> 1)."""
    arrs = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            arrs.append(spec._data)
            continue
        shape = tuple(1 if s == -1 else s for s in spec.shape)
        arrs.append(jnp.zeros(shape, to_jax_dtype(spec.dtype)))
    return arrs


def _symbolic_specs(input_spec: Sequence[InputSpec]):
    """ShapeDtypeStructs with symbolic dims for every -1 (jax.export shape
    polymorphism — the reference keeps -1 dims symbolic in ProgramDesc too)."""
    n_sym = 0
    names = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            continue
        for s in spec.shape:
            if s == -1:
                names.append(f"d{n_sym}")
                n_sym += 1
    syms = list(jax.export.symbolic_shape(",".join(names))) if names else []
    it = iter(syms)
    out = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            out.append(jax.ShapeDtypeStruct(tuple(spec._data.shape), spec._data.dtype))
            continue
        shape = tuple(next(it) if s == -1 else s for s in spec.shape)
        out.append(jax.ShapeDtypeStruct(shape, to_jax_dtype(spec.dtype)))
    return out


def save(layer, path: str, input_spec: Optional[Sequence] = None, **config):
    """Export ``layer`` (or a StaticFunction) for deployment.

    ``precision="bfloat16"|"float16"``: inference-optimization pass — float
    params/buffers are cast before export (weight-precision export: halves
    parameter memory and load bandwidth; matmuls run in XLA mixed
    precision). The reference analog is the analysis-pass pipeline's TRT
    fp16 mode.

    ``precision="int8"``: weight-only PTQ for the ARTIFACT (reference
    post-training quantization role): float matrix params are stored as
    per-output-channel symmetric int8 + scales (4x smaller file) and
    dequantized to float at load — the program itself still runs at its
    traced float precision."""
    precision = config.pop("precision", None)
    # reference-parity keys accepted as no-ops (XLA owns pruning/combining)
    for k in ("output_spec", "combine_params", "clip_extra", "skip_forward"):
        config.pop(k, None)
    if config:
        raise TypeError(f"jit.save got unknown options: {sorted(config)}")
    if isinstance(layer, Layer):
        fwd = layer.forward
        sf = fwd if isinstance(fwd, StaticFunction) else StaticFunction(fwd, layer=layer)
        if input_spec is None and getattr(sf, "_input_spec", None) is not None:
            input_spec = sf._input_spec
        if input_spec is None:
            raise ValueError("jit.save of a Layer requires input_spec")
        params = {n: p._data for n, p in layer.named_parameters()}
        buffers = {n: b._data for n, b in layer.named_buffers()}
        int8_weights = precision == "int8"
        if precision is not None and not int8_weights:
            dt = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
                  "float16": jnp.float16, "fp16": jnp.float16}.get(precision)
            if dt is None:
                raise ValueError(f"unknown export precision {precision!r}")
            cast = lambda a: a.astype(dt) if jnp.issubdtype(a.dtype, jnp.floating) else a
            params = {n: cast(a) for n, a in params.items()}
            buffers = {n: cast(a) for n, a in buffers.items()}
        was_training = layer.training
        layer.eval()
        try:
            arrays = _example_arrays(input_spec)
            flat_template = list(arrays)
            entry = sf._build(flat_template, jax.tree_util.tree_structure(
                tuple(Tensor(a) for a in arrays), is_leaf=lambda x: isinstance(x, Tensor)
            ), list(range(len(arrays))), {})
            jitted, cell = entry
            key = jax.random.PRNGKey(0)  # raw uint32 key: typed key dtypes don't serialize through 0.4.x jax.export
            specs = _symbolic_specs(input_spec)
            param_specs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype) for n, a in params.items()}
            buffer_specs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype) for n, a in buffers.items()}
            key_spec = jax.ShapeDtypeStruct(key.shape, key.dtype)
            exported = jax.export.export(jitted)(param_specs, buffer_specs, key_spec, *specs)
        finally:
            if was_training:
                layer.train()
        blob = exported.serialize()
        param_names = sorted(params)
        buffer_names = sorted(buffers)

        def _store(a):
            # np.savez writes bf16/fp16-ml_dtypes as raw void: view as u16
            # and record the dtype for the loader
            a = np.asarray(a)
            if a.dtype.kind == "V" or str(a.dtype) in ("bfloat16",):
                return a.view(np.uint16), str(jnp.asarray(a).dtype)
            return a, None

        cast_dtypes = {}
        int8_scales = {}
        blobs = {}
        for prefix, names, src_tree in (("p", param_names, params),
                                        ("b", buffer_names, buffers)):
            for n in names:
                key = f"{prefix}:{n}"
                a = np.asarray(src_tree[n])
                if (int8_weights and prefix == "p" and a.ndim >= 2
                        and a.dtype in (np.float32, np.float64)):
                    # per-output-channel symmetric int8 (reference abs-max
                    # weight quantization): the output axis is LAST for 2-D
                    # Linear (in, out) and FIRST for conv (cout, cin, kh, kw)
                    ch_axis = a.ndim - 1 if a.ndim == 2 else 0
                    red = tuple(i for i in range(a.ndim) if i != ch_axis)
                    amax = np.abs(a).max(axis=red, keepdims=True)
                    scale = np.maximum(amax, 1e-8) / 127.0
                    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
                    blobs[key] = q
                    int8_scales[key] = [scale.squeeze().tolist(),
                                        str(a.dtype), ch_axis]
                    continue
                arr, cdt = _store(a)
                blobs[key] = arr
                if cdt:
                    cast_dtypes[key] = cdt
        for key, (scale, _dt, _ax) in int8_scales.items():
            blobs[f"s:{key}"] = np.asarray(scale, np.float32)
        with open(path + PARAMS_SUFFIX, "wb") as f:
            np.savez(f, **blobs)
        with open(path + MODEL_SUFFIX, "wb") as f:
            f.write(blob)
        # output arity = leaves of the (outs, new_buffers) return's first
        # child (lets loaders resolve fetch names before the first run)
        try:
            n_outputs = exported.out_tree.children()[0].num_leaves
        except Exception:
            n_outputs = None
        meta = {
            "params": param_names,
            "buffers": buffer_names,
            "cast_dtypes": cast_dtypes,
            "int8_scales": {k: [v[1], v[2]] for k, v in int8_scales.items()},
            "input_shapes": [list(np.asarray(a).shape) for a in arrays],
            "input_dtypes": [str(a.dtype) for a in arrays],
            "n_outputs": n_outputs,
        }
        with open(path + META_SUFFIX, "w") as f:
            json.dump(meta, f)
        return
    raise TypeError(f"jit.save expects a Layer, got {type(layer)}")


class TranslatedLayer(Layer):
    """A loaded, code-free model (parity: dygraph/io.py TranslatedLayer)."""

    def __init__(self, exported, params, buffers):
        super().__init__()
        self._exported = exported
        from ..nn.layer import Parameter

        self._loaded_params = {}
        for n, arr in params.items():
            p = Parameter(arr)
            p.name = n
            self.add_parameter(n.replace(".", "__"), p)
            self._loaded_params[n] = p
        self._loaded_buffers = {}
        for n, arr in buffers.items():
            t = self.register_buffer(n.replace(".", "__"), Tensor(jnp.asarray(arr)))
            self._loaded_buffers[n] = t

    def forward(self, *inputs):
        arrays = [i._data if isinstance(i, Tensor) else jnp.asarray(i) for i in inputs]
        params = {n: p._data for n, p in self._loaded_params.items()}
        buffers = {n: b._data for n, b in self._loaded_buffers.items()}
        key = jax.random.PRNGKey(0)  # raw uint32 key: typed key dtypes don't serialize through 0.4.x jax.export
        out_arrays, _new_buffers = self._exported.call(params, buffers, key, *arrays)
        outs = [Tensor(a) for a in out_arrays]
        return outs[0] if len(outs) == 1 else tuple(outs)


def load(path: str):
    with open(path + MODEL_SUFFIX, "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path + META_SUFFIX) as f:
        meta = json.load(f)
    data = np.load(path + PARAMS_SUFFIX)
    cast = meta.get("cast_dtypes", {})
    int8 = meta.get("int8_scales", {})

    def _restore(key):
        arr = data[key]
        if key in int8:
            dtype, ch_axis = int8[key]
            scale = np.asarray(data[f"s:{key}"], np.float32)
            shape = [1] * arr.ndim
            shape[ch_axis] = -1
            scale = scale.reshape(shape)
            return jnp.asarray((arr.astype(np.float32) * scale).astype(dtype))
        if key in cast:
            import ml_dtypes

            return jnp.asarray(arr.view(getattr(ml_dtypes, cast[key])))
        return jnp.asarray(arr)

    params = {n: _restore(f"p:{n}") for n in meta["params"]}
    buffers = {n: _restore(f"b:{n}") for n in meta["buffers"]}
    return TranslatedLayer(exported, params, buffers)
