"""Concrete optimizers.

Parity: python/paddle/optimizer/{sgd,momentum,adam,adamw,adamax,adagrad,
adadelta,rmsprop,lamb}.py and the reference CUDA kernels in
/root/reference/paddle/fluid/operators/optimizers/ (sgd_op, momentum_op,
adam_op.cu, lamb_op, lars_momentum_op, adadelta_op, adagrad_op, rmsprop_op).
Update math follows the reference ops exactly (e.g. paddle momentum's
velocity = mu*v + g; p -= lr * (g + mu*v_new) when use_nesterov).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = [
    "SGD",
    "Momentum",
    "Adam",
    "AdamW",
    "Adamax",
    "Adagrad",
    "Adadelta",
    "RMSProp",
    "Lamb",
    "Lars",
    "Ftrl",
    "Dpsgd",
    "ProximalGD",
    "ProximalAdagrad",
    "DecayedAdagrad",
]


class SGD(Optimizer):
    _slot_names = ()

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _hyper(self):
        return ()

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        return (p - lr.astype(p.dtype) * g), slots


class Momentum(Optimizer):
    _slot_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = float(momentum)
        self._use_nesterov = bool(use_nesterov)

    def _hyper(self):
        return (self._momentum, self._use_nesterov)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        mu, nesterov = hyper
        v = mu * slots["velocity"] + g
        if nesterov:
            p_new = p - lr.astype(p.dtype) * (g + mu * v)
        else:
            p_new = p - lr.astype(p.dtype) * v
        return p_new, {"velocity": v}


class Adam(Optimizer):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, moment_dtype="float32", **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, multi_precision)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)
        # bf16 moments halve optimizer-state HBM (update math stays f32;
        # the slot dtype drives the cast in _update)
        self._moment_dtype = jnp.dtype(moment_dtype)

    def _hyper(self):
        return (self._beta1, self._beta2, self._epsilon, 0.0)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        b1, b2, eps, wd = hyper
        g32 = g.astype(jnp.float32)
        m = b1 * slots["moment1"].astype(jnp.float32) + (1 - b1) * g32
        v = b2 * slots["moment2"].astype(jnp.float32) + (1 - b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1**t)
        vhat = v / (1 - b2**t)
        upd = mhat / (jnp.sqrt(vhat) + eps)
        if wd:
            upd = upd + wd * p.astype(jnp.float32)
        p_new = (p.astype(jnp.float32) - lr * upd).astype(p.dtype)
        return p_new, {"moment1": m.astype(slots["moment1"].dtype),
                       "moment2": v.astype(slots["moment2"].dtype)}

    def _init_slots(self, param_arr):
        return {n: jnp.zeros(param_arr.shape, self._moment_dtype)
                for n in self._slot_names}


class AdamW(Adam):
    """Decoupled weight decay (reference: adamw applies decay on param
    directly, python/paddle/optimizer/adamw.py)."""

    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None,
                 moment_dtype="float32", **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip,
                         lazy_mode, multi_precision, name, moment_dtype=moment_dtype)
        self._wd = float(weight_decay) if isinstance(weight_decay, (int, float)) else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun

    def _hyper(self):
        return (self._beta1, self._beta2, self._epsilon, self._wd)

    def _hyper_no_decay(self):
        return (self._beta1, self._beta2, self._epsilon, 0.0)

    def _decay_grad(self, p, g):
        return g  # decay handled inside _update (decoupled)

    def _hyper_for(self, p):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            return self._hyper_no_decay()
        return self._hyper()


class Adamax(Optimizer):
    _slot_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)

    def _hyper(self):
        return (self._beta1, self._beta2, self._epsilon)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        b1, b2, eps = hyper
        m = b1 * slots["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(g) + eps)
        t = step.astype(jnp.float32)
        lr_t = (lr / (1 - b1**t)).astype(p.dtype)
        p_new = p - lr_t * m / u
        return p_new, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    _slot_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = float(epsilon)
        self._init_val = float(initial_accumulator_value)

    def _hyper(self):
        return (self._epsilon,)

    def _init_slots(self, param_arr):
        return {"moment": jnp.full_like(param_arr, self._init_val)}

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        (eps,) = hyper
        m = slots["moment"] + jnp.square(g)
        p_new = p - lr.astype(p.dtype) * g / (jnp.sqrt(m) + eps)
        return p_new, {"moment": m}


class Adadelta(Optimizer):
    _slot_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = float(epsilon), float(rho)

    def _hyper(self):
        return (self._epsilon, self._rho)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        eps, rho = hyper
        sg = rho * slots["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        upd = g * jnp.sqrt(slots["avg_squared_update"] + eps) / jnp.sqrt(sg + eps)
        su = rho * slots["avg_squared_update"] + (1 - rho) * jnp.square(upd)
        return p - lr.astype(p.dtype) * upd, {"avg_squared_grad": sg, "avg_squared_update": su}


class RMSProp(Optimizer):
    _slot_names = ("mean_square", "mean_grad", "momentum")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = float(rho), float(epsilon)
        self._momentum, self._centered = float(momentum), bool(centered)

    def _hyper(self):
        return (self._rho, self._epsilon, self._momentum, self._centered)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        rho, eps, mom, centered = hyper
        ms = rho * slots["mean_square"] + (1 - rho) * jnp.square(g)
        if centered:
            mg = rho * slots["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            mg = slots["mean_grad"]
            denom = jnp.sqrt(ms + eps)
        v = mom * slots["momentum"] + lr.astype(p.dtype) * g / denom
        return p - v, {"mean_square": ms, "mean_grad": mg, "momentum": v}


class Lamb(Optimizer):
    """LAMB (reference: lamb_op.cu + python/paddle/optimizer/lamb.py)."""

    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)
        self._lamb_wd = float(lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def _hyper(self):
        return (self._beta1, self._beta2, self._epsilon, self._lamb_wd)

    def _hyper_no_decay(self):
        return (self._beta1, self._beta2, self._epsilon, 0.0)

    def _hyper_for(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return self._hyper_no_decay()
        return self._hyper()

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        b1, b2, eps, wd = hyper
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = b1 * slots["moment1"] + (1 - b1) * g32
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1**t)
        vhat = v / (1 - b2**t)
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        p_new = (p32 - lr * trust * r).astype(p.dtype)
        return p_new, {"moment1": m, "moment2": v}

    def _init_slots(self, param_arr):
        return {n: jnp.zeros(param_arr.shape, jnp.float32) for n in self._slot_names}


class Lars(Optimizer):
    """LARS momentum (reference: lars_momentum_op.cu; fleet lars meta-opt)."""

    _slot_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = float(momentum)
        self._lars_coeff = float(lars_coeff)
        self._lars_wd = float(lars_weight_decay)
        self._eps = float(epsilon)
        self._exclude = list(exclude_from_weight_decay or [])

    def _hyper(self):
        return (self._momentum, self._lars_coeff, self._lars_wd, self._eps)

    def _hyper_no_decay(self):
        return (self._momentum, self._lars_coeff, 0.0, self._eps)

    def _hyper_for(self, p):
        name = p.name or ""
        if any(token in name for token in self._exclude):
            return self._hyper_no_decay()
        return self._hyper()

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        mu, coeff, wd, eps = hyper
        p32, g32 = p.astype(jnp.float32), g.astype(jnp.float32)
        p_norm = jnp.linalg.norm(p32)
        g_norm = jnp.linalg.norm(g32)
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            coeff * p_norm / (g_norm + wd * p_norm + eps),
            1.0,
        )
        v = mu * slots["velocity"] + lr * local_lr * (g32 + wd * p32)
        return (p32 - v).astype(p.dtype), {"velocity": v}


class Ftrl(Optimizer):
    """FTRL-proximal (reference: operators/optimizers/ftrl_op.h FTRLFunctor;
    python FtrlOptimizer). State: squared accumulator n and linear
    accumulator z; the closed-form proximal step zeroes weights whose
    |z| <= l1 (the sparsity-inducing part)."""

    _slot_names = ("squared_accum", "linear_accum")

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._l1, self._l2 = float(l1), float(l2)
        self._lr_power = float(lr_power)
        self._init_val = float(initial_accumulator_value)

    def _hyper(self):
        return (self._l1, self._l2, self._lr_power)

    def _init_slots(self, param_arr):
        return {"squared_accum": jnp.full_like(param_arr, self._init_val),
                "linear_accum": jnp.zeros_like(param_arr)}

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        l1, l2, lr_power = hyper
        lr = lr.astype(p.dtype)
        n, z = slots["squared_accum"], slots["linear_accum"]
        n_new = n + jnp.square(g)
        if lr_power == -0.5:
            sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
            y = jnp.sqrt(n_new) / lr + 2.0 * l2
        else:
            sigma = (jnp.power(n_new, -lr_power) - jnp.power(n, -lr_power)) / lr
            y = jnp.power(n_new, -lr_power) / lr + 2.0 * l2
        z_new = z + g - sigma * p
        x = jnp.sign(z_new) * l1 - z_new
        p_new = jnp.where(jnp.abs(z_new) > l1, x / y, jnp.zeros_like(p))
        return p_new, {"squared_accum": n_new, "linear_accum": z_new}


class Dpsgd(Optimizer):
    """Differentially-private SGD (reference: optimizers/dpsgd_op.h; CCS16
    "Deep Learning with Differential Privacy"): per-step global-L2 clip of
    the gradient to ``clip`` then one gaussian noise draw scaled by
    sigma/batch_size added to every element. RNG: jax threefry keyed by
    (seed, step) instead of the reference's Box-Muller over minstd_rand —
    same distribution, reproducible under jit."""

    _slot_names = ()

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, parameters=None, weight_decay=None,
                 grad_clip=None, seed=1, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._clip, self._batch = float(clip), float(batch_size)
        self._sigma, self._seed = float(sigma), int(seed)

    def _hyper(self):
        return (self._clip, self._batch, self._sigma, self._seed)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        import jax

        clip, batch, sigma, seed = hyper
        l2_norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.where(l2_norm > clip, l2_norm / clip, 1.0).astype(g.dtype)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        noise = (jax.random.normal(key, (), jnp.float32) * sigma).astype(g.dtype)
        return p - lr.astype(p.dtype) * (g / scale + noise / batch), slots


class ProximalGD(Optimizer):
    """Proximal gradient descent with l1/l2 regularisation (reference:
    optimizers/proximal_gd_op.h): soft-threshold the plain GD step."""

    _slot_names = ()

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._l1, self._l2 = float(l1), float(l2)

    def _hyper(self):
        return (self._l1, self._l2)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        l1, l2 = hyper
        lr = lr.astype(p.dtype)
        prox = p - lr * g
        if l1 > 0:
            p_new = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
                     / (1.0 + lr * l2))
        else:
            p_new = prox / (1.0 + lr * l2)
        return p_new, slots


class ProximalAdagrad(Optimizer):
    """Proximal Adagrad (reference: optimizers/proximal_adagrad_op.h):
    adagrad-scaled step followed by the same l1/l2 proximal shrink."""

    _slot_names = ("moment",)

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._l1, self._l2 = float(l1), float(l2)
        self._init_val = float(initial_accumulator_value)

    def _hyper(self):
        return (self._l1, self._l2)

    def _init_slots(self, param_arr):
        return {"moment": jnp.full_like(param_arr, self._init_val)}

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        l1, l2 = hyper
        lr = lr.astype(p.dtype)
        m = slots["moment"] + jnp.square(g)
        prox = p - lr * g / jnp.sqrt(m)
        if l1 > 0:
            p_new = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
                     / (1.0 + lr * l2))
        else:
            p_new = prox / (1.0 + lr * l2)
        return p_new, {"moment": m}


class DecayedAdagrad(Optimizer):
    """Decayed Adagrad (reference: optimizers/decayed_adagrad_op.h):
    moment = decay*moment + (1-decay)*g^2 — adagrad with a forgetting
    rate so the effective lr doesn't collapse."""

    _slot_names = ("moment",)

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._decay, self._epsilon = float(decay), float(epsilon)

    def _hyper(self):
        return (self._decay, self._epsilon)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        decay, eps = hyper
        m = decay * slots["moment"] + (1 - decay) * jnp.square(g)
        p_new = p - lr.astype(p.dtype) * g / (jnp.sqrt(m) + eps)
        return p_new, {"moment": m}
