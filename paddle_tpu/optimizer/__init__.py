"""paddle_tpu.optimizer — optimizers + LR schedulers.

Parity: python/paddle/optimizer/__init__.py.
"""
from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    DecayedAdagrad,
    Dpsgd,
    Ftrl,
    Lamb,
    Lars,
    Momentum,
    ProximalAdagrad,
    ProximalGD,
    RMSProp,
)
