"""Optimizer base.

Parity: python/paddle/optimizer/optimizer.py + the reference's per-op GPU
optimizer kernels (/root/reference/paddle/fluid/operators/optimizers/).

TPU-native two-level design:
- **eager**: ``opt.step()`` reads ``param.grad`` slots and applies a jitted
  pure update per parameter (XLA caches by shape — the dygraph path).
- **functional**: ``init_state(params)`` / ``apply_gradients(params, grads,
  state, lr)`` operate on pytrees of arrays, for use inside jit/pjit train
  steps; sharding the state pytree on the 'fsdp' axis IS ZeRO-1 (SURVEY §2.7).
Both levels share the same ``_update`` math, so eager and jitted training are
bit-identical.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    # subclasses define: _slot_names: tuple[str,...]; _update(...) staticmethod
    _slot_names: tuple = ()
    # True when _update applies weight decay itself (AdamW-style decoupled
    # decay): functional callers must then NOT fold decay into the grad
    _decoupled_wd: bool = False

    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        name=None,
        multi_precision=False,
    ):
        self._parameter_list = list(parameters) if parameters is not None else None
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._name = name
        self._multi_precision = multi_precision
        if weight_decay is None:
            self._weight_decay_coeff = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay_coeff = float(weight_decay)
        else:  # L2Decay-like object with _coeff / _regularization_coeff
            self._weight_decay_coeff = float(
                getattr(weight_decay, "_regularization_coeff", getattr(weight_decay, "_coeff", 0.0))
            )
        self._accumulators: Dict[int, Dict[str, jax.Array]] = {}
        self._global_step = 0
        self._jit_update = jax.jit(type(self)._update, static_argnames=("hyper",))

    def _hyper_no_decay(self):
        """Hyper tuple for no-decay params. Optimizers that pack a
        weight-decay coefficient into ``_hyper()`` (AdamW, Lamb, Lars)
        override this to zero that slot; callers must use this instead of
        assuming the decay coefficient's position in the tuple."""
        return self._hyper()

    # ------------------------------------------------------------------
    # lr
    # ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("optimizer's learning rate is an LRScheduler; call scheduler.step()")
        self._learning_rate = float(value)

    # ------------------------------------------------------------------
    # hyper / slots — subclass API
    # ------------------------------------------------------------------
    def _hyper(self) -> tuple:
        """Static hyper-parameters baked into the jitted update."""
        return ()

    def _hyper_for(self, param) -> tuple:
        """Per-parameter hyper override (e.g. AdamW's apply_decay_param_fun).
        Distinct tuples retrace the shared jitted update once each and stay
        cached."""
        return self._hyper()

    def _init_slots(self, param_arr) -> Dict[str, jax.Array]:
        return {name: jnp.zeros_like(param_arr) for name in self._slot_names}

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        """Pure: (param, grad, slots dict, lr, step, hyper tuple) ->
        (new_param, new_slots). Implemented by subclasses."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    # eager path
    # ------------------------------------------------------------------
    def _decay_grad(self, p, g):
        """L2 regularization into the gradient (reference: regularizer applied
        in append_regularization_ops). AdamW overrides for decoupled decay."""
        if self._weight_decay_coeff and getattr(p, "regularizer", None) is None:
            return g + self._weight_decay_coeff * p._data
        return g

    @property
    def _param_groups(self) -> List:
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without a parameters list")
        return self._parameter_list

    def step(self):
        params_grads = [(p, p.grad) for p in self._param_groups if p.grad is not None and not p.stop_gradient]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        self._global_step += 1
        for p, g in params_grads:
            if g is None:
                continue
            hyper = self._hyper_for(p)
            garr = g._data if isinstance(g, Tensor) else g
            garr = self._decay_grad(p, garr.astype(p._data.dtype))
            slots = self._accumulators.get(id(p))
            if slots is None:
                slots = self._init_slots(p._data)
                self._accumulators[id(p)] = slots
            p_lr = lr * getattr(p, "optimize_attr", {"learning_rate": 1.0}).get("learning_rate", 1.0)
            new_p, new_slots = self._jit_update(
                p._data, garr, slots, jnp.asarray(p_lr, jnp.float32),
                jnp.asarray(self._global_step, jnp.int32), hyper,
            )
            p._set_data(new_p)
            self._accumulators[id(p)] = new_slots

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._param_groups:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.program import Variable as _StaticVariable

        if isinstance(loss, _StaticVariable):
            # static paradigm: attach this optimizer to the program — the
            # Executor compiles forward+backward+update into one XLA step
            # (parity: static minimize appending backward + optimizer ops).
            # A parameter-less optimizer (the standard static idiom) falls
            # back to every trainable capture of the program.
            if parameters is not None:
                params = parameters
            elif self._parameter_list is not None:
                params = self._param_groups
            else:
                params = [t for (t, _) in loss._program.captures() if t.trainable]
            return loss._program._set_optimizer(self, loss, params)
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._param_groups]

    def _on_static_step(self):
        """Called by the static Executor after each optimized run."""
        self._global_step += 1

    # ------------------------------------------------------------------
    # functional path (jit/pjit training)
    # ------------------------------------------------------------------
    def init_state(self, params_tree):
        """params_tree: pytree of arrays -> state pytree {slots, step}."""
        slots = jax.tree_util.tree_map(lambda p: self._init_slots(p), params_tree)
        return {"slots": slots, "step": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, params_tree, grads_tree, state, lr=None):
        """Pure pytree update; returns (new_params, new_state)."""
        from ..nn.clip import clip_grads_functional

        lr = self.get_lr() if lr is None else lr
        hyper = self._hyper()
        step = state["step"] + 1
        grads_tree = clip_grads_functional(self._grad_clip, grads_tree)
        wd = self._weight_decay_coeff

        def upd(p, g, slots):
            g = g.astype(p.dtype)
            if wd and not self._decoupled_wd:
                g = g + wd * p
            return type(self)._update(p, g, slots, jnp.asarray(lr, jnp.float32), step, hyper)

        flat_p, treedef = jax.tree_util.tree_flatten(params_tree)
        flat_g = treedef.flatten_up_to(grads_tree)
        flat_s = treedef.flatten_up_to(state["slots"])
        new_p, new_s = [], []
        for p, g, s in zip(flat_p, flat_g, flat_s):
            np_, ns_ = upd(p, g, s)
            new_p.append(np_)
            new_s.append(ns_)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"slots": jax.tree_util.tree_unflatten(treedef, new_s), "step": step},
        )

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------
    def _param_key(self, p, i: int) -> str:
        return p.name if p.name else f"param_{i}"

    def state_dict(self):
        sd = {}
        for i, p in enumerate(self._param_groups):
            slots = self._accumulators.get(id(p))
            if slots:
                for k, v in slots.items():
                    sd[f"{self._param_key(p, i)}.{k}"] = Tensor(v)
        sd["global_step"] = self._global_step
        # positional alias so a restore can match slots even when the fresh
        # process assigned different auto-generated parameter names
        sd["__param_order__"] = [
            self._param_key(p, i) for i, p in enumerate(self._param_groups)
        ]
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("global_step", 0))
        if isinstance(self._learning_rate, LRScheduler) and "LR_Scheduler" in state_dict:
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        order = state_dict.get("__param_order__")
        for i, p in enumerate(self._param_groups):
            base = self._param_key(p, i)
            if self._slot_names and f"{base}.{self._slot_names[0]}" not in state_dict \
                    and order and i < len(order):
                base = order[i]  # name skew: fall back to positional identity
            slots = {}
            for name in self._slot_names:
                key = f"{base}.{name}"
                if key in state_dict:
                    v = state_dict[key]
                    slots[name] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
            if slots:
                existing = self._accumulators.get(id(p), self._init_slots(p._data))
                existing.update(slots)
                self._accumulators[id(p)] = existing

    set_dict = set_state_dict
