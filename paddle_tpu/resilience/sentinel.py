"""In-step anomaly sentinel: jitted non-finite and loss-spike detection.

Parity: the reference's ``FLAGS_check_nan_inf`` device guards
(/root/reference/paddle/fluid/framework/details/nan_inf_utils_detail.* —
every op output is scanned for nan/inf and the run aborts) and the
``check_finite_and_unscale`` amp op. Both are reactive: the reference aborts
the process, and the GradScaler only notices a blow-up after the grads are
already non-finite.

TPU-native redesign: detection runs INSIDE the jitted train step, costs one
reduction over values the step already computed, and feeds a policy that is
itself pure computation:

* non-finite guard — loss/grad finiteness, one ``jnp.isfinite`` reduce;
* spike guard — rolling loss statistics (exponentially-weighted mean and
  variance) ride in the step carry; a finite loss that jumps more than
  ``spike_factor`` standard deviations above the rolling mean after
  ``warmup_steps`` clean observations is flagged;
* skip policy — the parameter/optimizer update is gated with ``jnp.where``
  (the same keep-machinery the in-graph GradScaler uses), so an anomalous
  step costs its compute but mutates nothing. With a GradScaler attached the
  anomaly is folded into its state machine, so spikes also shrink the loss
  scale (skip-and-rescale);
* halt / rollback — host policies applied by :class:`SentinelMonitor` from
  the returned sentinel state (the device step always skips; the monitor
  decides whether to additionally raise :class:`AnomalyHalt` or restore the
  newest intact snapshot).

When ``enabled`` is False the wiring contributes NOTHING to the trace — the
sentinel state is an empty pytree and no detection ops are emitted, so the
train step compiles to the identical jaxpr (the same zero-overhead bar the
r6 profiler meets; enforced by tests/test_resilience.py jaxpr-identity).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import jax.numpy as jnp

__all__ = [
    "SentinelConfig",
    "SentinelMonitor",
    "AnomalyHalt",
    "SENTINEL_OK",
    "SENTINEL_NONFINITE",
    "SENTINEL_SPIKE",
    "sentinel_init_state",
    "sentinel_observe",
    "sentinel_to_host",
]

SENTINEL_OK = 0
SENTINEL_NONFINITE = 1
SENTINEL_SPIKE = 2

_POLICIES = ("skip", "halt", "rollback")


@dataclasses.dataclass
class SentinelConfig:
    """Anomaly-sentinel knobs.

    ``policy`` names what happens AFTER the in-graph skip: ``"skip"`` does
    nothing more, ``"halt"`` makes the monitor raise :class:`AnomalyHalt`,
    ``"rollback"`` makes it call its restore hook. ``spike_factor`` is in
    rolling standard deviations; ``min_spike_delta`` is an absolute floor so
    a flat loss curve (tiny variance) does not flag noise."""

    enabled: bool = True
    policy: str = "skip"
    check_nonfinite: bool = True
    spike_factor: float = 8.0
    min_spike_delta: float = 0.0
    ema_beta: float = 0.95
    warmup_steps: int = 20
    # livelock escape: after this many CONSECUTIVE spike classifications the
    # elevated level is treated as a genuine regime change (new data domain,
    # LR ramp) — observations are absorbed into the statistics instead of
    # skipped forever. 0 disables absorption (spikes always skip).
    max_consecutive_spikes: int = 8

    def __post_init__(self):
        if self.policy not in _POLICIES:
            raise ValueError(
                f"sentinel policy must be one of {_POLICIES}, got "
                f"{self.policy!r}")
        if not (0.0 < self.ema_beta < 1.0):
            raise ValueError("ema_beta must be in (0, 1)")
        if self.max_consecutive_spikes < 0:
            raise ValueError("max_consecutive_spikes must be >= 0")


def sentinel_init_state() -> Dict[str, jnp.ndarray]:
    """Fresh rolling-statistics carry (all scalars; lives in the jitted
    step's donated state alongside the GradScaler's scale_state)."""
    return {
        "count": jnp.zeros((), jnp.int32),         # clean observations seen
        "ema_mean": jnp.zeros((), jnp.float32),
        "ema_var": jnp.zeros((), jnp.float32),
        "anomaly_count": jnp.zeros((), jnp.int32),
        "last_code": jnp.zeros((), jnp.int32),     # SENTINEL_* of last step
        "spike_streak": jnp.zeros((), jnp.int32),  # consecutive spikes seen
    }


def sentinel_observe(state, loss, grads_finite, config: SentinelConfig):
    """Pure observation: classify this step's loss and advance the rolling
    statistics. Returns ``(code, new_state)`` where ``code`` is a traced
    int32 scalar (SENTINEL_OK / _NONFINITE / _SPIKE).

    ``grads_finite``: optional traced bool (e.g. the GradScaler's finite
    flag) AND-ed into the non-finite guard so one reduction is shared.
    Anomalous steps do NOT update the statistics — a spike must not drag the
    mean up and mask the next one."""
    loss = loss.astype(jnp.float32)
    finite = jnp.isfinite(loss)
    if config.check_nonfinite and grads_finite is not None:
        finite = finite & grads_finite
    warmed = state["count"] >= config.warmup_steps
    std = jnp.sqrt(jnp.maximum(state["ema_var"], 0.0))
    threshold = config.spike_factor * std + config.min_spike_delta
    spike_raw = warmed & finite & (loss - state["ema_mean"] > threshold)
    # livelock escape: past the consecutive-spike cap the elevated level is
    # a regime change, not an anomaly — absorb it into the statistics (the
    # streak holds at the cap until a genuinely sub-threshold loss resets
    # it, so the whole shifted plateau is absorbed and the mean catches up)
    streak = state["spike_streak"]
    absorb = spike_raw & (config.max_consecutive_spikes > 0) & (
        streak >= config.max_consecutive_spikes)
    spike = spike_raw & ~absorb
    code = jnp.where(
        ~finite, SENTINEL_NONFINITE,
        jnp.where(spike, SENTINEL_SPIKE, SENTINEL_OK)).astype(jnp.int32)
    anomaly = code > 0

    # exponentially-weighted mean/variance (West's recurrence), frozen on
    # anomalous steps and seeded by the first clean observation
    incr = 1.0 - config.ema_beta
    first = state["count"] == 0
    delta = loss - state["ema_mean"]
    mean_upd = jnp.where(first, loss, state["ema_mean"] + incr * delta)
    var_upd = jnp.where(
        first, 0.0,
        (1.0 - incr) * (state["ema_var"] + incr * delta * delta))
    clean = ~anomaly
    new_state = {
        "count": state["count"] + clean.astype(jnp.int32),
        "ema_mean": jnp.where(clean, mean_upd, state["ema_mean"]),
        "ema_var": jnp.where(clean, var_upd, state["ema_var"]),
        "anomaly_count": state["anomaly_count"] + anomaly.astype(jnp.int32),
        "last_code": code,
        "spike_streak": jnp.where(
            spike, streak + 1,
            jnp.where(absorb, streak, 0)).astype(jnp.int32),
    }
    return code, new_state


def sentinel_to_host(state) -> Dict[str, float]:
    """Device state → plain python numbers (one host sync)."""
    return {
        "count": int(state["count"]),
        "ema_mean": float(state["ema_mean"]),
        "ema_var": float(state["ema_var"]),
        "anomaly_count": int(state["anomaly_count"]),
        "last_code": int(state["last_code"]),
        "spike_streak": int(state["spike_streak"]),
    }


class AnomalyHalt(RuntimeError):
    """Raised by the monitor under policy='halt' (FLAGS_check_nan_inf abort
    parity — but AFTER the in-graph skip kept the params clean)."""

    def __init__(self, report: Dict[str, float]):
        msg = (f"anomaly sentinel halt: {report['anomaly_count']} anomalous "
               f"step(s), last code {report['last_code']} "
               f"(1=non-finite, 2=loss spike)")
        san = report.get("sanitizer")
        if isinstance(san, dict) and san.get("first_nonfinite"):
            first = san["first_nonfinite"]
            msg += (f"; sanitizer: first non-finite at "
                    f"'{first.get('prim')}' {first.get('where', '')}")
        self.report = report
        super().__init__(msg)


class SentinelMonitor:
    """Host-side policy driver over the device sentinel state.

    Reading device scalars forces a sync, so the monitor polls every
    ``poll_every`` calls (the in-graph skip already protected the params on
    the anomalous step itself — the host reaction can lag). ``restore_fn``
    is the rollback hook (e.g. reload the newest intact snapshot into the
    trainer); after it runs the monitor re-bases its counter so the restored
    (older) anomaly_count is not itself treated as a new anomaly.

    ``sanitize_fn`` (off by default) is the bridge to the analysis
    sanitizer: a zero-arg callable that replays the captured failing step
    eqn-by-eqn (e.g. ``lambda: trainer.sanitize_step(x, y).to_dict()``) —
    the sentinel knows *something* went non-finite, the sanitizer answers
    *which eqn*.  Its result lands in the monitor's report under
    ``"sanitizer"`` (and in :class:`AnomalyHalt`'s message) on every
    anomaly reaction; failures are contained (the policy action must never
    be lost to a broken replay)."""

    def __init__(self, config: SentinelConfig,
                 restore_fn: Optional[Callable[[], None]] = None,
                 poll_every: int = 1,
                 sanitize_fn: Optional[Callable[[], Dict]] = None):
        if config.policy == "rollback" and restore_fn is None:
            raise ValueError("policy='rollback' needs a restore_fn")
        self.config = config
        self.restore_fn = restore_fn
        self.poll_every = max(int(poll_every), 1)
        self.sanitize_fn = sanitize_fn
        self.last_sanitize: Optional[Dict] = None
        self._calls = 0
        self._seen_anomalies: Optional[int] = 0

    def after_step(self, trainer) -> Optional[str]:
        """Convenience for ParallelTrainer loops: polls
        ``trainer.sentinel_state``."""
        return self.poll(trainer.sentinel_state)

    def poll(self, sentinel_state) -> Optional[str]:
        """Check the state every ``poll_every``-th call; returns the action
        taken ('skip' | 'rollback' | None), raises AnomalyHalt under
        policy='halt'."""
        self._calls += 1
        if not sentinel_state or self._calls % self.poll_every:
            return None
        host = sentinel_to_host(sentinel_state)
        if self._seen_anomalies is None:
            # first poll after a rollback: re-base, don't re-trigger
            self._seen_anomalies = host["anomaly_count"]
            return None
        if host["anomaly_count"] == self._seen_anomalies:
            return None
        self._seen_anomalies = host["anomaly_count"]
        if self.sanitize_fn is not None:
            try:
                self.last_sanitize = self.sanitize_fn()
            except Exception as e:  # the policy action must still happen
                self.last_sanitize = {
                    "error": f"{type(e).__name__}: {e}"}
            host["sanitizer"] = self.last_sanitize
        if self.config.policy == "halt":
            # freeze the flight record before the halt unwinds the loop:
            # the ring still holds the spans (and step note) leading in,
            # so the post-mortem names BOTH the eqn and the step. dump()
            # is exception-contained — the halt can never be lost to it.
            from ..observability.flight import flight_recorder

            flight_recorder().dump("sentinel_halt", extra=host)
            raise AnomalyHalt(host)
        if self.config.policy == "rollback":
            from ..observability.flight import flight_recorder

            flight_recorder().dump("sentinel_rollback", extra=host)
            self.restore_fn()
            self._seen_anomalies = None
            return "rollback"
        return "skip"
