"""Fault-tolerant training runtime.

Three layers, wired through the training stack:

* :mod:`.sentinel` — jitted in-step anomaly detection (non-finite + loss
  spike) with skip / halt / rollback policies; zero trace-level overhead
  when disabled. Wired into ``ParallelTrainer`` and the pipeline step.
* :mod:`.preemption` — SIGTERM/SIGINT + deadline-watchdog emergency
  synchronous checkpointing (step counter, RNG, scaler, optimizer state).
* :mod:`.retry` — exponential backoff with jitter, used by the elastic
  store so one transient failure never kills the heartbeat; plus the
  shared :class:`RetryBudget` (persistent faults fail fast process-wide).
* :mod:`.inject` — the deterministic fault-injection plane: seeded
  :class:`FaultSchedule`\\ s firing named faults at exact trigger counts
  through the store/checkpoint/engine/router/replica/rank seams, so every
  chaos scenario replays bit-identically without process signals.
* :mod:`.durability` — the replicated checkpoint data plane (r19): each
  elastic rank durably writes its own shard snapshot, replicates it to K
  peer ranks over the KV plane, and the snapshot becomes visible only
  when a manifest commits to the quorum store; scrub/quarantine/repair
  keep the redundancy factor, and an empty-disk replacement rank
  recovers entirely from peer replicas.

Parity: FLAGS_check_nan_inf, incubate.checkpoint.auto_checkpoint and the
fleet elastic etcd heartbeats, redesigned as a TPU-native runtime (see
PARITY.md "Fault tolerance").
"""
from .durability import (  # noqa: F401
    BlobCorruptionError,
    BlobTransport,
    CheckpointDataPlane,
    DurabilityConfig,
)
from .elastic_trainer import ElasticDPTrainer  # noqa: F401
from .inject import (  # noqa: F401
    FaultSchedule,
    FaultSpec,
    InjectedCrash,
    InjectedDeath,
    InjectedFault,
)
from .preemption import DEADLINE_ENV, PreemptionGuard, capture_train_state  # noqa: F401
from .retry import (  # noqa: F401
    RetryBudget,
    RetryError,
    backoff_delays,
    call_with_retries,
    default_budget,
    set_default_budget,
)
from .sentinel import (  # noqa: F401
    SENTINEL_NONFINITE,
    SENTINEL_OK,
    SENTINEL_SPIKE,
    AnomalyHalt,
    SentinelConfig,
    SentinelMonitor,
    sentinel_init_state,
    sentinel_observe,
    sentinel_to_host,
)

__all__ = [
    "SentinelConfig", "SentinelMonitor", "AnomalyHalt",
    "SENTINEL_OK", "SENTINEL_NONFINITE", "SENTINEL_SPIKE",
    "sentinel_init_state", "sentinel_observe", "sentinel_to_host",
    "PreemptionGuard", "capture_train_state", "DEADLINE_ENV",
    "RetryError", "backoff_delays", "call_with_retries",
    "RetryBudget", "set_default_budget", "default_budget",
    "FaultSchedule", "FaultSpec",
    "InjectedFault", "InjectedDeath", "InjectedCrash",
    "ElasticDPTrainer",
    "DurabilityConfig", "CheckpointDataPlane", "BlobTransport",
    "BlobCorruptionError",
]
