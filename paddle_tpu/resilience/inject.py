"""Deterministic fault-injection plane: seeded, schedule-driven chaos.

Parity: the reference proves Fleet's elastic fault tolerance with real
process kills (etcd lease expiry after a SIGKILL'd trainer); our r7/r11
suites did the same with SIGTERM/SIGKILL — realistic, but *flaky under
concurrent load* (a slow CI box shifts where the signal lands) and
impossible to replay. This module makes failure a first-class, replayable
input instead of an accident of timing:

* **Named injection points** are threaded through the existing failure
  seams — the elastic ``_TcpStore`` register/heartbeat/KV RPCs, the
  checkpoint writer, the serving engine tick, the router transport, the
  replica loop, the elastic rank step, the preemption guard. Each seam
  calls :func:`fire` with a point name plus context labels; with no
  schedule armed the call is one ``None`` check (zero-cost in production).
* A :class:`FaultSchedule` holds :class:`FaultSpec` entries that fire at
  deterministic **trigger counts** (the Nth matching invocation of a
  point), so the same schedule over the same workload produces the same
  fault sequence bit-for-bit — no signals, no sleeps, no races. The
  ``seed`` stamps the schedule and drives :meth:`FaultSchedule.randomize`
  so even "random" chaos replays identically.
* Every fault that fires is appended to :attr:`FaultSchedule.fired` — two
  runs are replays of each other iff their fired logs match, which is the
  acceptance check the deterministic chaos tests assert.

Fault kinds and who interprets them:

====================  =====================================================
kind                  semantics (seam in parentheses)
====================  =====================================================
``raise``             :func:`fire` raises ``spec.exception`` (any seam)
``delay``/``stall``   :func:`fire` sleeps ``spec.seconds`` then proceeds
``timeout``           :func:`fire` raises ``socket.timeout`` (transport)
``drop``              the RPC is silently skipped (store register/
                      heartbeat/put) or answers "absent" (get/scan)
``duplicate``         the RPC is performed twice (store put/register)
``garbage``           the HTTP response body is replaced with non-JSON
                      bytes (router transport)
``torn``              the published checkpoint's array file is truncated
                      (checkpoint write)
``crash_after_temp``  the writer dies after the temp files are durable but
                      before the atomic rename — the temp dir is LEFT on
                      disk like a real crash (checkpoint write)
``kill``              abrupt death: replica ``kill()`` (serving loop),
                      heartbeat halt + :class:`InjectedDeath` (elastic
                      rank), emergency-save + :class:`InjectedDeath`
                      (preemption guard), store replica ``kill()``
                      (replicated coordination store monitor)
====================  =====================================================

Arming: :meth:`FaultSchedule.arm`/:meth:`disarm` install globally;
:meth:`FaultSchedule.scope` installs thread-locally (rank threads in one
process each carry their own schedule — the in-process elastic chaos
tests). Thread-local wins over global.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "FaultSpec",
    "FaultSchedule",
    "InjectedFault",
    "InjectedDeath",
    "InjectedCrash",
    "fire",
    "active_schedule",
    "POINTS",
]

# the documented injection points (instrumented seams); fire() accepts any
# name so new seams don't need a registry edit, but tests and schedules
# should prefer these. The `elastic.store.<op>` family is MESSAGE-level
# (drop/duplicate one logical RPC, before the retry layer); the
# `elastic.store.rpc.<op>` family is ATTEMPT-level (each retry re-fires —
# persistent raise faults burn real backoff and meet the RetryBudget)
POINTS = (
    "elastic.store.register",
    "elastic.store.heartbeat",
    "elastic.store.deregister",
    "elastic.store.kv.put",
    "elastic.store.kv.get",
    "elastic.store.kv.delete",
    "elastic.store.kv.scan",
    "elastic.store.rpc.register",
    "elastic.store.rpc.heartbeat",
    "elastic.store.rpc.deregister",
    "elastic.store.rpc.put",
    "elastic.store.rpc.get",
    "elastic.store.rpc.delete",
    "elastic.store.rpc.scan",
    "elastic.store.rpc.scan_kv",
    # replicated coordination store (r16): append is per-peer on the
    # leader (raise/timeout/drop = that peer misses this append), renew
    # fires in the leader's lease tick, kill in EVERY replica's monitor
    # tick (kind `kill` = that replica's deterministic SIGKILL), and the
    # election points mark candidacy/victory (raise delays candidacy)
    "store.replica.append",
    "store.lease.renew",
    "store.replica.kill",
    "store.election.start",
    "store.election.won",
    "checkpoint.write",
    # replicated checkpoint data plane (r19): `ckpt.replica.push` fires
    # per (step, shard, peer) push attempt on the plane's pusher thread
    # (drop = the push is skipped, garbage/torn = the pushed bytes are
    # corrupted/truncated so the receiver's CRC check rejects them —
    # the owner re-pushes after the confirm timeout); `ckpt.scrub.corrupt`
    # fires per resident blob in the scrub pass (kind corrupt/garbage =
    # a byte of the FILE is flipped first, so the scrubber detects rot it
    # planted itself — deterministic bit-rot); `ckpt.disk.loss` fires in
    # the elastic rank step (kind `kill` = halt heartbeats, WIPE this
    # rank's checkpoint directory, then die of InjectedDeath — the
    # preemption-with-local-SSD double failure)
    "ckpt.replica.push",
    "ckpt.scrub.corrupt",
    "ckpt.disk.loss",
    "engine.tick",
    "replica.tick",
    "serving.pages.exhausted",
    # speculative decoding (ISSUE 18): fires per active stream right
    # before the batched verify; a raise-kind fault fails ONLY the
    # matched streams and the tick falls back to plain decode
    "serving.spec.verify",
    "router.transport",
    # zero-loss streams (r21): `router.resurrect` fires at the head of a
    # continuation re-home (stall = wall-clock the recovery burns before
    # the resubmit, for deadline tests; raise = the recovery machinery
    # itself dying), `router.migrate` fires per migration stage
    # (labels src/dst/stage=export|import) before each hop's RPC
    "router.resurrect",
    "router.migrate",
    "elastic.rank.step",
    "preemption.update",
)


class InjectedFault(RuntimeError):
    """An injected failure (the generic ``raise`` kind's default class)."""

    def __init__(self, msg: str, point: str = "", kind: str = "",
                 count: int = 0):
        super().__init__(msg)
        self.point = point
        self.kind = kind
        self.count = count


class InjectedDeath(InjectedFault):
    """Abrupt simulated process death: the raising frame's owner (rank
    thread, training loop) must stop exactly as if SIGKILLed — no cleanup,
    no deregistration, heartbeats already halted."""


class InjectedCrash(InjectedFault):
    """Simulated crash mid-critical-section. The checkpoint writer treats
    it specially: temp files are LEFT on disk (a real crash does not run
    ``except`` cleanup), exercising the stale-temp sweep + newest-intact
    fallback."""


class FaultSpec:
    """One planned fault: WHERE (point + label match), WHEN (trigger
    counts), WHAT (kind + parameters).

    ``at``: 1-based matching-invocation count(s) at which to fire (int or
    iterable). ``every``: fire on every Nth matching invocation instead
    (persistent faults; ``at`` ignored). ``match``: labels that must be a
    subset of the ``fire()`` labels for the invocation to count.
    ``seconds``: sleep for delay/stall. ``exception``: class or instance
    raised for the ``raise`` kind (default :class:`InjectedFault`).
    ``max_fires`` bounds ``every``-mode firings (None = unbounded).
    """

    def __init__(self, point: str, kind: str = "raise", *,
                 at=1, every: Optional[int] = None,
                 match: Optional[Dict[str, object]] = None,
                 seconds: float = 0.05, exception=None,
                 max_fires: Optional[int] = None):
        self.point = str(point)
        self.kind = str(kind)
        if every is not None and int(every) < 1:
            raise ValueError("every must be >= 1")
        self.every = None if every is None else int(every)
        if isinstance(at, int):
            at = (at,)
        self.at: Tuple[int, ...] = tuple(sorted(int(a) for a in at))
        if self.every is None and any(a < 1 for a in self.at):
            raise ValueError("trigger counts are 1-based")
        self.match = dict(match or {})
        self.seconds = float(seconds)
        self.exception = exception
        self.max_fires = None if max_fires is None else int(max_fires)
        # mutable trigger state (owned by the schedule's lock)
        self.count = 0   # matching invocations seen
        self.fires = 0   # times this spec actually fired

    def _matches(self, labels: Dict[str, object]) -> bool:
        return all(labels.get(k) == v for k, v in self.match.items())

    def _due(self) -> bool:
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.every is not None:
            return self.count % self.every == 0
        return self.count in self.at

    def build_exception(self) -> BaseException:
        exc = self.exception
        if exc is None:
            cls = {"timeout": socket.timeout,
                   "crash_after_temp": InjectedCrash,
                   "kill": InjectedDeath}.get(self.kind, InjectedFault)
            exc = cls
        if isinstance(exc, type):
            if issubclass(exc, InjectedFault):
                return exc(
                    f"injected {self.kind} at {self.point} "
                    f"(count {self.count})",
                    point=self.point, kind=self.kind, count=self.count)
            return exc(f"injected {self.kind} at {self.point} "
                       f"(count {self.count})")
        return exc

    def to_dict(self) -> Dict:
        return {"point": self.point, "kind": self.kind, "at": list(self.at),
                "every": self.every, "match": dict(self.match),
                "seconds": self.seconds}


class FaultSchedule:
    """A seeded, replayable plan of faults.

    Two runs armed with equal schedules over a deterministic workload see
    the identical fault sequence — :attr:`fired` (the ordered log of
    ``(point, kind, count, labels)`` records) is the replay certificate.
    """

    def __init__(self, specs: Sequence[FaultSpec] = (), seed: int = 0):
        self.seed = int(seed)
        self.specs: List[FaultSpec] = list(specs)
        self.fired: List[Dict] = []
        self._lock = threading.Lock()
        self._armed_global = False

    # -- construction ---------------------------------------------------
    def add(self, point: str, kind: str = "raise", **kw) -> "FaultSchedule":
        """Append a :class:`FaultSpec` (chainable)."""
        self.specs.append(FaultSpec(point, kind, **kw))
        return self

    def randomize(self, points: Sequence[str], n: int = 3,
                  kinds: Sequence[str] = ("raise",),
                  max_count: int = 20) -> "FaultSchedule":
        """Seed-driven random schedule: ``n`` faults drawn from ``points``
        × ``kinds`` at trigger counts in [1, max_count]. The draw uses ONLY
        ``self.seed``, so the same seed always plans the same chaos."""
        import random

        rng = random.Random(self.seed)
        for _ in range(int(n)):
            self.add(rng.choice(list(points)), rng.choice(list(kinds)),
                     at=rng.randint(1, int(max_count)))
        return self

    # -- the hot path ---------------------------------------------------
    def _fire(self, point: str, labels: Dict[str, object]) -> Optional[FaultSpec]:
        hit = None
        with self._lock:
            for spec in self.specs:
                if spec.point != point or not spec._matches(labels):
                    continue
                spec.count += 1
                if hit is None and spec._due():
                    spec.fires += 1
                    hit = spec
                    self.fired.append({
                        "point": point, "kind": spec.kind,
                        "count": spec.count,
                        "labels": {k: v for k, v in labels.items()
                                   if isinstance(v, (str, int, float, bool,
                                                     type(None)))},
                    })
        return hit

    # -- replay bookkeeping ---------------------------------------------
    def fired_log(self) -> List[Dict]:
        """Copy of the ordered fired-fault log (the replay certificate)."""
        with self._lock:
            return [dict(f) for f in self.fired]

    def reset(self):
        """Zero all trigger counters and the fired log (reuse a schedule
        for a second, independent replay)."""
        with self._lock:
            self.fired.clear()
            for s in self.specs:
                s.count = 0
                s.fires = 0

    def to_dict(self) -> Dict:
        return {"seed": self.seed,
                "specs": [s.to_dict() for s in self.specs]}

    # -- arming ---------------------------------------------------------
    def arm(self) -> "FaultSchedule":
        """Install process-globally (single-scenario tests, CLI runs)."""
        global _global_schedule
        _global_schedule = self
        self._armed_global = True
        return self

    def disarm(self):
        global _global_schedule
        if _global_schedule is self:
            _global_schedule = None
        self._armed_global = False
        if getattr(_tls, "schedule", None) is self:
            _tls.schedule = None

    def scope(self):
        """Context manager arming this schedule for the CURRENT THREAD
        only — rank threads in one process each run their own chaos."""
        return _ThreadScope(self)

    def __enter__(self):
        return self.arm()

    def __exit__(self, *exc):
        self.disarm()


class _ThreadScope:
    def __init__(self, schedule: FaultSchedule):
        self.schedule = schedule
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "schedule", None)
        _tls.schedule = self.schedule
        return self.schedule

    def __exit__(self, *exc):
        _tls.schedule = self._prev


_global_schedule: Optional[FaultSchedule] = None
_tls = threading.local()


def active_schedule() -> Optional[FaultSchedule]:
    """The schedule governing this thread (thread-local wins, then
    global, else None)."""
    sched = getattr(_tls, "schedule", None)
    return sched if sched is not None else _global_schedule


def fire(point: str, **labels) -> Optional[FaultSpec]:
    """Injection-point hook, called by the instrumented seams.

    Returns ``None`` when nothing fires (the production fast path is one
    global read + one thread-local read). When a spec fires:

    * ``delay``/``stall`` sleep ``spec.seconds`` here and return ``None``
      (the operation proceeds, late);
    * ``raise``/``timeout`` raise here (the seam's normal error handling
      takes over — that is the point);
    * every other kind returns the :class:`FaultSpec` for the seam to
      interpret (drop/duplicate/garbage/torn/crash_after_temp/kill).
    """
    sched = active_schedule()
    if sched is None:
        return None
    spec = sched._fire(point, labels)
    if spec is None:
        return None
    if spec.kind in ("delay", "stall"):
        time.sleep(spec.seconds)
        return None
    if spec.kind in ("raise", "timeout"):
        raise spec.build_exception()
    return spec
