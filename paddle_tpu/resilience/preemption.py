"""Preemption-aware checkpointing: signal handlers + deadline watchdog.

Parity: the reference's elastic fault-tolerance levels (fleet/elastic —
SIGTERM means the scheduler is about to reclaim the node) and the
auto-checkpoint snapshot layer (incubate/checkpoint). On TPU the dominant
real-world failure is preemption: spot/preemptible TPU VMs get SIGTERM with
a short grace window, and maintenance events publish a wall-clock deadline.

:class:`PreemptionGuard` owns the last line of defence: on SIGTERM/SIGINT
(or ``grace`` seconds before a known deadline) it performs ONE emergency
SYNCHRONOUS save of the full training state — step counter, RNG keys,
GradScaler and optimizer state — through a :class:`CheckpointManager`
(which stamps per-array checksums, so a save cut off mid-write is detected
and skipped on reload). The state is captured at step boundaries via
:meth:`update` (or lazily via ``state_fn``), so a signal landing mid-step
snapshots the last CONSISTENT state, never a half-applied update.

The restart protocol is untouched: with ``exit_code=ELASTIC_EXIT_CODE``
(101) the relaunch loop in fleet/elastic treats the exit as "please
relaunch me", and the resumed process falls back to the newest intact
snapshot (framework/checkpoint.py corruption fallback).
"""
from __future__ import annotations

import os
import signal
import threading
import time
import warnings
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["PreemptionGuard", "DEADLINE_ENV", "capture_train_state"]

DEADLINE_ENV = "PADDLE_TPU_PREEMPTION_DEADLINE"  # absolute epoch seconds


def capture_train_state(step: int, model=None, optimizer=None, scaler=None,
                        trainer=None, extra: Optional[Dict] = None):
    """Standard snapshot pytree for a training loop: the
    :func:`framework.checkpoint.build_train_state` schema (model + optimizer
    state_dicts, GradScaler state, RNG) plus the step counter. With a
    ``trainer`` (ParallelTrainer) its sharded device arrays are captured as
    host copies (and its in-graph scale state synced back first)."""
    from ..framework.checkpoint import build_train_state

    state: Dict[str, Any] = build_train_state(
        model=model, optimizer=optimizer, scaler=scaler, extra=extra)
    state["step"] = int(step)
    if trainer is not None:
        trainer.sync_scaler()
        state["trainer"] = trainer.capture_state()
    return state


class PreemptionGuard:
    """Install with a manager and a way to read the current state::

        guard = PreemptionGuard(mgr, exit_code=ELASTIC_EXIT_CODE)
        guard.install()
        for step in range(start, total):
            loss = trainer.step(x, y)
            guard.update(step, lambda: capture_train_state(step, trainer=trainer))

    ``update`` stores the (step, state-thunk) pair atomically; the signal
    handler and the deadline watchdog both funnel into
    :meth:`emergency_save`, which runs at most once.

    ``deadline``: absolute epoch seconds (defaults to $PADDLE_TPU_PREEMPTION_
    DEADLINE when set); the watchdog saves ``grace`` seconds before it.
    ``exit_code``: when not None the signal handler exits the process with
    it after saving (101 = the elastic relaunch protocol); None returns
    control to the training loop, which should check ``guard.preempted``.
    """

    def __init__(self, manager, state_fn: Optional[Callable[[], Tuple[int, Any]]] = None,
                 *, signals=(signal.SIGTERM, signal.SIGINT),
                 deadline: Optional[float] = None, grace: float = 30.0,
                 exit_code: Optional[int] = None,
                 watchdog_interval: float = 1.0,
                 on_preempt: Optional[Callable[[], None]] = None,
                 publisher: Optional[Callable[[int], Any]] = None,
                 publish_deadline_s: float = 2.0):
        self.manager = manager
        self.state_fn = state_fn
        self.signals = tuple(signals)
        if deadline is None and os.environ.get(DEADLINE_ENV):
            deadline = float(os.environ[DEADLINE_ENV])
        self.deadline = deadline
        self.grace = float(grace)
        self.exit_code = exit_code
        self.watchdog_interval = float(watchdog_interval)
        self.on_preempt = on_preempt
        # replicated-plane hook (r19): after the synchronous local write,
        # a best-effort manifest-commit/replica-push runs in a worker
        # thread joined with a hard cap — a stalled store may cost the
        # cluster the final-step replicas, but it can NEVER delay the
        # exit-101 relaunch protocol
        self.publisher = publisher
        self.publish_deadline_s = float(publish_deadline_s)
        self.publish_completed: Optional[bool] = None
        self.preempted = False
        self.saved_step: Optional[int] = None
        self._latest: Optional[Tuple[int, Any]] = None  # (step, state|thunk)
        self._prev_handlers: Dict[int, Any] = {}
        # RLock + in-progress flag: a signal can interrupt the main thread
        # INSIDE emergency_save and re-enter it from the handler — the
        # nested call must return, not deadlock and not double-save
        self._save_lock = threading.RLock()
        self._saving = False
        self._saving_thread: Optional[threading.Thread] = None
        self._saved = False
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None

    # -- state capture --------------------------------------------------
    def update(self, step: int, state):
        """Record the latest CONSISTENT state (call at step boundaries).
        ``state`` may be the pytree itself or a zero-arg thunk producing it
        (thunks defer the device→host copies to save time)."""
        self._latest = (int(step), state)
        # pin the step into the flight recorder so a SIGTERM dump names
        # the final completed step even when the trainer isn't noting it
        from ..observability.flight import flight_recorder

        flight_recorder().note(step=int(step))
        # injection seam: a scheduled `kill` here is the deterministic
        # SIGTERM — the state for THIS step is already registered (exactly
        # the signal-after-print window the chaos test aims at), the
        # emergency save runs, and InjectedDeath unwinds the training
        # loop like a real termination
        from .inject import fire as _inject_fire

        f = _inject_fire("preemption.update", step=int(step))
        if f is not None and f.kind == "kill":
            self.preempt_now(reason=f"injected kill at step {step}")
            raise f.build_exception()

    def preempt_now(self, reason: str = "injected preemption",
                    dump_tag: str = "preemption_injected") -> bool:
        """The preemption protocol minus signal and process exit —
        at-most-once emergency save (failures warned, never raised: the
        exit protocol must win), flight dump under ``dump_tag``,
        ``preempted`` flag, ``on_preempt`` hook. The signal handler
        funnels through here too; deterministic callers (the injection
        plane) decide how to unwind afterwards. Returns True when a
        snapshot was written."""
        self.preempted = True
        saved = False
        try:
            saved = self.emergency_save(reason=reason)
        except Exception as e:
            warnings.warn(f"PreemptionGuard: emergency save failed "
                          f"({type(e).__name__}: {e})", RuntimeWarning)
        self._flight_dump(dump_tag)
        if self.on_preempt is not None:
            try:
                self.on_preempt()
            except Exception as e:
                warnings.warn(f"PreemptionGuard: on_preempt hook failed "
                              f"({type(e).__name__}: {e})", RuntimeWarning)
        return saved

    def _current(self) -> Optional[Tuple[int, Any]]:
        if self._latest is not None:
            step, state = self._latest
            return step, (state() if callable(state) else state)
        if self.state_fn is not None:
            return self.state_fn()
        return None

    # -- the emergency path ---------------------------------------------
    def emergency_save(self, reason: str = "preemption") -> bool:
        """Synchronous, at-most-once snapshot. Returns True when a snapshot
        was written (False: nothing to save or already saved)."""
        with self._save_lock:
            if self._saved or self._saving:
                return False
            try:
                cur = self._current()
            except Exception as e:
                # a thunk can legitimately fail at signal time: with donated
                # buffers a signal landing between the jitted step returning
                # and the trainer rebinding its state reads deleted arrays.
                # Losing the emergency snapshot must not lose the exit
                # protocol — resume falls back to the last periodic snapshot
                # (the corruption-fallback loader makes that safe).
                warnings.warn(
                    f"PreemptionGuard: state capture failed "
                    f"({type(e).__name__}: {e}); emergency save skipped — "
                    "resume will use the newest periodic snapshot",
                    RuntimeWarning)
                return False
            if cur is None:
                warnings.warn(
                    "PreemptionGuard: no state registered (call update() or "
                    "pass state_fn) — emergency save skipped", RuntimeWarning)
                return False
            self._saving = True
            self._saving_thread = threading.current_thread()
            try:
                step, state = cur
                # join any in-flight async write first so the emergency
                # snapshot can never interleave with a half-written one
                self.manager.wait()
                self.manager.save(
                    step, state,
                    metadata={"preempted": True, "reason": reason},
                    sync=True)
                self.manager.wait()
                self._saved = True
                self.saved_step = step
                # best-effort replica push + manifest commit so the final
                # step is recoverable by PEERS even if this disk never
                # comes back — deadline-capped AFTER the durable local
                # write, so a stalled store cannot hold the exit hostage
                self._publish_capped(step)
            finally:
                self._saving = False
                self._saving_thread = None
            return True

    def _publish_capped(self, step: int):
        """Run ``publisher(step)`` on a daemon thread joined with the
        configured cap. The thread may outlive the join (a store stalled
        mid-RPC keeps it blocked) — that is the point: the exit protocol
        proceeds; the orphan either finishes in the grace window or dies
        with the process, and resume falls back to peer replicas of the
        previous manifest."""
        if self.publisher is None:
            return
        done = threading.Event()

        def _run():
            try:
                self.publisher(step)
            except Exception as e:
                warnings.warn(
                    f"PreemptionGuard: emergency publish failed "
                    f"({type(e).__name__}: {e})", RuntimeWarning)
            finally:
                done.set()

        t = threading.Thread(target=_run, daemon=True)
        t.start()
        t.join(self.publish_deadline_s)
        self.publish_completed = done.is_set()
        if not self.publish_completed:
            warnings.warn(
                f"PreemptionGuard: emergency publish still in flight at "
                f"the {self.publish_deadline_s}s cap; proceeding with the "
                "exit protocol (peers recover from the previous manifest)",
                RuntimeWarning)

    # -- signal + watchdog wiring ----------------------------------------
    def _handler(self, signum, frame):
        self.preempted = True
        if self._saving:
            if self._saving_thread is threading.current_thread():
                # re-entered mid-write on this very thread (repeated
                # SIGTERM): raising would unwind the interrupted _write
                # frame and discard the snapshot — record the signal and
                # return; the outer save completes and its caller exits
                return
            # the watchdog thread is writing: block until it finishes
            # (cross-thread acquire really waits), then honor exit_code
            with self._save_lock:
                pass
            if self.exit_code is not None:
                raise SystemExit(self.exit_code)
            return
        # nothing before the exit protocol may escape: a failed save (disk
        # full, capture race) must still produce the relaunchable exit
        # code — preempt_now contains save/dump/hook failures
        self.preempt_now(reason=f"signal {signum}",
                         dump_tag=f"preemption_signal_{signum}")
        if self.exit_code is not None:
            raise SystemExit(self.exit_code)
        prev = self._prev_handlers.get(signum)
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)

    def _flight_dump(self, reason: str):
        """Flight-recorder snapshot of the preemption moment. Lands in the
        configured flight directory, defaulting to the checkpoint
        manager's directory so a SIGTERM'd run always leaves a readable
        dump naming its final step next to its snapshots. Contained —
        the exit protocol survives any recorder failure."""
        try:
            from ..observability.flight import flight_recorder

            fr = flight_recorder()
            fr.dump(reason,
                    extra={"saved_step": self.saved_step,
                           "deadline": self.deadline},
                    directory=None if fr.armed else getattr(
                        self.manager, "directory", None))
        except Exception:
            pass

    def _watch(self):
        fire_at = self.deadline - self.grace
        while not self._stop.wait(self.watchdog_interval):
            if time.time() >= fire_at:
                self.preempted = True
                try:
                    self.emergency_save(reason="deadline")
                except Exception as e:
                    warnings.warn(f"PreemptionGuard: deadline save failed "
                                  f"({type(e).__name__}: {e})",
                                  RuntimeWarning)
                self._flight_dump("preemption_deadline")
                return

    def install(self):
        for sig in self.signals:
            self._prev_handlers[sig] = signal.signal(sig, self._handler)
        if self.deadline is not None and self._watchdog is None:
            self._watchdog = threading.Thread(target=self._watch, daemon=True)
            self._watchdog.start()
        return self

    def uninstall(self):
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers.clear()
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=5.0)
            self._watchdog = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
