"""Replicated checkpoint data plane: peer-redundant snapshots, scrub & repair.

The r16 round made the *coordination* plane survive node loss (quorum
replicated KV store); this module does the same for the *state* plane. The
r11 elastic trainer had rank 0 gather every shard and write ONE snapshot to
its own local disk — lose that disk (the common preemption-with-local-SSD
case on TPU pods) and the run is gone even though the store, the survivors
and every other disk are healthy. Here, durability is peer-redundant:

* **Each rank durably writes its OWN shard snapshot locally** (params are
  replicated; the ZeRO-style momentum shard is this rank's partition) using
  the same atomic-rename + fsync + CRC publish protocol as the r7/r11
  checkpoint writer (:func:`~paddle_tpu.framework.checkpoint
  .durable_write_bytes`, CRC sidecar written last = the commit marker).
* **Shard blobs are pushed asynchronously to K peer ranks** over the KV/HTTP
  plane as chunked, CRC-stamped transfers (:class:`BlobTransport`: chunk
  records then a head record LAST, so an incomplete transfer is never
  observable; the head doubles as the streaming-put framing for the
  quorum-replicated store — no single append carries more than one chunk).
  In-flight bytes are bounded (:class:`_BandwidthGate`) so replication can
  never starve the gradient plane. A receiving peer CRC-verifies before
  persisting; corrupt or dropped transfers are simply re-pushed after the
  confirm timeout.
* **A snapshot becomes VISIBLE only when its manifest commits** to the
  (r16 quorum-replicated) store: ``{step, layout, shard → {owner, replica
  ranks, crc, nbytes}}``. The committer (rank 0) waits until every shard's
  owner reports local-durable + K confirmed replicas — an incomplete
  multi-rank snapshot is never observable, exactly the newest-INTACT rule
  of the single-disk loader lifted to the cluster.
* **Recovery composes with the r11 reshard machinery**: a replacement rank
  with an EMPTY disk pulls any shard it needs from peer replicas (pull
  requests over the same KV plane, answered by every plane's worker),
  verifies CRCs against the manifest (a rotted replica cannot poison
  recovery), re-persists what it pulled (restoring redundancy as a side
  effect), reassembles the global state and reshards it to the new world.
* **A background scrubber re-verifies resident blob CRCs**, quarantines
  corrupt files (rename, never delete — and intact copies are never
  touched, so the last intact copy is structurally safe), re-replicates
  from peers to restore the redundancy factor, and emits the r12 series
  ``ckpt_replicas_resident`` / ``ckpt_replication_lag_steps`` /
  ``ckpt_scrub_corruptions_total`` plus one flight dump per corruption
  episode.

Failure seams (r13 inject plane): ``ckpt.replica.push`` (drop / garbage /
torn per push attempt), ``ckpt.scrub.corrupt`` (deterministic bit-rot),
``ckpt.disk.loss`` (fired by the elastic trainer: heartbeat halt + directory
wipe + InjectedDeath — the kill-AND-wipe double failure). The plane's worker
thread inherits the schedule active on the constructing thread, so per-rank
thread-local chaos scopes reach the pushes they schedule.
"""
from __future__ import annotations

import base64
import io
import json
import os
import re
import shutil
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..framework.checkpoint import (
    _TreeSpec,
    _flatten_state,
    durable_write_bytes,
)
from .inject import active_schedule, fire as _inject_fire

__all__ = [
    "DurabilityConfig",
    "CheckpointDataPlane",
    "BlobTransport",
    "BlobCorruptionError",
    "pack_state",
    "unpack_state",
    "assemble_global_state",
]

_CHUNK_RE = re.compile(r"\.c\d+$")


class BlobCorruptionError(RuntimeError):
    """A transferred or resident blob failed its CRC check."""


# ---------------------------------------------------------------------------
# state <-> bytes (no pickle: npz members + a JSON head member)
# ---------------------------------------------------------------------------
def pack_state(state) -> bytes:
    """Serialize a checkpoint pytree (dicts/lists of numpy/jax arrays and
    JSON python values) to one npz blob. Shares the checkpoint module's
    flatten/treedef machinery so the schema can never diverge from the
    on-disk snapshot format; the structure rides as a uint8 JSON member
    (``allow_pickle=False`` everywhere — loading an untrusted blob never
    executes code)."""
    flat = _flatten_state(state)
    arrays: Dict[str, np.ndarray] = {}
    pyvals: Dict[str, object] = {}
    for path, leaf in flat.items():
        if isinstance(leaf, tuple) and len(leaf) == 2 and leaf[0] == "__py__":
            pyvals[path] = leaf[1]
        else:
            arrays[path] = np.asarray(leaf)
    head = json.dumps({"treedef": _TreeSpec.from_state(state).to_json(),
                       "pyvals": pyvals}).encode()
    buf = io.BytesIO()
    np.savez(buf, __tree__=np.frombuffer(head, dtype=np.uint8),
             **{k.replace("/", "|"): v for k, v in arrays.items()})
    return buf.getvalue()


def unpack_state(data: bytes):
    z = np.load(io.BytesIO(data), allow_pickle=False)
    head = json.loads(z["__tree__"].tobytes().decode())
    arrays = {k.replace("|", "/"): z[k] for k in z.files if k != "__tree__"}
    tree = _TreeSpec.from_json(head["treedef"])
    return tree.unflatten(arrays, head["pyvals"])


def _get_path(tree, path: str):
    node = tree
    for part in path.split("/"):
        if not part:
            continue
        node = node[part] if isinstance(node, dict) else node[int(part)]
    return node


def _set_path(tree, path: str, value):
    parts = [p for p in path.split("/") if p]
    node = tree
    for part in parts[:-1]:
        node = node[part] if isinstance(node, dict) else node[int(part)]
    last = parts[-1]
    if isinstance(node, dict):
        node[last] = value
    else:
        node[int(last)] = value


def assemble_global_state(shard_states: List, layout: Dict[str, Dict]):
    """Rebuild the GLOBAL snapshot from the per-rank shard states: every
    path named by ``layout`` (the dp-shard schema) is concatenated in rank
    order along its axis; everything else (replicated params, step
    counters) is taken from shard 0 — the same world-size-agnostic global
    form the single-writer snapshot used to hold, ready for
    :func:`~paddle_tpu.framework.checkpoint.reshard_train_state`."""
    if not shard_states:
        raise ValueError("no shard states to assemble")
    base = shard_states[0]
    for path, entry in (layout or {}).items():
        axis = int(entry.get("axis", 0))
        parts = [np.asarray(_get_path(s, path)) for s in shard_states]
        _set_path(base, path, np.concatenate(parts, axis=axis))
    return base


# ---------------------------------------------------------------------------
# bounded in-flight bandwidth
# ---------------------------------------------------------------------------
class _BandwidthGate:
    """Caps the total bytes of replica payload in flight at once. An
    oversized single blob (> cap) is still allowed through ALONE — the
    gate bounds concurrency, it must never deadlock a legitimate push."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._cv = threading.Condition()
        self._inflight = 0  # guarded-by: self._cv

    def acquire(self, nbytes: int):
        with self._cv:
            while self._inflight > 0 and self._inflight + nbytes > self.max_bytes:
                self._cv.wait(timeout=1.0)
            self._inflight += nbytes

    def release(self, nbytes: int):
        with self._cv:
            self._inflight = max(0, self._inflight - nbytes)
            self._cv.notify_all()

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight


# ---------------------------------------------------------------------------
# chunked blob transfers over the KV plane
# ---------------------------------------------------------------------------
class BlobTransport:
    """Streaming put/get of byte blobs over a string KV store.

    The KV/HTTP plane (and the r16 quorum store behind it) replicates one
    VALUE per append — a multi-megabyte shard pushed as a single value
    would stall the quorum pipeline for the whole transfer. Blobs are
    therefore split into bounded base64 chunk records (``<key>.c<i>``)
    followed by a small head record (``<key>`` = ``{chunks, crc, nbytes}``)
    written LAST: the head is the commit point, so a reader either sees a
    complete, CRC-checkable transfer or nothing at all."""

    def __init__(self, store, chunk_bytes: int = 1 << 18,
                 gate: Optional[_BandwidthGate] = None):
        self.store = store
        # chunk_bytes bounds the DECODED payload per record; the b64 text
        # is 4/3 of that
        self.chunk_chars = max(4, (int(chunk_bytes) * 4 // 3) & ~3)
        self.gate = gate

    def put(self, key: str, data: bytes, crc: Optional[int] = None,
            nbytes: Optional[int] = None) -> dict:
        """Stream ``data`` under ``key``. ``crc``/``nbytes`` override the
        head's integrity stamp — the replica pusher stamps the TRUE values
        of the clean blob so an injected garbage/torn payload fails the
        receiver's verify exactly like wire corruption would."""
        if self.gate is not None:
            self.gate.acquire(len(data))
        try:
            b64 = base64.b64encode(data).decode("ascii")
            n = 0
            for i in range(0, len(b64), self.chunk_chars):
                self.store.put(f"{key}.c{n}", b64[i:i + self.chunk_chars])
                n += 1
            head = {"chunks": n,
                    "crc": zlib.crc32(data) if crc is None else int(crc),
                    "nbytes": len(data) if nbytes is None else int(nbytes)}
            self.store.put(key, json.dumps(head))
            return head
        finally:
            if self.gate is not None:
                self.gate.release(len(data))

    def head(self, key: str) -> Optional[dict]:
        raw = self.store.get(key)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def get(self, key: str) -> Optional[bytes]:
        """Blob bytes, or None when absent/incomplete. Raises
        :class:`BlobCorruptionError` when the assembled bytes do not match
        the head's CRC (a garbage/torn transfer)."""
        head = self.head(key)
        if head is None or "chunks" not in head:
            return None
        parts = []
        for i in range(int(head["chunks"])):
            c = self.store.get(f"{key}.c{i}")
            if c is None:
                return None  # chunk GC'd under us: treat as absent
            parts.append(c)
        try:
            data = base64.b64decode("".join(parts).encode("ascii"))
        except Exception as e:
            raise BlobCorruptionError(f"{key}: undecodable chunks") from e
        if (zlib.crc32(data) != int(head["crc"])
                or len(data) != int(head["nbytes"])):
            raise BlobCorruptionError(
                f"{key}: crc/length mismatch ({len(data)} bytes)")
        return data

    def delete(self, key: str):
        head = self.head(key)
        # head first: a concurrent reader sees "absent", never "torn"
        try:
            self.store.delete(key)
        except Exception:
            pass
        n = int(head.get("chunks", 0)) if head else 0
        for i in range(n):
            try:
                self.store.delete(f"{key}.c{i}")
            except Exception:
                pass


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------
class DurabilityConfig:
    """Knobs for the replicated checkpoint data plane.

    ``replicas``: peer copies per shard (K). A shard's snapshot is
    manifest-committable only once its owner's local copy is durable AND
    min(K, world-1) peers have CRC-confirmed their replica.
    ``scrub_interval_s``: None disables the periodic pass (tests drive
    :meth:`CheckpointDataPlane.scrub_once` directly)."""

    def __init__(self, replicas: int = 1, *, chunk_bytes: int = 1 << 18,
                 max_inflight_bytes: int = 8 << 20,
                 scrub_interval_s: Optional[float] = None,
                 push_confirm_timeout_s: float = 2.0,
                 push_retries: int = 3,
                 manifest_timeout_s: float = 30.0,
                 keep_manifests: int = 10,
                 pull_hop_timeout_s: float = 3.0,
                 worker_interval_s: float = 0.02):
        if replicas < 0:
            raise ValueError("replicas must be >= 0")
        self.replicas = int(replicas)
        self.chunk_bytes = int(chunk_bytes)
        self.max_inflight_bytes = int(max_inflight_bytes)
        self.scrub_interval_s = scrub_interval_s
        self.push_confirm_timeout_s = float(push_confirm_timeout_s)
        self.push_retries = int(push_retries)
        self.manifest_timeout_s = float(manifest_timeout_s)
        self.keep_manifests = int(keep_manifests)
        self.pull_hop_timeout_s = float(pull_hop_timeout_s)
        self.worker_interval_s = float(worker_interval_s)


class _PushTask:
    def __init__(self, step: int, shard: int, data: bytes, crc: int,
                 peers: List[str], required: int, deadline: float,
                 generation: int = 0):
        self.step = step
        self.shard = shard
        self.data = data
        self.crc = crc
        self.generation = int(generation)
        # the first `required` peers are the ACTIVE replica targets; the
        # rest stand by and rotate in only when an active peer exhausts
        # its retry budget (a black-holed peer must not sink redundancy,
        # but K=1 must also not push to world-1 peers)
        self.active = list(peers[:required])
        self.standby = list(peers[required:])
        self.required = int(required)
        self.deadline = deadline
        # confirm/ready state is touched by the worker AND (during a
        # preemption) emergency_flush on the guard's thread: the dedup +
        # quorum decision must be atomic or a doubly-appended peer could
        # satisfy the replica quorum with fewer DISTINCT copies
        self.lock = threading.Lock()
        self.confirmed: List[str] = []   # guarded-by: self.lock
        self.pushed_at: Dict[str, float] = {}
        self.attempts: Dict[str, int] = {}
        self.ready = False               # guarded-by: self.lock


class _CommitTask:
    def __init__(self, step: int, world: int, members: List[str],
                 layout: Dict, generation: int, deadline: float):
        self.step = step
        self.world = int(world)
        self.members = list(members)
        self.layout = dict(layout or {})
        self.generation = int(generation)
        self.deadline = deadline


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------
class CheckpointDataPlane:
    """One rank's half of the replicated checkpoint data plane.

    ``store`` is the elastic ``_TcpStore`` KV plane (put/get/delete/scan
    with ``prefix``/``keys_only``); ``root`` is THIS RANK'S private
    checkpoint directory (per-rank — that is the point). All network work
    runs on one worker thread: replica pushes (FIFO, so injected faults
    replay deterministically), draining blobs peers pushed to us, answering
    pull requests, committing manifests (when this rank saved as rank 0)
    and the scrub pass.

    Key namespace (all inside the store's KV scope, prefix-disjoint from
    the rendezvous/allgather keys):

    ======================================  ===============================
    ``ckb:<peer>:<step>:<shard>``           pushed replica blob (chunked)
    ``ckres:<step>:<shard>:<node>``         replica residency receipt (crc)
    ``ckrdy:<step>:<shard>``                owner's shard-ready record
    ``ckmf:<step:012d>``                    committed manifest (JSON)
    ``ckpl:<holder>:<reqid>``               pull request
    ``ckpr:<reqid>``                        pull response blob (chunked)
    ======================================  ===============================
    """

    def __init__(self, store, node_id: str, root: str,
                 config: Optional[DurabilityConfig] = None):
        self.store = store
        self.node = str(node_id)
        self.root = root
        self.cfg = config or DurabilityConfig()
        self.gate = _BandwidthGate(self.cfg.max_inflight_bytes)
        self.tx = BlobTransport(store, self.cfg.chunk_bytes, gate=self.gate)
        self.blob_dir = os.path.join(root, "blobs")
        self.quarantine_dir = os.path.join(root, "quarantine")
        os.makedirs(self.blob_dir, exist_ok=True)
        os.makedirs(self.quarantine_dir, exist_ok=True)

        self._lock = threading.Lock()
        self._pushes: "deque[_PushTask]" = deque()   # guarded-by: self._lock
        self._commits: "deque[_CommitTask]" = deque()  # guarded-by: self._lock
        self._own_newest: Optional[int] = None       # guarded-by: self._lock
        self._committed_newest: Optional[int] = None  # guarded-by: self._lock
        self._pull_seq = 0                           # guarded-by: self._lock
        # reqids of in-flight pulls; a ckpr response not listed here is an
        # orphan a timed-out requester abandoned (GC'd in _prune_local)
        self._pending_pulls: set = set()             # guarded-by: self._lock
        self.dead = False
        self._last_scrub = time.monotonic()
        self._last_prune = time.monotonic()
        # the worker inherits the chaos schedule active on the CONSTRUCTING
        # thread (rank threads carry thread-local schedules): pushes it
        # performs count against the same deterministic plan as the rank
        self._sched = active_schedule()
        self._stop = threading.Event()

        from ..observability.metrics import default_registry

        r = default_registry()
        self._g_resident = r.gauge(
            "ckpt_replicas_resident",
            "resident blob copies this node holds for the newest "
            "committed manifest step", ("node",))
        self._g_lag = r.gauge(
            "ckpt_replication_lag_steps",
            "newest locally saved shard step minus newest committed "
            "manifest step", ("node",))
        self._c_scrub = r.counter(
            "ckpt_scrub_corruptions_total",
            "resident blobs the scrubber found corrupt", ("node",))
        self._c_manifests = r.counter(
            "ckpt_manifests_committed_total",
            "snapshot manifests this rank committed", ("node",))
        self._c_pushes = r.counter(
            "ckpt_replica_pushes_total",
            "replica blob push attempts", ("node",))

        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # -- local blob store ------------------------------------------------
    def _blob_path(self, step: int, shard: int) -> str:
        return os.path.join(self.blob_dir, f"b_{int(step):012d}_{int(shard)}.npz")

    def _write_local(self, step: int, shard: int, data: bytes, source: str):
        """Durable local persist: blob first, CRC sidecar LAST (the commit
        marker) — both through the checkpoint writer's atomic-rename +
        fsync protocol."""
        path = self._blob_path(step, shard)
        durable_write_bytes(path, data)
        meta = {"crc": zlib.crc32(data), "nbytes": len(data),
                "step": int(step), "shard": int(shard), "source": source}
        durable_write_bytes(path + ".meta", json.dumps(meta).encode())

    def _read_local(self, step: int, shard: int,
                    verify: bool = True) -> Optional[bytes]:
        """Resident blob bytes, CRC-verified against the sidecar; None
        when absent or unreadable; raises :class:`BlobCorruptionError` on
        a CRC mismatch (the scrubber's signal)."""
        path = self._blob_path(step, shard)
        try:
            with open(path + ".meta") as f:
                meta = json.load(f)
            with open(path, "rb") as f:
                data = f.read()
        except (OSError, ValueError):
            return None
        if verify and (zlib.crc32(data) != int(meta["crc"])
                       or len(data) != int(meta["nbytes"])):
            raise BlobCorruptionError(f"{path}: resident blob crc mismatch")
        return data

    def resident(self) -> Dict[Tuple[int, int], dict]:
        """{(step, shard): sidecar meta} for every committed local blob."""
        out = {}
        try:
            names = os.listdir(self.blob_dir)
        except OSError:
            return out
        for name in names:
            m = re.match(r"^b_(\d{12})_(\d+)\.npz\.meta$", name)
            if not m:
                continue
            try:
                with open(os.path.join(self.blob_dir, name)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue
            out[(int(m.group(1)), int(m.group(2)))] = meta
        return out

    # -- save path -------------------------------------------------------
    def save_shard(self, step: int, state, *, rank: int, world: int,
                   members: List[str], layout: Optional[Dict] = None,
                   generation: int = 0):
        """Durably persist THIS rank's shard snapshot locally, then hand
        replication + (for rank 0) manifest commit to the worker. Returns
        after the local write — the training step never waits on peers."""
        data = pack_state(state)
        crc = zlib.crc32(data)
        self._write_local(step, rank, data, source="own")
        required = min(self.cfg.replicas, max(0, int(world) - 1))
        # replica targets: the next K ranks in committed order (wrap),
        # deterministic so two runs push to identical peers
        peers = [members[(rank + 1 + i) % world] for i in range(world - 1)
                 if members[(rank + 1 + i) % world] != self.node]
        now = time.monotonic()
        task = _PushTask(step, rank, data, crc, peers, required,
                         now + self.cfg.manifest_timeout_s,
                         generation=generation)
        with self._lock:
            self._own_newest = max(step, self._own_newest or -1)
            self._pushes.append(task)
            if rank == 0:
                self._commits.append(_CommitTask(
                    step, world, members, layout or {}, generation,
                    now + self.cfg.manifest_timeout_s))
        self._update_gauges()

    # -- manifest queries ------------------------------------------------
    def manifest_steps(self) -> List[int]:
        try:
            keys = self.store.scan(keys_only=True, prefix="ckmf:")
        except Exception:
            return []
        out = []
        for k in keys:
            try:
                out.append(int(k.split(":", 1)[1]))
            except (IndexError, ValueError):
                pass
        return sorted(out)

    def manifest(self, step: int) -> Optional[dict]:
        raw = self.store.get(f"ckmf:{int(step):012d}")
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def newest_recoverable(self, live_nodes=None) -> Optional[int]:
        """Newest COMMITTED manifest step whose every shard still has at
        least one holder among ``live_nodes`` (∪ this node's own resident
        blobs) — the cluster-level newest-intact rule. Walks older
        manifests when a newer one has lost all copies of some shard."""
        live = None if live_nodes is None else set(live_nodes) | {self.node}
        for step in reversed(self.manifest_steps()):
            m = self.manifest(step)
            if m is None:
                continue
            if live is None:
                return step
            if all(set(self._holders(m, step, j)) & live
                   for j in range(int(m["world"]))):
                return step
        return None

    def _holders(self, manifest: dict, step: int, shard: int) -> List[str]:
        """Owner first, then replicas, then any node that published a
        residency receipt (a repaired/pulled copy counts)."""
        info = manifest["shards"][str(int(shard))]
        holders = [info["owner"]] + [p for p in info.get("replicas", ())]
        try:
            extra = self.store.scan(keys_only=True,
                                    prefix=f"ckres:{int(step)}:{int(shard)}:")
            for k in extra:
                holders.append(k.rsplit(":", 1)[1])
        except Exception:
            pass
        seen, out = set(), []
        for h in holders:
            if h and h not in seen:
                seen.add(h)
                out.append(h)
        return out

    # -- load / recovery -------------------------------------------------
    def load_step(self, step: int, timeout: float = 30.0,
                  live_nodes=None):
        """Assemble the GLOBAL snapshot for a committed manifest: local
        blobs where resident and CRC-clean, peer pulls otherwise (every
        pulled copy is CRC-verified against the MANIFEST). A pulled copy
        is persisted + announced only while the shard's LIVE holder count
        is below the redundancy target (owner + K replicas) — recovery
        restores redundancy as it runs, but N ranks restoring together do
        not balloon every shard to N resident copies. Without
        ``live_nodes`` every pulled copy is adopted (a lone verifier has
        no liveness information). Returns ``(global_state, layout)``
        ready for :func:`~paddle_tpu.framework.checkpoint
        .reshard_train_state`."""
        m = self.manifest(step)
        if m is None:
            raise FileNotFoundError(f"no committed manifest for step {step}")
        deadline = time.monotonic() + timeout
        live = None if live_nodes is None else set(live_nodes) | {self.node}
        states = []
        for j in range(int(m["world"])):
            want = int(m["shards"][str(j)]["crc"])
            data = None
            try:
                local = self._read_local(step, j)
            except BlobCorruptionError:
                local = None
            if local is not None and zlib.crc32(local) == want:
                data = local
            else:
                data = self._pull(step, j, m, want, deadline)
                holders = set(self._holders(m, step, j))
                if live is not None:
                    holders &= live
                if live is None or len(holders) < self.cfg.replicas + 1:
                    self._write_local(step, j, data, source="pulled")
                    try:
                        self.store.put(f"ckres:{step}:{j}:{self.node}",
                                       str(want))
                    except Exception:
                        pass
            states.append(unpack_state(data))
        self._update_gauges()
        return assemble_global_state(states, m.get("layout", {})), \
            m.get("layout", {})

    def _pull(self, step: int, shard: int, manifest: dict, want_crc: int,
              deadline: float, service=None) -> bytes:
        """Fetch one shard blob from a peer holder: request keyed to a
        specific holder, response CRC-verified against the manifest.
        Cycles through holders (a dead or blobless holder costs one hop
        timeout) until the overall deadline. ``service`` (optional,
        throttled to ~4/s) runs inside the poll wait so a pull issued from
        the worker thread — a scrub repair — keeps answering peers'
        pulls/pushes instead of starving the whole plane for the hop."""
        tried: List[str] = []
        attempt = 0
        last_service = 0.0
        while time.monotonic() < deadline:
            holders = [h for h in self._holders(manifest, step, shard)
                       if h != self.node]
            if not holders:
                break
            # round-robin over the holder list (a dead or blobless
            # holder costs one hop timeout, then the next one is asked)
            holder = holders[attempt % len(holders)]
            attempt += 1
            if holder not in tried:
                tried.append(holder)
            with self._lock:
                self._pull_seq += 1
                reqid = f"{self.node}.{step}.{shard}.{self._pull_seq}"
                self._pending_pulls.add(reqid)
            resp_key = f"ckpr:{reqid}"
            try:
                try:
                    self.store.put(
                        f"ckpl:{holder}:{reqid}",
                        json.dumps({"step": int(step), "shard": int(shard),
                                    "reply": resp_key}))
                except Exception:
                    continue
                hop_deadline = min(
                    time.monotonic() + self.cfg.pull_hop_timeout_s,
                    deadline)
                while time.monotonic() < hop_deadline:
                    try:
                        head = self.tx.head(resp_key)
                    except Exception:
                        head = None
                    if head is not None:
                        if head.get("miss"):
                            self.tx.delete(resp_key)
                            break  # holder lost its copy: next holder
                        try:
                            data = self.tx.get(resp_key)
                        except BlobCorruptionError:
                            self.tx.delete(resp_key)
                            break
                        if data is not None:
                            self.tx.delete(resp_key)
                            if zlib.crc32(data) == int(want_crc):
                                return data
                            break  # holder's copy rotted: next holder
                    if (service is not None
                            and time.monotonic() - last_service >= 0.25):
                        last_service = time.monotonic()
                        try:
                            service()
                        except Exception:
                            pass
                    time.sleep(0.02)
                else:
                    # hop expired: best-effort reap of a response the
                    # holder may already have written (a late write after
                    # this delete is caught by the _prune_local orphan GC)
                    try:
                        self.tx.delete(resp_key)
                    except Exception:
                        pass
            finally:
                with self._lock:
                    self._pending_pulls.discard(reqid)
        raise TimeoutError(
            f"shard {shard} of snapshot step {step} unavailable from any "
            f"holder (tried {tried}) — redundancy exhausted")

    # -- emergency path (preemption) -------------------------------------
    def emergency_flush(self, deadline_s: float = 2.0) -> dict:
        """Best-effort, deadline-capped flush for the preemption guard:
        push every still-unconfirmed replica of queued shards INLINE (the
        dying rank's final step must reach peers even if this disk never
        comes back), publish ready records once peers confirm, and drive
        any queued manifest commits. Loops until everything lands or the
        deadline cuts it off; never raises and never exceeds the cap by
        more than one in-flight RPC. Safe next to the worker thread:
        every operation is an idempotent keyed put."""
        deadline = time.monotonic() + float(deadline_s)
        out = {"pushed": 0, "ready": 0, "committed": 0}
        with self._lock:
            pushes = list(self._pushes)
            commits = list(self._commits)
        pushed_once = set()
        while True:
            busy = False
            for task in pushes:
                if task.ready:
                    continue
                for peer in list(task.active):
                    if (peer in task.confirmed
                            or (task.step, task.shard, peer) in pushed_once):
                        continue
                    try:
                        if self._push_one(task, peer):
                            out["pushed"] += 1
                    except Exception:
                        pass
                    pushed_once.add((task.step, task.shard, peer))
                try:
                    if self._confirm_and_ready(task, force_check=True):
                        out["ready"] += 1
                except Exception:
                    pass
                busy = busy or not task.ready
            still = []
            for ct in commits:
                done = False
                try:
                    done = self._try_commit(ct)
                except Exception:
                    pass
                if done:
                    out["committed"] += 1
                    with self._lock:
                        if ct in self._commits:
                            self._commits.remove(ct)
                else:
                    still.append(ct)
                    busy = True
            commits = still
            if not busy or time.monotonic() >= deadline:
                return out
            time.sleep(min(0.05, max(deadline - time.monotonic(), 0.0)))

    # -- worker ----------------------------------------------------------
    def _run(self):
        import contextlib

        ctx = (self._sched.scope() if self._sched is not None
               else contextlib.nullcontext())
        with ctx:
            while not self._stop.wait(self.cfg.worker_interval_s):
                if self.dead:
                    return
                try:
                    self._tick()
                except Exception:
                    # the worker is the plane's heart: a store outage or a
                    # single bad record must never kill it
                    pass

    def _tick(self):
        self._advance_pushes()
        self._drain_incoming()
        self._answer_pulls()
        self._advance_commits()
        # every rank prunes its own retired blobs (replica holders too —
        # the committer only retires the manifests); throttled to one
        # store scan per second
        # det-ok: prune/scrub throttles pace MAINTENANCE against real
        # time; commit/push ordering is store-sequenced, not clocked
        if time.monotonic() - self._last_prune >= 1.0:
            self._prune_local()
        if (self.cfg.scrub_interval_s is not None
                # det-ok: same maintenance throttle as the prune above
                and time.monotonic() - self._last_scrub
                >= self.cfg.scrub_interval_s):
            self.scrub_once()

    # push pipeline ------------------------------------------------------
    def _push_one(self, task: _PushTask, peer: str) -> bool:
        """One push attempt of one shard blob to one peer, through the
        ``ckpt.replica.push`` seam. Returns True when bytes were sent."""
        f = _inject_fire("ckpt.replica.push", step=task.step,
                         shard=task.shard, peer=peer, node=self.node)
        self._c_pushes.inc(node=self.node)
        task.attempts[peer] = task.attempts.get(peer, 0) + 1
        task.pushed_at[peer] = time.monotonic()
        if f is not None and f.kind == "drop":
            return False  # the push is silently lost; confirm times out
        data = task.data
        if f is not None and f.kind == "garbage":
            corrupt = bytearray(data)
            corrupt[len(corrupt) // 2] ^= 0xFF
            data = bytes(corrupt)
        elif f is not None and f.kind == "torn":
            data = data[: max(1, len(data) // 2)]
        # the head is stamped with the CLEAN blob's crc/length: a
        # garbage/torn payload fails the receiver's verify exactly like
        # wire corruption would
        self.tx.put(f"ckb:{peer}:{task.step}:{task.shard}", data,
                    crc=task.crc, nbytes=len(task.data))
        return True

    def _confirm_and_ready(self, task: _PushTask,
                           force_check: bool = False) -> bool:
        """Collect residency receipts; once ``required`` DISTINCT peers
        confirmed, publish the shard-ready record (the committer's
        evidence). Receipt RPCs run unlocked; the dedup + quorum decision
        is atomic under the task lock (worker vs emergency_flush)."""
        with task.lock:
            if task.ready:
                return True
            unconfirmed = [p for p in task.active
                           if p not in task.confirmed]
        newly = []
        for peer in unconfirmed:
            raw = self.store.get(f"ckres:{task.step}:{task.shard}:{peer}")
            if raw is not None and raw == str(task.crc):
                newly.append(peer)
        publish = False
        with task.lock:
            for p in newly:
                if p not in task.confirmed:
                    task.confirmed.append(p)
            if not task.ready and len(task.confirmed) >= task.required:
                task.ready = True  # claim: exactly one thread publishes
                publish = True
            replicas = sorted(task.confirmed)
            ready = task.ready
        if publish:
            try:
                self.store.put(
                    f"ckrdy:{task.step}:{task.shard}",
                    json.dumps({"owner": self.node, "replicas": replicas,
                                "crc": task.crc,
                                "generation": task.generation,
                                "nbytes": len(task.data)}))
            except BaseException:
                with task.lock:
                    task.ready = False  # let the next pass retry
                raise
        if ready:
            return True
        if force_check:
            return False
        # re-push peers whose confirm window lapsed (dropped/garbage/torn
        # transfers); after push_retries on a peer, rotate in the next
        # standby rank so a black-holed peer cannot sink redundancy
        now = time.monotonic()
        for peer in list(task.active):
            if peer in task.confirmed:
                continue
            at = task.pushed_at.get(peer)
            if at is None:
                self._push_one(task, peer)
            elif now - at > self.cfg.push_confirm_timeout_s:
                if task.attempts.get(peer, 0) > self.cfg.push_retries:
                    if task.standby:
                        repl = task.standby.pop(0)
                        task.active[task.active.index(peer)] = repl
                        self._push_one(task, repl)
                    continue  # exhausted: the ready bar holds the task
                else:
                    self._push_one(task, peer)
        return False

    def _advance_pushes(self):
        with self._lock:
            tasks = list(self._pushes)
        for task in tasks:
            done = False
            try:
                done = self._confirm_and_ready(task)
            except Exception:
                pass
            if done or time.monotonic() > task.deadline:
                with self._lock:
                    if task in self._pushes:
                        self._pushes.remove(task)

    # receive pipeline ---------------------------------------------------
    def _drain_incoming(self):
        try:
            keys = self.store.scan(keys_only=True,
                                   prefix=f"ckb:{self.node}:")
        except Exception:
            return
        for key in sorted(keys):
            if _CHUNK_RE.search(key):
                continue
            parts = key.split(":")
            if len(parts) != 4:
                continue
            try:
                step, shard = int(parts[2]), int(parts[3])
            except ValueError:
                continue
            try:
                data = self.tx.get(key)
            except BlobCorruptionError:
                # garbage/torn transfer: reject, delete, let the owner's
                # confirm timeout drive a clean re-push
                self.tx.delete(key)
                continue
            if data is None:
                continue  # head present but chunks missing: skip this tick
            self._write_local(step, shard, data, source="replica")
            try:
                self.store.put(f"ckres:{step}:{shard}:{self.node}",
                               str(zlib.crc32(data)))
            except Exception:
                pass
            self.tx.delete(key)

    # pull service -------------------------------------------------------
    def _answer_pulls(self):
        try:
            reqs = self.store.scan(prefix=f"ckpl:{self.node}:")
        except Exception:
            return
        for key in sorted(reqs):
            raw = reqs[key][0]
            try:
                req = json.loads(raw)
                step, shard = int(req["step"]), int(req["shard"])
                reply = str(req["reply"])
            except (ValueError, KeyError, TypeError):
                self.store.delete(key)
                continue
            try:
                data = self._read_local(step, shard)
            except BlobCorruptionError:
                data = None  # our copy rotted: answer miss, let scrub fix
            if data is None:
                self.store.put(reply, json.dumps({"miss": True}))
            else:
                self.tx.put(reply, data)
            self.store.delete(key)

    # commit pipeline ----------------------------------------------------
    def _try_commit(self, ct: _CommitTask) -> bool:
        ready = {}
        for j in range(ct.world):
            raw = self.store.get(f"ckrdy:{ct.step}:{j}")
            if raw is None:
                return False
            try:
                rec = json.loads(raw)
            except ValueError:
                return False
            # generation fence: a ready record left behind by an ABANDONED
            # commit of this same step number (the step was re-executed
            # after an elastic regroup) describes blobs that no longer
            # exist — committing it would stamp the manifest with CRCs
            # matching no surviving data. Only same-generation records
            # count; the re-executed save publishes a fresh record.
            if int(rec.get("generation", -1)) != ct.generation:
                return False
            ready[str(j)] = rec
        manifest = {"step": ct.step, "world": ct.world,
                    "generation": ct.generation, "members": ct.members,
                    "layout": ct.layout, "shards": ready,
                    "committed_by": self.node}
        # the manifest put IS the visibility commit point: before this
        # write the snapshot does not exist as far as any loader knows
        self.store.put(f"ckmf:{ct.step:012d}", json.dumps(manifest))
        self._c_manifests.inc(node=self.node)
        for j in range(ct.world):
            try:
                self.store.delete(f"ckrdy:{ct.step}:{j}")
            except Exception:
                pass
        with self._lock:
            self._committed_newest = max(ct.step,
                                         self._committed_newest or -1)
        self._retire_manifests()
        self._prune_local()
        self._update_gauges()
        return True

    def _retire_manifests(self):
        """Committer-side rotation: manifests past ``keep_manifests`` are
        DELETED from the store (with their residency receipts) before any
        rank prunes the backing blobs — a retired snapshot is formally
        withdrawn, never silently advertised while its blobs are gone.
        The keep window itself is unprunable, so the newest committed
        manifest can never be retired."""
        steps = self.manifest_steps()
        retired = steps[: -max(1, self.cfg.keep_manifests)]
        for s in retired:
            try:
                self.store.delete(f"ckmf:{s:012d}")
                for k in self.store.scan(keys_only=True,
                                         prefix=f"ckres:{s}:"):
                    self.store.delete(k)
            except Exception:
                pass  # best-effort: a missed GC retries next commit
        if retired:
            # replica pushes addressed to a peer that died before draining
            # them (ckb:<peer>:<step>:<shard>) have no other reaper — the
            # committer sweeps any at or below the newest retired step
            horizon = retired[-1]
            try:
                for k in self.store.scan(keys_only=True, prefix="ckb:"):
                    parts = _CHUNK_RE.sub("", k).split(":")
                    if len(parts) == 4 and parts[2].isdigit() \
                            and int(parts[2]) <= horizon:
                        self.store.delete(k)
            except Exception:
                pass

    def _advance_commits(self):
        with self._lock:
            tasks = list(self._commits)
        for ct in tasks:
            done = False
            try:
                done = self._try_commit(ct)
            except Exception:
                pass
            if done or time.monotonic() > ct.deadline:
                # an abandoned commit leaves NO manifest: the incomplete
                # snapshot stays invisible, which is the contract. GC the
                # shard-ready records already published for it so they can
                # never linger into a later commit of a re-executed step
                # (the generation fence in _try_commit is the correctness
                # backstop; this keeps the store clean).
                if not done:
                    for j in range(ct.world):
                        try:
                            raw = self.store.get(f"ckrdy:{ct.step}:{j}")
                            if raw is None:
                                continue
                            # only reap THIS commit's records — a fresh
                            # record from a re-executed save (newer
                            # generation) belongs to the next commit
                            rec = json.loads(raw)
                            if int(rec.get("generation", -1)) \
                                    == ct.generation:
                                self.store.delete(f"ckrdy:{ct.step}:{j}")
                        except Exception:
                            pass
                with self._lock:
                    if ct in self._commits:
                        self._commits.remove(ct)

    # scrub / repair -----------------------------------------------------
    def scrub_once(self) -> Dict[str, int]:
        """One scrub pass over every resident blob: re-verify CRCs,
        quarantine corrupt files (rename — intact copies are never
        touched, so the newest intact copy can never be scrubbed away),
        re-replicate from peers to restore redundancy, update gauges and
        leave one flight dump per corruption found."""
        self._last_scrub = time.monotonic()
        found = {"checked": 0, "corrupt": 0, "repaired": 0}
        for (step, shard), meta in sorted(self.resident().items()):
            path = self._blob_path(step, shard)
            f = _inject_fire("ckpt.scrub.corrupt", step=step, shard=shard,
                             node=self.node)
            if f is not None and f.kind in ("corrupt", "garbage", "bitflip"):
                self._flip_byte(path)
            found["checked"] += 1
            try:
                data = self._read_local(step, shard)
                ok = data is not None
            except BlobCorruptionError:
                ok = False
            if ok:
                continue
            found["corrupt"] += 1
            self._c_scrub.inc(node=self.node)
            self._quarantine(step, shard)
            self._corruption_dump(step, shard, path)
            # repair: pull a clean copy back from any peer holder
            m = self.manifest(step)
            if m is not None and str(shard) in m.get("shards", {}):
                want = int(m["shards"][str(shard)]["crc"])
                try:
                    data = self._pull(step, shard, m, want,
                                      time.monotonic()
                                      + self.cfg.pull_hop_timeout_s * 2,
                                      service=self._service_while_repair)
                    self._write_local(step, shard, data, source="repaired")
                    self.store.put(f"ckres:{step}:{shard}:{self.node}",
                                   str(want))
                    found["repaired"] += 1
                except Exception:
                    pass  # no clean copy reachable: redundancy stays down
                    # until a later scrub or load restores it
        self._update_gauges()
        return found

    def _service_while_repair(self):
        """Service pass run inside a scrub-repair pull's poll wait: the
        repair shares the plane's single worker thread, and peers pulling
        FROM this node (or waiting on push confirms) must not starve for
        the repair hop's duration."""
        self._drain_incoming()
        self._answer_pulls()
        self._advance_pushes()

    @staticmethod
    def _flip_byte(path: str):
        try:
            with open(path, "r+b") as f:
                f.seek(max(0, os.path.getsize(path) // 2))
                b = f.read(1)
                if b:
                    f.seek(-1, os.SEEK_CUR)
                    f.write(bytes([b[0] ^ 0xFF]))
        except OSError:
            pass

    def _quarantine(self, step: int, shard: int):
        """Move (never delete) a corrupt blob + sidecar aside. Uniquely
        suffixed so repeated corruption of a repaired copy keeps every
        piece of forensic evidence."""
        path = self._blob_path(step, shard)
        stamp = f"q{int(time.time() * 1000)}"
        for suffix in ("", ".meta"):
            src = path + suffix
            if os.path.exists(src):
                dst = os.path.join(self.quarantine_dir,
                                   os.path.basename(src) + f".{stamp}")
                try:
                    os.rename(src, dst)
                except OSError:
                    pass

    def _corruption_dump(self, step: int, shard: int, path: str):
        try:
            from ..observability.flight import flight_recorder

            flight_recorder().dump(
                "ckpt_scrub_corruption",
                extra={"node": self.node, "step": int(step),
                       "shard": int(shard), "path": path})
        except Exception:
            pass

    # housekeeping -------------------------------------------------------
    def _prune_local(self):
        """Evict local blobs whose snapshot has been RETIRED (its
        manifest no longer exists in the store and a newer committed
        manifest does). Runs on EVERY rank's worker — replica holders
        prune too, not just the committer. Steps newer than the newest
        committed manifest are always kept (their manifest may still be
        in flight), steps whose manifest is still committed are backing
        a live snapshot, and the newest committed step is therefore
        structurally unprunable — the single-disk prune audit rule,
        cluster edition."""
        self._last_prune = time.monotonic()
        steps = self.manifest_steps()
        if not steps:
            return
        newest = steps[-1]
        with self._lock:
            self._committed_newest = newest = max(
                newest, self._committed_newest or -1)
        live = set(steps)
        for (step, shard) in list(self.resident()):
            if step >= newest or step in live:
                continue
            for suffix in ("", ".meta"):
                try:
                    os.unlink(self._blob_path(step, shard) + suffix)
                except OSError:
                    pass
        # orphan pull responses addressed to US (reqids start with this
        # node's id): a hop that timed out stopped waiting, but the holder
        # may have written the multi-chunk blob afterwards — without this
        # sweep each such race leaks a full shard blob into the store
        try:
            for key in self.store.scan(keys_only=True,
                                       prefix=f"ckpr:{self.node}."):
                reqid = _CHUNK_RE.sub("", key.split(":", 1)[1])
                with self._lock:
                    live_req = reqid in self._pending_pulls
                if not live_req:
                    self.store.delete(key)
        except Exception:
            pass

    def _update_gauges(self):
        # the manifest scan is a store RPC and must run unlocked; the
        # dependent write below re-validates with max() under the lock,
        # so a concurrent commit in the window can only raise the value
        # hostrace: ok(host-toctou)
        with self._lock:
            own = self._own_newest
            newest = self._committed_newest
        if newest is None:
            steps = self.manifest_steps()
            if steps:
                with self._lock:
                    self._committed_newest = newest = max(
                        steps[-1], self._committed_newest or -1)
        lag = 0 if own is None or newest is None else max(0, own - newest)
        self._g_lag.set(lag, node=self.node)
        if newest is not None:
            n = sum(1 for (s, _j) in self.resident() if s == newest)
            self._g_resident.set(n, node=self.node)

    def pending_pushes(self) -> int:
        with self._lock:
            return len(self._pushes)

    def wipe(self):
        """The disk-loss chaos hook: stop the worker and DELETE this
        rank's entire checkpoint directory — local snapshots, replicas,
        quarantine, everything. Peers' copies and the committed manifests
        are the only survivors, which is the point."""
        self.dead = True
        self._stop.set()
        shutil.rmtree(self.root, ignore_errors=True)

    def close(self):
        self._stop.set()
        self._worker.join(timeout=2.0)
