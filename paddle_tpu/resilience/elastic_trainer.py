"""Elastic multi-process data-parallel training with resharded recovery.

Parity: Fleet's elastic multi-node training (the reference's etcd-driven
``ElasticManager`` + collective trainer relaunch). The r7 layer already
survives failures *within* one process (sentinel, preemption checkpoints);
this module survives the failure that dominates production TPU fleets — a
whole RANK preempted mid-run:

* dp rank processes coordinate membership through the elastic
  :class:`~paddle_tpu.distributed.fleet.elastic.manager.ElasticManager`
  (heartbeat TTL liveness) and exchange gradients through the store's KV
  plane (:class:`~paddle_tpu.distributed.fleet.elastic.collective
  .ElasticCollective`) in deterministic rank order;
* momentum slots are ZeRO-style sharded: each rank owns a contiguous
  row-partition of every slot array (``checkpoint.shard_bounds``) and
  updates only its partition of the params, allgathering the shards back —
  the update is elementwise, so the global result is independent of the
  partitioning;
* rank 0 periodically gathers the slot shards and writes ONE global
  snapshot stamped with the dp layout
  (``CheckpointManager.save(layout=...)``) — the snapshot is
  world-size-agnostic; with ``durability=`` (r19) the single writer is
  replaced by the replicated checkpoint data plane
  (:mod:`~paddle_tpu.resilience.durability`): each rank durably writes
  its OWN shard snapshot locally, pushes CRC-stamped replicas to K peer
  ranks, and the snapshot becomes visible only when a manifest commits
  to the (quorum-replicated) store — so losing a rank AND its disk
  costs nothing as long as redundancy holds, and a replacement rank
  with an empty disk recovers entirely from peer replicas;
* when a rank's heartbeat lapses mid-collective (:class:`RankFailure`),
  survivors bump the rendezvous generation, agree on the new world size,
  reshard the newest INTACT snapshot
  (:func:`~paddle_tpu.framework.checkpoint.reshard_train_state`) and
  continue — the recovery leader broadcasts the chosen snapshot step so
  two survivors can never resume from different checkpoints.

Because gradients are averaged in rank order and the data stream is keyed
by ``(step, rank, world)``, the survivors' post-recovery loss trajectory is
bit-identical to a fresh (N−k)-rank run restored from the same resharded
snapshot — the e2e acceptance test SIGKILLs a rank and asserts exactly
that.
"""
from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..distributed.fleet.elastic.collective import (
    ElasticCollective,
    RankFailure,
    pack_arrays,
    unpack_arrays,
)
from ..distributed.fleet.elastic.manager import StoreUnavailable
from ..observability import trace as obstrace
from ..observability.flight import flight_recorder
from ..observability.metrics import default_registry
from ..framework.checkpoint import (
    CheckpointManager,
    reshard_train_state,
    shard_bounds,
    shard_slice,
    unshard,
)
from .durability import CheckpointDataPlane, DurabilityConfig

__all__ = ["ElasticDPTrainer"]

GradFn = Callable[[Dict[str, np.ndarray], int, int, int],
                  Tuple[float, Dict[str, np.ndarray]]]


class ElasticDPTrainer:
    """Data-parallel momentum-SGD driver for one elastic rank process.

    ``grad_fn(params, step, rank, world) -> (loss, grads)`` computes this
    rank's local loss/gradients on its shard of the global batch — it must
    be a pure function of its arguments (the data stream keyed by
    ``(step, rank, world)``), which is what makes recovery trajectories
    reproducible. ``init_params()`` must return identical arrays on every
    rank (seed it).

    The manager's store must be a ``_TcpStore`` (HTTP KV server): the
    shared-filesystem fallback has no KV data plane.
    """

    def __init__(self, manager, ckpt_dir: str, grad_fn: GradFn,
                 init_params: Callable[[], Dict[str, np.ndarray]], *,
                 lr: float = 0.1, momentum: float = 0.9, min_ranks: int = 1,
                 save_every: int = 1, keep_max: int = 10,
                 step_timeout: float = 60.0, rendezvous_timeout: float = 60.0,
                 on_step: Optional[Callable] = None,
                 on_event: Optional[Callable[[str], None]] = None,
                 durability: Optional[DurabilityConfig] = None):
        if not hasattr(manager.store, "scan"):
            raise TypeError(
                "ElasticDPTrainer needs a KV-plane store (_TcpStore via "
                "PADDLE_ELASTIC_SERVER); the shared-FS _FileStore only "
                "does membership")
        self.manager = manager
        self.collective = ElasticCollective(manager.store, manager.node_id)
        if durability is not None:
            # replicated data plane (r19): ckpt_dir is THIS RANK'S private
            # directory; each rank persists its own shard, replicates to K
            # peers and the snapshot is visible only via a committed
            # manifest in the (quorum-replicated) store
            self.plane: Optional[CheckpointDataPlane] = CheckpointDataPlane(
                manager.store, manager.node_id, ckpt_dir, durability)
            self.ckpt: Optional[CheckpointManager] = None
        else:
            self.plane = None
            self.ckpt = CheckpointManager(ckpt_dir, keep_max=keep_max)
        self.grad_fn = grad_fn
        self.init_params = init_params
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.min_ranks = int(min_ranks)
        self.save_every = max(1, int(save_every))
        self.step_timeout = float(step_timeout)
        self.rendezvous_timeout = float(rendezvous_timeout)
        self.on_step = on_step
        self.on_event = on_event or (lambda msg: None)
        self.params: Dict[str, np.ndarray] = {}
        self.velocity: Dict[str, np.ndarray] = {}  # THIS RANK'S shards only
        self.step = 0
        self.recoveries = 0
        # rank 0's _pick_snapshot already fully loads the newest snapshot
        # to resolve its step; _restore reuses that load instead of paying
        # the read+CRC cost twice per recovery
        self._pick_cache: Optional[tuple] = None
        self.history: List[Tuple[int, int, float]] = []  # (step, world, loss)
        # first-class elastic series (the rendezvous generation and world
        # size become scrapeable next to the serving/router planes)
        r = default_registry()
        self._g_world = r.gauge("elastic_world_size",
                                "committed dp world size", ("node",))
        self._g_gen = r.gauge("elastic_rendezvous_generation",
                              "committed rendezvous generation", ("node",))
        self._g_rank = r.gauge("elastic_rank", "this process's rank",
                               ("node",))
        self._c_recoveries = r.counter(
            "elastic_recoveries_total",
            "rank-failure recoveries survived", ("node",))
        self._node = str(manager.node_id)

    # -- state shape ----------------------------------------------------
    @property
    def rank(self) -> int:
        return self.collective.rank

    @property
    def world(self) -> int:
        return self.collective.world

    def _layout(self) -> Dict[str, Dict]:
        """Snapshot layout: slot arrays are gathered-from-sharded (axis 0
        over the CURRENT world); params are replicated (absent)."""
        return {f"/velocity/{n}": {"axis": 0, "world": self.world}
                for n in self.params}

    @staticmethod
    def _check_shardable(params: Dict[str, np.ndarray]):
        """Momentum slots are row-sharded over axis 0, so every parameter
        needs at least one axis — fail a 0-d (scalar) param up front with
        guidance instead of an IndexError deep inside step 1."""
        bad = sorted(n for n, p in params.items() if np.ndim(p) == 0)
        if bad:
            raise ValueError(
                f"ElasticDPTrainer cannot row-shard 0-d parameter(s) "
                f"{bad}: reshape scalars to (1,) in init_params()")

    def _fresh_velocity(self):
        self.velocity = {
            n: np.zeros_like(shard_slice(p, self.world, self.rank))
            for n, p in self.params.items()
        }

    # -- lifecycle ------------------------------------------------------
    def _join(self, gen: int, min_ranks: Optional[int] = None):
        with obstrace.span("train.rendezvous", generation=int(gen)):
            self.collective.rendezvous(gen,
                                       min_ranks=min_ranks or self.min_ranks,
                                       timeout=self.rendezvous_timeout)
        self._g_world.set(self.world, node=self._node)
        self._g_rank.set(self.rank, node=self._node)
        self._g_gen.set(int(self.collective.generation), node=self._node)
        flight_recorder().note(world=self.world, rank=self.rank,
                               generation=int(self.collective.generation))
        self.on_event(f"rendezvous gen={gen} rank={self.rank}/"
                      f"{self.world} members={self.collective.members}")

    def _pick_snapshot(self, prefer: Optional[int] = None) -> Optional[int]:
        """Leader-broadcast snapshot decision, run by EVERY member after
        EVERY rendezvous commit (initial join and recovery alike — a rank
        on the initial path and a rank mid-recovery meet in the same
        generation, so the protocol must be symmetric or the non-leader
        waits for a broadcast that never comes). Rank 0 resolves the step
        (``prefer`` if forced, else the newest INTACT snapshot — corrupt
        ones are skipped with a warning by CheckpointManager.load) and
        broadcasts it; peers poll for the decision instead of each walking
        the directory — two survivors must never resume from different
        steps."""
        key = f"recover{self.collective.generation}"
        if self.rank == 0:
            if prefer is not None:
                chosen: Optional[int] = prefer
            elif self.plane is not None:
                # replicated plane: the newest COMMITTED manifest whose
                # every shard still has a live holder — the cluster-level
                # newest-intact rule (an uncommitted snapshot was never
                # visible, a coverage-lost one is walked past)
                try:
                    live = set(self.manager.store.nodes())
                except OSError:
                    live = set(self.collective.members)
                chosen = self.plane.newest_recoverable(live)
            else:
                try:
                    state, metadata = self.ckpt.load()
                    chosen = self.ckpt.last_loaded_step
                    self._pick_cache = (chosen, state,
                                        self.ckpt.last_loaded_meta or {},
                                        metadata)
                except FileNotFoundError:
                    chosen = None
            # det-ok: rendezvous timeouts bound LIVENESS (give up on a
            # dead store); the chosen step is store-content, not clocked
            deadline = time.monotonic() + self.rendezvous_timeout
            while True:
                try:
                    self.manager.store.put(key, json.dumps({"step": chosen}))
                    break
                except OSError:
                    # store failover window: the members are all polling
                    # for this broadcast — keep trying to land it
                    # det-ok: liveness bound only (see deadline above)
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            return chosen
        # det-ok: rendezvous poll deadline — liveness bound only
        deadline = time.monotonic() + self.rendezvous_timeout
        # det-ok: poll loop bounded by the liveness deadline above
        while time.monotonic() < deadline:
            try:
                raw = self.manager.store.get(key)
                if raw is not None:
                    return json.loads(raw)["step"]
                leader = self.collective.members[0]
                if leader not in self.manager.store.nodes():
                    raise RankFailure("recovery leader died before "
                                      "broadcasting the snapshot step",
                                      dead=[leader])
            except OSError:
                pass  # store failover window: keep polling to deadline
            time.sleep(0.05)
        raise TimeoutError("no snapshot decision from the recovery leader")

    def _restore(self, snapshot_step: Optional[int]):
        """Load + reshard ``snapshot_step`` (None ⇒ virgin start)."""
        with obstrace.span("train.reshard",
                           snapshot_step=snapshot_step, world=self.world):
            self._restore_impl(snapshot_step)

    def _restore_impl(self, snapshot_step: Optional[int]):
        cache, self._pick_cache = self._pick_cache, None
        if snapshot_step is None:
            self.params = {n: np.array(a)
                           for n, a in self.init_params().items()}
            self._check_shardable(self.params)
            self._fresh_velocity()
            self.step = 0
            self.on_event("restore: no snapshot, starting from init")
            return
        if self.plane is not None:
            # assemble from local blobs + peer replicas (a replacement
            # rank with an EMPTY disk recovers entirely over the wire),
            # CRC-verified against the committed manifest
            state, layout = self.plane.load_step(
                snapshot_step, timeout=self.rendezvous_timeout,
                live_nodes=list(self.collective.members))
            _meta = {"world": next((e.get("world")
                                    for e in layout.values()), None)}
        elif cache is not None and cache[0] == snapshot_step:
            state, full_meta, _meta = cache[1], cache[2], cache[3]
            layout = full_meta.get("layout", {})
        else:
            state, _meta = self.ckpt.load(step=snapshot_step)
            full_meta = self.ckpt.last_loaded_meta or {}
            layout = full_meta.get("layout", {})
        local = reshard_train_state(state, layout, self.world, self.rank)
        self.params = {n: np.array(a) for n, a in state["params"].items()}
        self._check_shardable(self.params)
        self.velocity = {n: np.array(a)
                         for n, a in local["velocity"].items()}
        self.step = int(state["step"]) + 1
        self.on_event(
            f"restore: snapshot step={snapshot_step} "
            f"(saved at world={_meta.get('world')}) resharded to "
            f"world={self.world}, resuming at step {self.step}")

    def _recover(self, reason: str, prefer: Optional[int] = None):
        """Re-rendezvous on the survivors and reload/reshard. Loops when a
        FURTHER rank dies mid-recovery (e.g. the recovery leader), bounded
        by the rendezvous timeout per attempt. ``prefer`` forwards an
        explicit snapshot step (the initial-restore path retrying after
        the leader died pre-broadcast must not lose its ``resume_step``)."""
        self.recoveries += 1
        self._c_recoveries.inc(node=self._node)
        obstrace.event("train.rank_failure", reason=str(reason)[:200])
        while True:
            self.on_event(f"recovering ({reason})")
            try:
                self._join(self.collective.generation + 1)
                self._restore(self._pick_snapshot(prefer=prefer))
                return
            except RankFailure as e:
                reason = str(e)

    # -- one step --------------------------------------------------------
    def _train_one_step(self) -> float:
        s, world, rank = self.step, self.world, self.rank
        # injection seam: a scheduled `kill` is this rank's deterministic
        # SIGKILL — heartbeats halt FIRST (peers must see TTL expiry, not
        # a goodbye) and InjectedDeath unwinds the rank exactly where a
        # real kill would: before this step's gradients ever publish
        from .inject import fire as _inject_fire

        f = _inject_fire("elastic.rank.step", rank=rank, step=s,
                         node=self._node)
        if f is not None and f.kind == "kill":
            self.manager.halt_heartbeat()
            raise f.build_exception()
        # the double failure the replicated plane exists for: the rank
        # dies AND its local checkpoint storage is gone (preemption with
        # local SSD). Heartbeats halt first (peers must see TTL expiry),
        # the directory is wiped like a reclaimed disk, and InjectedDeath
        # unwinds the rank before this step's gradients ever publish.
        f = _inject_fire("ckpt.disk.loss", rank=rank, step=s,
                         node=self._node)
        if f is not None and f.kind == "kill":
            self.manager.halt_heartbeat()
            if self.plane is not None:
                self.plane.wipe()
            elif self.ckpt is not None:
                import shutil as _shutil

                _shutil.rmtree(self.ckpt.directory, ignore_errors=True)
            raise f.build_exception()
        fr = flight_recorder()
        if fr.armed or obstrace.tracing_enabled():
            fr.note(step=s)
        with obstrace.span("train.step", step=s, world=world, rank=rank):
            return self._train_one_step_impl(s, world, rank)

    def _train_one_step_impl(self, s: int, world: int, rank: int) -> float:
        loss, grads = self.grad_fn(self.params, s, rank, world)
        blobs = self.collective.allgather(
            f"g{s}", pack_arrays({"loss": np.asarray([loss], np.float64),
                                  **grads}),
            timeout=self.step_timeout)
        trees = [unpack_arrays(b) for b in blobs]  # rank order
        mean_loss = float(np.mean(np.stack(
            [t["loss"][0] for t in trees])))
        save_now = (s % self.save_every) == 0
        out: Dict[str, np.ndarray] = {}
        for n in sorted(self.params):
            # slice each peer's gradient to OUR row shard before the
            # mean: elementwise over the same W values in the same stack
            # order, so bit-identical to averaging the full arrays, and
            # W× cheaper on the hot path
            lo, hi = shard_bounds(self.params[n].shape[0], world)[rank]
            g = np.mean(np.stack([t[n][lo:hi] for t in trees]), axis=0)
            v = self.momentum * self.velocity[n] + g
            self.velocity[n] = v
            out[f"p:{n}"] = self.params[n][lo:hi] - self.lr * v
            if save_now and self.plane is None:
                # the single-writer path gathers every velocity shard to
                # rank 0; the replicated plane does NOT — each rank saves
                # its own shard locally, so the save costs zero extra
                # allgather bandwidth
                out[f"v:{n}"] = v
        shard_blobs = self.collective.allgather(
            f"p{s}", pack_arrays(out), timeout=self.step_timeout)
        shards = [unpack_arrays(b) for b in shard_blobs]
        for n in self.params:
            self.params[n] = unshard([t[f"p:{n}"] for t in shards])
        if save_now:
            if self.plane is not None:
                # every rank persists {replicated params, OWN velocity
                # shard}; the worker replicates to K peers and rank 0
                # commits the manifest once every shard reports durable
                # + confirmed — visibility is the manifest, not the file
                self.plane.save_shard(
                    s, {"params": dict(self.params),
                        "velocity": dict(self.velocity), "step": s},
                    rank=rank, world=world,
                    members=list(self.collective.members),
                    layout=self._layout(),
                    generation=int(self.collective.generation))
            elif rank == 0:
                velocity = {n: unshard([t[f"v:{n}"] for t in shards])
                            for n in self.params}
                self.ckpt.save(s, {"params": dict(self.params),
                                   "velocity": velocity, "step": s},
                               metadata={"world": world},
                               layout=self._layout())
        return mean_loss

    # -- driver ----------------------------------------------------------
    def run(self, total_steps: int, resume_step: Optional[int] = None,
            wait_world: Optional[int] = None) -> List[Tuple[int, int, float]]:
        """Train to ``total_steps`` global steps, recovering from rank
        failures along the way. ``resume_step`` forces the initial restore
        to an explicit snapshot (the fresh-run-from-resharded-snapshot
        comparison arm); default is newest-intact-or-init. ``wait_world``
        makes the INITIAL rendezvous hold out for that many ranks (a
        cohort launched together must not let its fastest starter commit
        a world of one and train ahead); recoveries still commit on
        whatever survives (``min_ranks``)."""
        self.manager.register()
        # join one PAST the highest generation ever proposed: incumbents
        # (if any) will meet us there on their next membership check, and
        # racing fresh starters adopt the max inside rendezvous()
        self._join(self.collective.latest_generation() + 1,
                   min_ranks=max(self.min_ranks, wait_world or 0))
        try:
            self._restore(self._pick_snapshot(prefer=resume_step))
        except RankFailure as e:
            # the leader died between committing the rendezvous and
            # broadcasting the snapshot step — recover exactly like a
            # mid-training death (keeping the explicit resume preference)
            self._recover(str(e), prefer=resume_step)
        store_deadline = None  # bounds consecutive store-outage retries
        while self.step < total_steps:
            if self.collective.membership_changed():
                self._recover("membership changed at step boundary")
                continue
            try:
                loss = self._train_one_step()
            except RankFailure as e:
                self._recover(str(e))
                continue
            except StoreUnavailable as e:
                # coordination-store outage outlasting the collective's
                # own in-loop tolerance (e.g. a replicated-store failover
                # colliding with a retry burst): retry the SAME step —
                # grad_fn is pure and the allgather keys/payloads are
                # keyed by (generation, step, rank), so the replay is
                # idempotent and the trajectory unchanged. Bounded: a
                # store that stays dead past step_timeout re-raises.
                now = time.monotonic()
                if store_deadline is None:
                    store_deadline = now + self.step_timeout
                if now > store_deadline:
                    raise
                self.on_event(
                    f"store unavailable at step {self.step}; retrying")
                time.sleep(0.1)
                continue
            store_deadline = None
            self.history.append((self.step, self.world, loss))
            if self.on_step is not None:
                self.on_step(self.step, self.world, loss)
            self.step += 1
        return self.history

    def close(self):
        if self.plane is not None:
            self.plane.close()
        self.manager.exit()
