"""Retry-with-backoff primitives shared by the resilience layer.

Parity: the reference's etcd elastic manager retries transient registry
failures inside the etcd client; our HTTP KV store (fleet/utils/http_server)
deliberately has a dumb client that reports failure, so the retry policy
lives here — exponential backoff with decorrelated jitter, the standard
recipe for not stampeding a recovering store.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type

__all__ = ["backoff_delays", "call_with_retries", "RetryError"]


class RetryError(RuntimeError):
    """All attempts failed; ``last`` holds the final exception (or None when
    the callable signalled failure by return value)."""

    def __init__(self, msg: str, last: Optional[BaseException] = None):
        super().__init__(msg)
        self.last = last


def backoff_delays(retries: int, base: float = 0.05, max_delay: float = 2.0,
                   jitter: float = 0.5) -> Iterator[float]:
    """Yield ``retries`` sleep intervals: base * 2^k, capped at ``max_delay``,
    each scaled by a uniform factor in [1-jitter, 1+jitter] so a fleet of
    clients retrying the same dead store spreads out instead of thundering."""
    for k in range(retries):
        # cap the exponent: 2.0**k overflows float (OverflowError) near
        # k=1024, and long-lived poll loops (elastic wait_for_np) drive k
        # far past the point where max_delay already dominates
        d = min(base * (2.0 ** min(k, 63)), max_delay)
        yield d * (1.0 + jitter * (2.0 * random.random() - 1.0))


def call_with_retries(fn: Callable, *, retries: int = 4, base: float = 0.05,
                      max_delay: float = 2.0, jitter: float = 0.5,
                      retry_on: Tuple[Type[BaseException], ...] = (OSError,),
                      ok: Callable = lambda r: True,
                      sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` up to ``retries + 1`` times.

    A failure is either an exception in ``retry_on`` or a return value that
    ``ok`` rejects (the KV client reports failure as False/None rather than
    raising). Returns the first accepted value; raises :class:`RetryError`
    when every attempt failed."""
    last_exc: Optional[BaseException] = None
    delays = backoff_delays(retries, base=base, max_delay=max_delay,
                            jitter=jitter)
    for attempt in range(retries + 1):
        try:
            result = fn()
        except retry_on as e:
            last_exc = e
        else:
            if ok(result):
                return result
            last_exc = None
        if attempt < retries:
            sleep(next(delays))
    raise RetryError(
        f"{getattr(fn, '__name__', 'call')} failed after {retries + 1} "
        f"attempts", last=last_exc)
