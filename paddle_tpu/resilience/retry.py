"""Retry-with-backoff primitives shared by the resilience layer.

Parity: the reference's etcd elastic manager retries transient registry
failures inside the etcd client; our HTTP KV store (fleet/utils/http_server)
deliberately has a dumb client that reports failure, so the retry policy
lives here — exponential backoff with decorrelated jitter, the standard
recipe for not stampeding a recovering store.

:class:`RetryBudget` adds the missing global dimension: per-call retry caps
bound ONE operation, but a persistent fault (an injected ``every=1`` store
failure, a dead dependency) makes every caller burn its full per-call
allowance in lockstep — N subsystems × (retries+1) attempts against a
dependency that is not coming back. A budget caps total RETRY attempts
(first attempts are always free) across an operation window; once spent,
``call_with_retries`` fails fast with ``RetryError.budget_exhausted=True``
and increments the ``retry_budget_exhausted_total`` counter in the
observability registry.
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Iterator, Optional, Tuple, Type

__all__ = ["backoff_delays", "call_with_retries", "RetryError",
           "RetryBudget", "set_default_budget", "default_budget"]


class RetryError(RuntimeError):
    """All attempts failed; ``last`` holds the final exception (or None when
    the callable signalled failure by return value). ``budget_exhausted``
    is True when the retry BUDGET cut the attempts short (fail-fast under a
    persistent fault) rather than the per-call retry cap running out."""

    def __init__(self, msg: str, last: Optional[BaseException] = None,
                 budget_exhausted: bool = False):
        super().__init__(msg)
        self.last = last
        self.budget_exhausted = bool(budget_exhausted)


class RetryBudget:
    """Sliding-window cap on total retry attempts across callers.

    ``max_retries`` retries may be spent per ``window_s`` seconds; first
    attempts are never charged (a healthy system with zero failures never
    touches the budget). Thread-safe; one instance is meant to be shared
    by every retry loop talking to the same dependency."""

    def __init__(self, max_retries: int = 64, window_s: float = 30.0):
        if int(max_retries) < 0:
            raise ValueError("max_retries must be >= 0")
        self.max_retries = int(max_retries)
        self.window_s = float(window_s)
        self.exhausted_count = 0  # times try_spend() said no
        self._spent: deque = deque()
        self._lock = threading.Lock()
        self._counter = None  # lazy: observability may not be imported yet

    def _exhausted_counter(self):
        if self._counter is None:
            try:
                from ..observability.metrics import default_registry

                self._counter = default_registry().counter(
                    "retry_budget_exhausted_total",
                    "retry attempts refused by the shared retry budget")
            except Exception:  # pragma: no cover - observability optional
                self._counter = False
        return self._counter or None

    def try_spend(self, now: Optional[float] = None) -> bool:
        """Charge one retry attempt. False = budget spent: the caller must
        fail fast instead of retrying."""
        now = time.monotonic() if now is None else now
        with self._lock:
            while self._spent and now - self._spent[0] > self.window_s:
                self._spent.popleft()
            if len(self._spent) >= self.max_retries:
                self.exhausted_count += 1
                c = self._exhausted_counter()
                if c is not None:
                    c.inc()
                return False
            self._spent.append(now)
            return True

    def remaining(self, now: Optional[float] = None) -> int:
        now = time.monotonic() if now is None else now
        with self._lock:
            while self._spent and now - self._spent[0] > self.window_s:
                self._spent.popleft()
            return max(0, self.max_retries - len(self._spent))


_default_budget: Optional[RetryBudget] = None


def set_default_budget(budget: Optional[RetryBudget]) -> Optional[RetryBudget]:
    """Install (or clear, with None) the process-wide retry budget that
    every ``call_with_retries`` without an explicit ``budget=`` consults.
    Returns the previous budget."""
    global _default_budget
    prev, _default_budget = _default_budget, budget
    return prev


def default_budget() -> Optional[RetryBudget]:
    return _default_budget


def backoff_delays(retries: int, base: float = 0.05, max_delay: float = 2.0,
                   jitter: float = 0.5) -> Iterator[float]:
    """Yield ``retries`` sleep intervals: base * 2^k, capped at ``max_delay``,
    each scaled by a uniform factor in [1-jitter, 1+jitter] so a fleet of
    clients retrying the same dead store spreads out instead of thundering."""
    for k in range(retries):
        # cap the exponent: 2.0**k overflows float (OverflowError) near
        # k=1024, and long-lived poll loops (elastic wait_for_np) drive k
        # far past the point where max_delay already dominates
        d = min(base * (2.0 ** min(k, 63)), max_delay)
        # det-ok: backoff jitter is deliberately decorrelated across
        # processes (thundering-herd control); no replayed decision
        # depends on the delay value
        yield d * (1.0 + jitter * (2.0 * random.random() - 1.0))


def call_with_retries(fn: Callable, *, retries: int = 4, base: float = 0.05,
                      max_delay: float = 2.0, jitter: float = 0.5,
                      retry_on: Tuple[Type[BaseException], ...] = (OSError,),
                      ok: Callable = lambda r: True,
                      sleep: Callable[[float], None] = time.sleep,
                      budget: Optional[RetryBudget] = None):
    """Run ``fn()`` up to ``retries + 1`` times.

    A failure is either an exception in ``retry_on`` or a return value that
    ``ok`` rejects (the KV client reports failure as False/None rather than
    raising). Returns the first accepted value; raises :class:`RetryError`
    when every attempt failed.

    ``budget`` (default: the process-wide :func:`default_budget`, when one
    is installed) charges each RETRY attempt against a shared sliding
    window; a spent budget fails fast (``budget_exhausted=True``) so a
    persistent fault degrades in bounded time instead of every caller
    burning its full backoff sequence."""
    if budget is None:
        budget = _default_budget
    last_exc: Optional[BaseException] = None
    delays = backoff_delays(retries, base=base, max_delay=max_delay,
                            jitter=jitter)
    for attempt in range(retries + 1):
        try:
            result = fn()
        except retry_on as e:
            last_exc = e
        else:
            if ok(result):
                return result
            last_exc = None
        if attempt < retries:
            if budget is not None and not budget.try_spend():
                raise RetryError(
                    f"{getattr(fn, '__name__', 'call')} failed and the "
                    f"shared retry budget is exhausted after "
                    f"{attempt + 1} attempt(s) (fail-fast)",
                    last=last_exc, budget_exhausted=True)
            sleep(next(delays))
    raise RetryError(
        f"{getattr(fn, '__name__', 'call')} failed after {retries + 1} "
        f"attempts", last=last_exc)
