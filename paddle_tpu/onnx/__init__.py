"""paddle_tpu.onnx — model export façade.

Parity: paddle.onnx.export (reference python/paddle/onnx/export.py, which
delegates to the external paddle2onnx package). That package is not available
here and ONNX is not the TPU deployment path — ``export`` therefore emits the
portable StableHLO artifact (via jit.save) next to a clear notice; StableHLO
is this framework's cross-runtime interchange format the way ONNX is the
reference's.
"""
from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version: int = 9, **configs):
    from ..jit import save as jit_save

    warnings.warn(
        "ONNX emission is unavailable (paddle2onnx not present); exporting "
        "portable StableHLO instead — load with paddle_tpu.jit.load or any "
        "StableHLO-consuming runtime",
        stacklevel=2,
    )
    jit_save(layer, path, input_spec=input_spec)
    return path
