"""paddle_tpu.core — the native (C++) host-runtime layer.

The reference keeps its host runtime in C++ (queues, allocators, shared
memory, profiler — see SURVEY.md §2.1/§2.4/§5.1). On TPU, device-side
execution belongs to XLA/PJRT, but the host side of the hot path — feeding
batches, staging offloaded state, recording events — is still native here:
``core.cc`` is compiled on first import (g++, cached by source hash) and
bound over ctypes. Every facility has a pure-Python fallback so the package
works on machines without a toolchain.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

__all__ = [
    "native_available",
    "BlockingQueue",
    "PinnedPool",
    "ShmRing",
    "lib",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native", "core.cc")
_lib = None
_build_err = None


def _build_and_load():
    global _lib, _build_err
    if _lib is not None or _build_err is not None:
        return _lib
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        so_path = os.path.join(_HERE, "native", f"libpaddle_tpu_core_{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-fPIC", "-shared", "-pthread", "-std=c++14",
                 _SRC, "-o", tmp, "-lrt"],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, so_path)  # atomic vs concurrent builders
        lib = ctypes.CDLL(so_path)
        _sig(lib)
        _lib = lib
    except Exception as e:  # no toolchain / sandbox — Python fallbacks take over
        _build_err = e
        _lib = None
    return _lib


def _sig(lib):
    u64, i32, p = ctypes.c_uint64, ctypes.c_int, ctypes.c_void_p
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.pt_now_ns.restype = u64
    lib.ptq_create.restype = p
    lib.ptq_create.argtypes = [u64]
    lib.ptq_push.restype = i32
    lib.ptq_push.argtypes = [p, u8p, u64, i32]
    lib.ptq_pop.restype = i32
    lib.ptq_pop.argtypes = [p, ctypes.POINTER(u8p), ctypes.POINTER(u64), i32]
    lib.ptq_size.restype = u64
    lib.ptq_size.argtypes = [p]
    lib.ptq_close.argtypes = [p]
    lib.ptq_destroy.argtypes = [p]
    lib.pt_free.argtypes = [p]
    lib.ppool_create.restype = p
    lib.ppool_create.argtypes = [u64, i32]
    lib.ppool_alloc.restype = p
    lib.ppool_alloc.argtypes = [p, u64]
    lib.ppool_free.restype = i32
    lib.ppool_free.argtypes = [p, p]
    lib.ppool_stats.argtypes = [p, ctypes.POINTER(u64)]
    lib.ppool_destroy.argtypes = [p]
    lib.shmring_create.restype = p
    lib.shmring_create.argtypes = [ctypes.c_char_p, u64, u64]
    lib.shmring_attach.restype = p
    lib.shmring_attach.argtypes = [ctypes.c_char_p]
    lib.shmring_write.restype = i32
    lib.shmring_write.argtypes = [p, u8p, u64, i32]
    lib.shmring_read.restype = i32
    lib.shmring_read.argtypes = [p, u8p, u64, ctypes.POINTER(u64), i32]
    lib.shmring_count.restype = u64
    lib.shmring_count.argtypes = [p]
    lib.shmring_slot_size.restype = u64
    lib.shmring_slot_size.argtypes = [p]
    lib.shmring_close.argtypes = [p]
    lib.shmring_destroy.argtypes = [p]
    lib.prof_enable.argtypes = [i32]
    lib.prof_is_enabled.restype = i32
    lib.prof_push.argtypes = [ctypes.c_uint32]
    lib.prof_pop.argtypes = []
    lib.prof_collect.restype = u64
    lib.prof_collect.argtypes = [ctypes.POINTER(u64), u64]
    lib.prof_clear.argtypes = []


def lib():
    """The loaded native library, or None when unavailable."""
    return _build_and_load()


def native_available() -> bool:
    return _build_and_load() is not None


def build_error():
    _build_and_load()
    return _build_err


# ---------------------------------------------------------------------------
# BlockingQueue — parity: LoDTensorBlockingQueue (reader/
# lod_tensor_blocking_queue.h). Bounded byte-blob queue; native when possible.
# ---------------------------------------------------------------------------
class _NativeQueue:
    def __init__(self, capacity):
        self._lib = lib()
        self._h = self._lib.ptq_create(capacity)

    def push(self, data: bytes, timeout_ms: int = -1) -> bool:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        rc = self._lib.ptq_push(self._h, buf, len(data), timeout_ms)
        if rc == -2:
            raise RuntimeError("queue closed")
        return rc == 0

    def pop(self, timeout_ms: int = -1):
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_uint64()
        rc = self._lib.ptq_pop(self._h, ctypes.byref(out), ctypes.byref(n), timeout_ms)
        if rc == -1:
            return None
        if rc == -2:
            raise EOFError("queue closed and drained")
        try:
            return ctypes.string_at(out, n.value)
        finally:
            self._lib.pt_free(out)

    def size(self):
        return self._lib.ptq_size(self._h)

    def close(self):
        if self._h:
            self._lib.ptq_close(self._h)

    def __del__(self):
        try:
            if self._h:
                self._lib.ptq_destroy(self._h)
                self._h = None
        except Exception:
            pass


class _PyQueue:
    def __init__(self, capacity):
        import queue

        self._q = queue.Queue(maxsize=capacity)
        self._closed = threading.Event()

    def push(self, data, timeout_ms=-1):
        import queue

        if self._closed.is_set():
            raise RuntimeError("queue closed")
        try:
            self._q.put(data, timeout=None if timeout_ms < 0 else timeout_ms / 1000)
            return True
        except queue.Full:
            return False

    def pop(self, timeout_ms=-1):
        import queue

        remaining = None if timeout_ms < 0 else timeout_ms / 1000.0
        while True:
            wait = 0.05 if remaining is None else max(0.0, min(0.05, remaining))
            try:
                return self._q.get(timeout=wait)
            except queue.Empty:
                if self._closed.is_set() and self._q.empty():
                    raise EOFError("queue closed and drained")
                if remaining is not None:
                    remaining -= 0.05
                    if remaining <= 0:
                        return None

    def size(self):
        return self._q.qsize()

    def close(self):
        self._closed.set()


def BlockingQueue(capacity: int = 8):
    return _NativeQueue(capacity) if native_available() else _PyQueue(capacity)


# ---------------------------------------------------------------------------
# PinnedPool — parity: AutoGrowthBestFitAllocator + pinned host memory
# (memory/allocation/). Hands out numpy arrays backed by pool buffers.
# ---------------------------------------------------------------------------
class PinnedPool:
    def __init__(self, chunk_size: int = 64 << 20, use_mlock: bool = False):
        self._native = native_available()
        if self._native:
            self._lib = lib()
            self._h = self._lib.ppool_create(chunk_size, 1 if use_mlock else 0)
        self._live = {}

    def alloc_array(self, shape, dtype):
        """A numpy array on pool memory; free with :meth:`free_array`."""
        import numpy as np

        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize if len(shape) else dtype.itemsize
        if not self._native:
            return np.empty(shape, dtype)
        ptr = self._lib.ppool_alloc(self._h, max(nbytes, 1))
        if not ptr:
            return np.empty(shape, dtype)
        buf = (ctypes.c_uint8 * max(nbytes, 1)).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype, count=int(np.prod(shape)) if len(shape) else 1).reshape(shape)
        self._live[arr.__array_interface__["data"][0]] = ptr
        return arr

    def free_array(self, arr) -> bool:
        if not self._native:
            return True
        addr = arr.__array_interface__["data"][0]
        ptr = self._live.pop(addr, None)
        if ptr is None:
            return False
        return self._lib.ppool_free(self._h, ptr) == 0

    def stats(self):
        if not self._native:
            return {"total_alloc": 0, "in_use": 0, "chunks": 0, "free_blocks": 0}
        out = (ctypes.c_uint64 * 4)()
        self._lib.ppool_stats(self._h, out)
        return {"total_alloc": out[0], "in_use": out[1], "chunks": out[2], "free_blocks": out[3]}

    def __del__(self):
        try:
            if self._native and self._h:
                self._lib.ppool_destroy(self._h)
                self._h = None
        except Exception:
            pass


# ---------------------------------------------------------------------------
# ShmRing — parity: mmap_allocator.cc + imperative/data_loader.cc shared-
# memory DataLoader transport. Cross-process; attach by name.
# ---------------------------------------------------------------------------
class ShmRing:
    def __init__(self, name: str, slot_size: int = 8 << 20, nslots: int = 8,
                 create: bool = True):
        if not native_available():
            raise RuntimeError(f"native core unavailable: {build_error()}")
        self._lib = lib()
        self.name = name
        if create:
            self._h = self._lib.shmring_create(name.encode(), slot_size, nslots)
        else:
            self._h = self._lib.shmring_attach(name.encode())
        if not self._h:
            raise OSError(f"shmring_{'create' if create else 'attach'}({name}) failed")
        self._rbuf = (ctypes.c_uint8 * self._lib.shmring_slot_size(self._h))()

    def write(self, data: bytes, timeout_ms: int = -1) -> bool:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        rc = self._lib.shmring_write(self._h, buf, len(data), timeout_ms)
        if rc == -2:
            raise EOFError("ring closed")
        if rc == -3:
            raise ValueError(f"payload {len(data)} exceeds slot size {self._lib.shmring_slot_size(self._h)}")
        return rc == 0

    def read(self, timeout_ms: int = -1):
        buf = self._rbuf  # reused across calls; payload copied out below
        n = ctypes.c_uint64()
        rc = self._lib.shmring_read(self._h, buf, len(buf), ctypes.byref(n), timeout_ms)
        if rc == -1:
            return None
        if rc == -2:
            raise EOFError("ring closed and drained")
        if rc == -4:
            raise ValueError("slot payload larger than slot size (corrupt ring)")
        return ctypes.string_at(buf, n.value)

    def count(self):
        return self._lib.shmring_count(self._h)

    def close(self):
        if self._h:
            self._lib.shmring_close(self._h)

    def destroy(self):
        if self._h:
            self._lib.shmring_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass
