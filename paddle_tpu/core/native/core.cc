// paddle_tpu native runtime core.
//
// TPU-native re-design of the reference's C++ host runtime pieces that are
// NOT absorbed by XLA/PJRT:
//   * BlockingQueue  — bounded byte-blob queue feeding the device prefetch
//     pipeline (parity role: paddle/fluid/operators/reader/
//     lod_tensor_blocking_queue.h + buffered_reader.cc).
//   * PinnedPool     — auto-growth best-fit host allocator handing out
//     aligned, optionally mlock'd buffers for batch collation and
//     ZeRO-offload staging (parity role: paddle/fluid/memory/allocation/
//     auto_growth_best_fit_allocator.cc + pinned_allocator.cc).
//   * ShmRing        — process-shared ring of fixed slots over shm_open +
//     process-shared pthread mutex/cond, the multiprocess DataLoader batch
//     transport (parity role: paddle/fluid/memory/allocation/
//     mmap_allocator.cc + imperative/data_loader.cc).
//   * Profiler       — per-thread span recorder with nanosecond monotonic
//     clocks (parity role: paddle/fluid/platform/profiler.cc RecordEvent);
//     dumped to a flat file the Python side turns into chrome-trace.
//
// Exposed as a plain C ABI for ctypes (pybind11 is not in the image).
// Build: g++ -O2 -fPIC -shared -pthread core.cc -o libpaddle_tpu_core.so

#include <pthread.h>
#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <new>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// time
// ---------------------------------------------------------------------------
static inline uint64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

uint64_t pt_now_ns() { return now_ns(); }

// ---------------------------------------------------------------------------
// BlockingQueue: bounded queue of malloc'd byte blobs.
// ---------------------------------------------------------------------------
struct Blob {
  uint8_t* data;
  uint64_t size;
};

struct BlockingQueue {
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  std::deque<Blob> items;
  size_t capacity;
  bool closed = false;
};

void* ptq_create(uint64_t capacity) {
  auto* q = new BlockingQueue();
  q->capacity = capacity ? capacity : 1;
  return q;
}

// returns 0 ok, -1 timeout, -2 closed
int ptq_push(void* h, const uint8_t* data, uint64_t n, int timeout_ms) {
  auto* q = static_cast<BlockingQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [&] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_ms < 0) {
    q->not_full.wait(lk, pred);
  } else if (!q->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred)) {
    return -1;
  }
  if (q->closed) return -2;
  Blob b;
  b.data = static_cast<uint8_t*>(malloc(n));
  if (!b.data && n) return -3;
  memcpy(b.data, data, n);
  b.size = n;
  q->items.push_back(b);
  q->not_empty.notify_one();
  return 0;
}

// returns 0 ok (out malloc'd, caller frees via pt_free), -1 timeout, -2 closed+empty
int ptq_pop(void* h, uint8_t** out, uint64_t* n, int timeout_ms) {
  auto* q = static_cast<BlockingQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [&] { return q->closed || !q->items.empty(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(lk, pred);
  } else if (!q->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred)) {
    return -1;
  }
  if (q->items.empty()) return -2;  // closed and drained
  Blob b = q->items.front();
  q->items.pop_front();
  *out = b.data;
  *n = b.size;
  q->not_full.notify_one();
  return 0;
}

uint64_t ptq_size(void* h) {
  auto* q = static_cast<BlockingQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

void ptq_close(void* h) {
  auto* q = static_cast<BlockingQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

void ptq_destroy(void* h) {
  auto* q = static_cast<BlockingQueue*>(h);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    for (auto& b : q->items) free(b.data);
    q->items.clear();
  }
  delete q;
}

void pt_free(void* p) { free(p); }

// ---------------------------------------------------------------------------
// PinnedPool: best-fit, auto-growth chunked host allocator.
// Free blocks keyed by size in a multimap; adjacent-free coalescing.
// ---------------------------------------------------------------------------
struct PoolBlock {
  uint64_t offset;
  uint64_t size;
  int chunk;
  bool free_;
};

struct PoolChunk {
  uint8_t* base;
  uint64_t size;
};

struct PinnedPool {
  std::mutex mu;
  std::vector<PoolChunk> chunks;
  // offset-ordered block list per chunk for coalescing
  std::map<std::pair<int, uint64_t>, PoolBlock> blocks;  // (chunk, offset) -> block
  std::multimap<uint64_t, std::pair<int, uint64_t>> free_by_size;
  std::unordered_map<void*, std::pair<int, uint64_t>> live;  // ptr -> key
  uint64_t chunk_size;
  uint64_t total_alloc = 0, total_in_use = 0;
  bool use_mlock;
  uint64_t alignment = 64;
};

static void pool_insert_free(PinnedPool* p, PoolBlock b) {
  b.free_ = true;
  auto key = std::make_pair(b.chunk, b.offset);
  p->blocks[key] = b;
  p->free_by_size.emplace(b.size, key);
}

static void pool_erase_free_index(PinnedPool* p, const PoolBlock& b) {
  auto range = p->free_by_size.equal_range(b.size);
  for (auto it = range.first; it != range.second; ++it) {
    if (it->second == std::make_pair(b.chunk, b.offset)) {
      p->free_by_size.erase(it);
      return;
    }
  }
}

void* ppool_create(uint64_t chunk_size, int use_mlock) {
  auto* p = new PinnedPool();
  p->chunk_size = chunk_size ? chunk_size : (64ull << 20);
  p->use_mlock = use_mlock != 0;
  return p;
}

static int pool_grow(PinnedPool* p, uint64_t need) {
  uint64_t sz = p->chunk_size;
  while (sz < need) sz <<= 1;
  void* mem = nullptr;
  if (posix_memalign(&mem, 4096, sz) != 0) return -1;
  if (p->use_mlock) mlock(mem, sz);  // best-effort pin
  PoolChunk c{static_cast<uint8_t*>(mem), sz};
  p->chunks.push_back(c);
  p->total_alloc += sz;
  PoolBlock b{0, sz, static_cast<int>(p->chunks.size() - 1), true};
  pool_insert_free(p, b);
  return 0;
}

void* ppool_alloc(void* h, uint64_t size) {
  auto* p = static_cast<PinnedPool*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  uint64_t need = (size + p->alignment - 1) & ~(p->alignment - 1);
  if (!need) need = p->alignment;
  auto it = p->free_by_size.lower_bound(need);  // best fit
  if (it == p->free_by_size.end()) {
    if (pool_grow(p, need) != 0) return nullptr;
    it = p->free_by_size.lower_bound(need);
    if (it == p->free_by_size.end()) return nullptr;
  }
  auto key = it->second;
  PoolBlock b = p->blocks[key];
  p->free_by_size.erase(it);
  p->blocks.erase(key);
  if (b.size > need + p->alignment) {  // split tail back to free list
    PoolBlock rest{b.offset + need, b.size - need, b.chunk, true};
    pool_insert_free(p, rest);
    b.size = need;
  }
  b.free_ = false;
  p->blocks[std::make_pair(b.chunk, b.offset)] = b;
  void* ptr = p->chunks[b.chunk].base + b.offset;
  p->live[ptr] = std::make_pair(b.chunk, b.offset);
  p->total_in_use += b.size;
  return ptr;
}

int ppool_free(void* h, void* ptr) {
  auto* p = static_cast<PinnedPool*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  auto lit = p->live.find(ptr);
  if (lit == p->live.end()) return -1;
  auto key = lit->second;
  p->live.erase(lit);
  PoolBlock b = p->blocks[key];
  p->blocks.erase(key);
  p->total_in_use -= b.size;
  // coalesce with next
  auto nkey = std::make_pair(b.chunk, b.offset + b.size);
  auto nit = p->blocks.find(nkey);
  if (nit != p->blocks.end() && nit->second.free_) {
    pool_erase_free_index(p, nit->second);
    b.size += nit->second.size;
    p->blocks.erase(nit);
  }
  // coalesce with prev
  auto pit = p->blocks.lower_bound(std::make_pair(b.chunk, b.offset));
  if (pit != p->blocks.begin()) {
    --pit;
    if (pit->first.first == b.chunk && pit->second.free_ &&
        pit->second.offset + pit->second.size == b.offset) {
      pool_erase_free_index(p, pit->second);
      b.offset = pit->second.offset;
      b.size += pit->second.size;
      p->blocks.erase(pit);
    }
  }
  pool_insert_free(p, b);
  return 0;
}

void ppool_stats(void* h, uint64_t* out4) {
  auto* p = static_cast<PinnedPool*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  out4[0] = p->total_alloc;
  out4[1] = p->total_in_use;
  out4[2] = p->chunks.size();
  out4[3] = p->free_by_size.size();
}

void ppool_destroy(void* h) {
  auto* p = static_cast<PinnedPool*>(h);
  for (auto& c : p->chunks) {
    if (p->use_mlock) munlock(c.base, c.size);
    free(c.base);
  }
  delete p;
}

// ---------------------------------------------------------------------------
// ShmRing: fixed-slot ring in POSIX shared memory, process-shared
// pthread mutex + conds in the header. Writers (dataloader workers) block
// when full; the reader (trainer proc) blocks when empty.
// Slot payload: uint64 len + bytes.
// ---------------------------------------------------------------------------
struct ShmHeader {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t nslots;
  uint64_t slot_size;
  uint64_t head;   // next slot to read
  uint64_t tail;   // next slot to write
  uint64_t count;  // filled slots
  int32_t closed;
  int32_t _pad;
};

struct ShmRing {
  ShmHeader* hdr;
  uint8_t* slots;
  uint64_t map_size;
  std::string name;
  bool owner;
};

static uint64_t shm_total_size(uint64_t slot_size, uint64_t nslots) {
  return sizeof(ShmHeader) + nslots * (sizeof(uint64_t) + slot_size);
}

void* shmring_create(const char* name, uint64_t slot_size, uint64_t nslots) {
  shm_unlink(name);  // stale from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = shm_total_size(slot_size, nslots);
  if (ftruncate(fd, total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = static_cast<ShmHeader*>(mem);
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
#ifdef PTHREAD_MUTEX_ROBUST
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
#endif
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_cond_init(&hdr->not_full, &ca);
  hdr->nslots = nslots;
  hdr->slot_size = slot_size;
  hdr->head = hdr->tail = hdr->count = 0;
  hdr->closed = 0;
  auto* r = new ShmRing();
  r->hdr = hdr;
  r->slots = static_cast<uint8_t*>(mem) + sizeof(ShmHeader);
  r->map_size = total;
  r->name = name;
  r->owner = true;
  return r;
}

void* shmring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* r = new ShmRing();
  r->hdr = static_cast<ShmHeader*>(mem);
  r->slots = static_cast<uint8_t*>(mem) + sizeof(ShmHeader);
  r->map_size = st.st_size;
  r->name = name;
  r->owner = false;
  return r;
}

static uint8_t* slot_ptr(ShmRing* r, uint64_t idx) {
  return r->slots + idx * (sizeof(uint64_t) + r->hdr->slot_size);
}

// 0 ok, -1 timeout, -2 closed, -3 too large
int shmring_write(void* h, const uint8_t* data, uint64_t n, int timeout_ms) {
  auto* r = static_cast<ShmRing*>(h);
  ShmHeader* hd = r->hdr;
  if (n > hd->slot_size) return -3;
  pthread_mutex_lock(&hd->mu);
  while (hd->count == hd->nslots && !hd->closed) {
    if (timeout_ms < 0) {
      pthread_cond_wait(&hd->not_full, &hd->mu);
    } else {
      struct timespec ts;
      clock_gettime(CLOCK_REALTIME, &ts);
      ts.tv_sec += timeout_ms / 1000;
      ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
      if (ts.tv_nsec >= 1000000000L) { ts.tv_sec++; ts.tv_nsec -= 1000000000L; }
      if (pthread_cond_timedwait(&hd->not_full, &hd->mu, &ts) != 0) {
        pthread_mutex_unlock(&hd->mu);
        return -1;
      }
    }
  }
  if (hd->closed) {
    pthread_mutex_unlock(&hd->mu);
    return -2;
  }
  uint8_t* slot = slot_ptr(r, hd->tail);
  memcpy(slot, &n, sizeof(uint64_t));
  memcpy(slot + sizeof(uint64_t), data, n);
  hd->tail = (hd->tail + 1) % hd->nslots;
  hd->count++;
  pthread_cond_signal(&hd->not_empty);
  pthread_mutex_unlock(&hd->mu);
  return 0;
}

// 0 ok, -1 timeout, -2 closed+drained, -4 buffer too small (len in *n)
int shmring_read(void* h, uint8_t* buf, uint64_t cap, uint64_t* n, int timeout_ms) {
  auto* r = static_cast<ShmRing*>(h);
  ShmHeader* hd = r->hdr;
  pthread_mutex_lock(&hd->mu);
  while (hd->count == 0 && !hd->closed) {
    if (timeout_ms < 0) {
      pthread_cond_wait(&hd->not_empty, &hd->mu);
    } else {
      struct timespec ts;
      clock_gettime(CLOCK_REALTIME, &ts);
      ts.tv_sec += timeout_ms / 1000;
      ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
      if (ts.tv_nsec >= 1000000000L) { ts.tv_sec++; ts.tv_nsec -= 1000000000L; }
      if (pthread_cond_timedwait(&hd->not_empty, &hd->mu, &ts) != 0) {
        pthread_mutex_unlock(&hd->mu);
        return -1;
      }
    }
  }
  if (hd->count == 0) {
    pthread_mutex_unlock(&hd->mu);
    return -2;
  }
  uint8_t* slot = slot_ptr(r, hd->head);
  uint64_t len;
  memcpy(&len, slot, sizeof(uint64_t));
  *n = len;
  if (len > cap) {
    pthread_mutex_unlock(&hd->mu);
    return -4;
  }
  memcpy(buf, slot + sizeof(uint64_t), len);
  hd->head = (hd->head + 1) % hd->nslots;
  hd->count--;
  pthread_cond_signal(&hd->not_full);
  pthread_mutex_unlock(&hd->mu);
  return 0;
}

uint64_t shmring_count(void* h) {
  auto* r = static_cast<ShmRing*>(h);
  pthread_mutex_lock(&r->hdr->mu);
  uint64_t c = r->hdr->count;
  pthread_mutex_unlock(&r->hdr->mu);
  return c;
}

uint64_t shmring_slot_size(void* h) {
  return static_cast<ShmRing*>(h)->hdr->slot_size;
}

void shmring_close(void* h) {
  auto* r = static_cast<ShmRing*>(h);
  pthread_mutex_lock(&r->hdr->mu);
  r->hdr->closed = 1;
  pthread_cond_broadcast(&r->hdr->not_empty);
  pthread_cond_broadcast(&r->hdr->not_full);
  pthread_mutex_unlock(&r->hdr->mu);
}

void shmring_destroy(void* h) {
  auto* r = static_cast<ShmRing*>(h);
  bool owner = r->owner;
  std::string name = r->name;
  munmap(r->hdr, r->map_size);
  if (owner) shm_unlink(name.c_str());
  delete r;
}

// ---------------------------------------------------------------------------
// Profiler: per-thread span buffers. Python assigns names once via interning.
// ---------------------------------------------------------------------------
struct ProfEvent {
  uint32_t name_id;
  uint32_t depth;
  uint64_t t0, t1;
};

struct ProfThreadBuf {
  std::vector<ProfEvent> events;
  std::vector<std::pair<uint32_t, uint64_t>> stack;  // (name_id, t0)
  uint64_t tid;
};

static std::mutex g_prof_mu;
static std::vector<ProfThreadBuf*> g_prof_bufs;
static std::atomic<bool> g_prof_enabled{false};
static std::atomic<uint64_t> g_tid_counter{0};

static thread_local ProfThreadBuf* tl_buf = nullptr;

static ProfThreadBuf* prof_buf() {
  if (!tl_buf) {
    tl_buf = new ProfThreadBuf();
    tl_buf->tid = g_tid_counter.fetch_add(1);
    std::lock_guard<std::mutex> lk(g_prof_mu);
    g_prof_bufs.push_back(tl_buf);
  }
  return tl_buf;
}

void prof_enable(int on) { g_prof_enabled.store(on != 0); }
int prof_is_enabled() { return g_prof_enabled.load() ? 1 : 0; }

void prof_push(uint32_t name_id) {
  if (!g_prof_enabled.load(std::memory_order_relaxed)) return;
  auto* b = prof_buf();
  b->stack.emplace_back(name_id, now_ns());
}

void prof_pop() {
  if (!tl_buf || tl_buf->stack.empty()) return;
  auto top = tl_buf->stack.back();
  tl_buf->stack.pop_back();
  ProfEvent e;
  e.name_id = top.first;
  e.depth = static_cast<uint32_t>(tl_buf->stack.size());
  e.t0 = top.second;
  e.t1 = now_ns();
  tl_buf->events.push_back(e);
}

// Copies out as flat u64 quads: (tid, name_id | depth<<32, t0, t1). Returns
// number of events copied; call with nullptr to query count.
uint64_t prof_collect(uint64_t* out, uint64_t cap) {
  std::lock_guard<std::mutex> lk(g_prof_mu);
  uint64_t total = 0;
  for (auto* b : g_prof_bufs) total += b->events.size();
  if (!out) return total;
  uint64_t written = 0;
  for (auto* b : g_prof_bufs) {
    for (auto& e : b->events) {
      if (written >= cap) return written;
      out[written * 4 + 0] = b->tid;
      out[written * 4 + 1] = (static_cast<uint64_t>(e.depth) << 32) | e.name_id;
      out[written * 4 + 2] = e.t0;
      out[written * 4 + 3] = e.t1;
      written++;
    }
  }
  return written;
}

void prof_clear() {
  std::lock_guard<std::mutex> lk(g_prof_mu);
  for (auto* b : g_prof_bufs) b->events.clear();
}

}  // extern "C"
