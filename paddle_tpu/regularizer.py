"""paddle_tpu.regularizer — weight-decay regularizers.

Parity: python/paddle/regularizer.py in the reference (L1Decay, L2Decay),
consumed by optimizers as ``weight_decay=`` (the optimizer base already reads
``_regularization_coeff``, optimizer/optimizer.py).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class L2Decay:
    """Adds coeff * param to the gradient (ridge/weight decay)."""

    def __init__(self, coeff=0.0):
        self._regularization_coeff = float(coeff)
        self._coeff = float(coeff)

    def __call__(self, param):
        return self._regularization_coeff * param

    def __repr__(self):
        return f"L2Decay(coeff={self._regularization_coeff})"


class L1Decay:
    """Adds coeff * sign(param) to the gradient (lasso)."""

    def __init__(self, coeff=0.0):
        self._regularization_coeff = float(coeff)
        self._coeff = float(coeff)

    def __call__(self, param):
        return self._regularization_coeff * jnp.sign(param)

    def __repr__(self):
        return f"L1Decay(coeff={self._regularization_coeff})"
