"""paddle_tpu.hub — hubconf-based model loading.

Parity: python/paddle/hub.py in the reference (list/help/load over a repo
that exposes ``hubconf.py`` entrypoints). Network sources (github) are out of
scope in this zero-egress build: only ``source='local'`` is supported; remote
sources raise with a clear message.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} found in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source != "local":
        raise ValueError(
            f"source={source!r} is not available in this build; only 'local' "
            "repo directories are supported (no network egress)")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    """Docstring of one entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"model {model} not found in {repo_dir}/{_HUBCONF}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, *args, source="local", force_reload=False, **kwargs):
    """Instantiate one entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"model {model} not found in {repo_dir}/{_HUBCONF}")
    return getattr(mod, model)(*args, **kwargs)
