"""Eager AMP autocast.

Parity: paddle.amp.auto_cast (/root/reference/python/paddle/amp/auto_cast.py)
and the dygraph cast insertion in
/root/reference/paddle/fluid/imperative/amp_auto_cast.cc — ``AmpLevel`` O1
(white/black-list casting per op) and O2 (pure reduced precision except the
black list), plus ``decorate`` for O2 model/optimizer preparation.

TPU-native: the "cast op insertion" happens inside ops._primitive — each op
asks :func:`amp_wrap_fn` for a casting wrapper, so casts are part of the
traced computation and their VJP restores parameter-dtype gradients.
bfloat16 is the default reduced dtype on TPU (no loss scaling needed);
float16 is kept for parity with GradScaler.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp

from .amp_lists import build_lists

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_state", "amp_wrap_fn"]


class _AmpState:
    __slots__ = ("enable", "dtype", "level", "white", "black", "version")

    def __init__(self):
        self.enable = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.white, self.black = build_lists()
        self.version = 0  # bumped on every config change; keys the fn cache


_state = _AmpState()


def amp_state() -> _AmpState:
    return _state


def _cast_tree(tree, pred, target):
    def cast(x):
        if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.floating) and pred(x.dtype):
            return x.astype(target)
        return x

    return jax.tree_util.tree_map(cast, tree)


_wrap_cache: dict = {}  # id(fn) -> wrapped fn, valid for _wrap_cache_version
_wrap_cache_version: int = -1


def amp_wrap_fn(fn, op_name: str):
    """Return fn wrapped with the casts AMP mandates for this op (or fn).

    Wrapped fns are cached per fn for the current amp-config version; a
    version bump (auto_cast enter/exit) resets the cache wholesale so stale
    entries die immediately and hot entries rebuild once.
    """
    global _wrap_cache_version
    if not _state.enable:
        return fn
    if _wrap_cache_version != _state.version:
        _wrap_cache.clear()
        _wrap_cache_version = _state.version
    key = id(fn)
    cached = _wrap_cache.get(key)
    if cached is not None:
        return cached
    op_name = op_name.lstrip("_")  # internal primitives are _-prefixed
    amp_dtype = _state.dtype
    if op_name in _state.black:
        def wrapped(*a, **k):
            a, k = _cast_tree((a, k), lambda dt: dt in (jnp.float16, jnp.bfloat16), jnp.float32)
            return fn(*a, **k)
    elif _state.level == "O2" or op_name in _state.white:
        def wrapped(*a, **k):
            a, k = _cast_tree((a, k), lambda dt: dt == jnp.float32, amp_dtype)
            return fn(*a, **k)
    else:
        wrapped = fn
    if len(_wrap_cache) > 4096:
        # bound growth from per-call-defined closures (fresh id(fn) each call)
        _wrap_cache.clear()
    _wrap_cache[key] = wrapped
    return wrapped


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None, custom_black_list=None,
              level: str = "O1", dtype: str = "bfloat16"):
    """paddle.amp.auto_cast parity context manager."""
    assert level in ("O0", "O1", "O2")
    prev = (_state.enable, _state.dtype, _state.level, _state.white, _state.black)
    _state.enable = enable and level != "O0"
    _state.dtype = jnp.float16 if str(dtype) in ("float16", "fp16") else jnp.bfloat16
    _state.level = level
    _state.white, _state.black = build_lists(custom_white_list, custom_black_list)
    _state.version += 1
    try:
        yield
    finally:
        (_state.enable, _state.dtype, _state.level, _state.white, _state.black) = prev
        _state.version += 1


amp_guard = auto_cast  # fluid.dygraph.amp.amp_guard alias


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight: Optional[bool] = None, save_dtype: Optional[str] = None):
    """O2 preparation: cast model params to the reduced dtype.

    Master fp32 copies live in the optimizer slots (the jitted trainer path
    keeps fp32 params and casts per-step instead — both parities exist).
    """
    target = jnp.float16 if str(dtype) in ("float16", "fp16") else jnp.bfloat16
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._set_data(p._data.astype(target))
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers
