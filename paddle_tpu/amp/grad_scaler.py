"""Dynamic loss scaling.

Parity: paddle.amp.GradScaler (/root/reference/python/paddle/amp/
grad_scaler.py:26) whose device side is the check_finite_and_unscale and
update_loss_scaling CUDA ops (/root/reference/paddle/fluid/operators/amp/).
The scale-update state machine is identical: grow by ``incr_ratio`` after
``incr_every_n_steps`` consecutive finite steps, shrink by ``decr_ratio``
after ``decr_every_n_nan_or_inf`` non-finite steps (skipping the update).

On TPU bf16 training needs no scaler (same exponent range as fp32); this
exists for fp16 parity and for the jitted trainer's in-graph variant
(ParallelTrainer use_loss_scaling).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000, decr_every_n_nan_or_inf: int = 1,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    # ------------------------------------------------------------------
    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def get_loss_scaling(self):
        return self._scale

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def _iter_grads(self, optimizer):
        for p in optimizer._param_groups:
            if p.grad is not None and not p.stop_gradient:
                yield p

    def unscale_(self, optimizer):
        """check_finite_and_unscale parity: divide grads by the scale and
        flag non-finite values."""
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        finite = jnp.asarray(True)  # accumulate on-device; one host sync below
        for p in self._iter_grads(optimizer):
            g = p.grad._data if isinstance(p.grad, Tensor) else p.grad
            g = (g.astype(jnp.float32) * inv).astype(g.dtype)
            finite = finite & jnp.isfinite(g).all()
            p.grad = Tensor(g)
        self._found_inf = not bool(finite)
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        """update_loss_scaling parity: advance the dynamic-scale machine."""
        if not (self._enable and self._use_dynamic):
            self._unscaled = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):  # noqa: ARG002 - loss already backpropped
        self.step(optimizer)
        self.update()

    def mark_anomaly(self):
        """Resilience hook (eager skip-and-rescale): treat the CURRENT step
        as bad regardless of grad finiteness — ``step`` will skip the
        optimizer and ``update`` will shrink the scale. The anomaly
        sentinel's jitted variant folds the same decision into the in-graph
        scale machine (ParallelTrainer); this is the eager-loop sibling.
        Call after backward, before ``step``/``update``."""
        if not self._enable:
            return
        self._found_inf = True
        self._unscaled = True  # freeze unscale_ so the verdict sticks

    # ------------------------------------------------------------------
    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._use_dynamic,
        }

    def load_state_dict(self, state):
        self._scale = float(state["scale"])
        self._incr_ratio = state["incr_ratio"]
        self._decr_ratio = state["decr_ratio"]
        self._incr_every_n_steps = state["incr_every_n_steps"]
        self._decr_every_n_nan_or_inf = state["decr_every_n_nan_or_inf"]
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)
        self._use_dynamic = state.get("use_dynamic_loss_scaling", True)


AmpScaler = GradScaler  # fluid.dygraph.AmpScaler alias
