"""paddle.amp parity package (O1/O2 autocast + dynamic loss scaling)."""
from .amp_lists import BLACK_LIST, WHITE_LIST, build_lists  # noqa: F401
from .auto_cast import amp_guard, amp_state, amp_wrap_fn, auto_cast, decorate  # noqa: F401
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401

__all__ = [
    "auto_cast", "amp_guard", "decorate", "GradScaler", "AmpScaler",
    "WHITE_LIST", "BLACK_LIST",
]
