"""AMP O1 op lists.

Parity: /root/reference/python/paddle/fluid/contrib/mixed_precision/
fp16_lists.py (white/black/gray lists consumed by rewrite_program) and the
dygraph AmpOperators sets (/root/reference/paddle/fluid/imperative/
amp_auto_cast.cc). Names here are this framework's op names (the function
names wrapped by ops._primitive.primitive).
"""
from __future__ import annotations

# Names are matched after stripping the internal "_" prefix convention.
# ops that are numerically safe and fast in reduced precision (MXU-bound)
WHITE_LIST = {
    "matmul", "mm", "bmm", "dot", "mv", "linear",
    "conv1d", "conv2d", "conv3d", "conv_nd",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
    "einsum", "addmm", "flash", "attn", "flash_attention",
}

# numerically sensitive ops forced to float32
BLACK_LIST = {
    "exp", "expm1", "log", "log2", "log10", "log1p",
    "pow", "square", "sqrt", "rsqrt", "cumprod",
    "mean", "sum", "prod", "logsumexp",
    "softmax", "log_softmax",
    "cross_entropy", "nll_loss", "kl_div",
    "sigmoid_cross_entropy_with_logits", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "softmax_with_cross_entropy",
    "pce",  # ParallelCrossEntropy kernel
    "layer_norm", "ln", "batch_norm", "bn_train", "bn_infer",
    "instance_norm", "group_norm", "local_response_norm",
    "cos_sim", "norm", "p_norm", "dist",
    "erf", "erfinv", "lgamma", "digamma",
}

# everything else is "gray": runs in whatever dtype its inputs carry


def build_lists(custom_white_list=None, custom_black_list=None):
    white = set(WHITE_LIST)
    black = set(BLACK_LIST)
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    return white, black
