"""paddle.summary parity: /root/reference/python/paddle/hapi/model_summary.py.
Hook-based layer table + parameter totals."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..nn.layer import Layer
from ..tensor import Tensor

__all__ = ["summary"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):  # noqa: A002
    """Print a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(out.shape) if hasattr(out, "shape") else []
            n_params = sum(int(np.prod(p._data.shape)) for p in lyr._parameters.values()
                           if p is not None)
            rows.append((name, type(lyr).__name__, shape, n_params))
        return hook

    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaves only, like the reference table
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    if input is not None:
        x = input
    else:
        assert input_size is not None, "summary needs input_size or input"
        sizes = input_size if isinstance(input_size, list) else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
        xs = []
        for s, dt in zip(sizes, dts):
            s = tuple(1 if d is None or d == -1 else d for d in s)
            xs.append(Tensor(np.zeros(s, np.dtype(dt or "float32"))))
        x = xs if len(xs) > 1 else xs[0]

    was_training = net.training
    net.eval()
    try:
        net(*x) if isinstance(x, list) else net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p._data.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p._data.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    line = "-" * 80
    print(line)
    print(f"{'Layer (type)':<40}{'Output Shape':<24}{'Param #':>14}")
    print(line)
    for name, cls, shape, n in rows:
        print(f"{name + ' (' + cls + ')':<40}{str(shape):<24}{n:>14,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
