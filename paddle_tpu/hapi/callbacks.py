"""hapi callbacks. Parity: /root/reference/python/paddle/hapi/callbacks.py
(Callback:117, ProgBarLogger:287, ModelCheckpoint:505, LRScheduler:562,
EarlyStopping:619, VisualDL:723)."""
from __future__ import annotations

import json
import numbers
import os
import time
from typing import List, Optional

import numpy as np

__all__ = [
    "Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
    "LRScheduler", "EarlyStopping", "VisualDL", "config_callbacks",
]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # lifecycle hooks (all optional)
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._call(name, *a)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch stdout logging (condensed progbar: step lines at
    ``log_freq``, epoch summaries always)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else 0.0
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
        return " - ".join(parts)

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose and self.params.get("verbose", 1):
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            print(f"step {step + 1}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        self.best_value = self.baseline if self.baseline is not None else (
            -np.inf if self.mode == "max" else np.inf)

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best_value + self.min_delta
        return cur < self.best_value - self.min_delta

    def on_eval_end(self, logs=None):
        value = (logs or {}).get(self.monitor)
        if value is None:
            return
        if isinstance(value, (list, tuple)):
            value = value[0]
        if self._better(value):
            self.best_value = value
            self.wait_epoch = 0
            if self.save_best_model and getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir, "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"EarlyStopping: no {self.monitor} improvement "
                      f"for {self.patience + 1} evals; stopping")


class VisualDL(Callback):
    """Scalar logger with the VisualDL callback's surface; writes JSONL
    (the VisualDL wire format needs the visualdl package — not in image)."""

    def __init__(self, log_dir: str = "./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            for k, v in (logs or {}).items():
                if isinstance(v, (list, tuple)):
                    v = v[0] if v else 0.0
                if isinstance(v, numbers.Number):
                    f.write(json.dumps({"tag": f"{tag}/{k}", "step": self._step,
                                        "value": float(v)}) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=1, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst
