"""hapi Model — fit/evaluate/predict high-level API.

Parity: /root/reference/python/paddle/hapi/model.py (Model:906, fit:1556,
evaluate:1786, predict:1889, save/load:1265-1419, train_batch:1060).

TPU-native notes: the train loop is the framework's eager path (each op is a
jitted XLA call); swap in ``paddle_tpu.distributed.ParallelTrainer`` or
``jit.to_static`` for the fully-compiled step when throughput matters —
``Model`` stays the orchestration/callback layer, same as the reference
keeps hapi above the executor.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..framework import io as fio
from ..io.dataloader import DataLoader
from ..io.dataset import Dataset
from ..metric import Metric
from ..tensor import Tensor
from .callbacks import config_callbacks

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._save_dir = None

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None,
                strategy=None):
        """``strategy``: a fleet ``DistributedStrategy`` (or True to
        auto-detect the installed mesh) — swaps the inner train loop to the
        jitted multi-device ``ParallelTrainer`` step (parity: the
        reference's dist-hapi path, hapi/model.py:906 _strategy plumbing)."""
        self._optimizer = optimizer
        self._loss = loss
        self._strategy = strategy
        self._dist_trainer = None
        self._dist_failed = False
        ms = _to_list(metrics)
        for m in ms:
            assert isinstance(m, Metric), f"metrics must be Metric, got {type(m)}"
        self._metrics = ms
        # amp_configs parity (reference model.py prepare amp_configs): "O1"/
        # "O2" string or {"level": ..., custom lists...}
        self._amp_level = "O0"
        self._amp_kwargs = {}
        if amp_configs:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            elif isinstance(amp_configs, dict):
                cfg = dict(amp_configs)
                self._amp_level = cfg.pop("level", "O1")
                self._amp_kwargs = {
                    k: v for k, v in cfg.items()
                    if k in ("custom_white_list", "custom_black_list", "dtype")
                }

    # ------------------------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        lbls = _to_list(labels)
        if isinstance(self._loss, (list, tuple)):
            # per-output losses (reference: loss list zipped with outputs),
            # summed into the optimized scalar
            if not (len(self._loss) == len(outs) == len(lbls)):
                raise ValueError(
                    f"loss list ({len(self._loss)}) must match outputs "
                    f"({len(outs)}) and labels ({len(lbls)})")
            losses = [fn(o, l) for fn, o, l in zip(self._loss, outs, lbls)]
            total = losses[0]
            for l in losses[1:]:
                total = total + l
            return total
        if callable(self._loss):
            return self._loss(*(outs + lbls))
        raise ValueError("prepare(loss=...) with a callable loss first")

    def _forward(self, ins):
        if getattr(self, "_amp_level", "O0") in ("O1", "O2"):
            from ..amp.auto_cast import auto_cast

            with auto_cast(enable=True, level=self._amp_level,
                           **getattr(self, "_amp_kwargs", {})):
                return self.network(*ins)
        return self.network(*ins)

    def _maybe_dist_trainer(self):
        """Build (once) the multi-device jitted step when a strategy or a
        >1-device mesh is present and the configuration routes cleanly
        (single input/label, scalar callable loss, no per-batch metrics)."""
        if self._dist_trainer is not None:
            return self._dist_trainer
        if self._dist_failed or getattr(self, "_strategy", None) is None:
            return None
        if self._metrics or isinstance(self._loss, (list, tuple)) \
                or not callable(self._loss):
            import warnings

            warnings.warn(
                "Model.prepare(strategy=...): metrics / per-output loss "
                "lists need per-batch outputs — falling back to the eager "
                "loop", RuntimeWarning, stacklevel=3)
            self._dist_failed = True
            return None
        from ..distributed.env import get_mesh
        from ..distributed.parallel_trainer import ParallelTrainer

        if get_mesh() is None:
            import warnings

            warnings.warn(
                "Model.prepare(strategy=...) needs an installed mesh "
                "(fleet.init / init_mesh) — falling back to the eager loop",
                RuntimeWarning, stacklevel=3)
            self._dist_failed = True
            return None
        strategy = None if self._strategy is True else self._strategy
        loss_fn = self._loss
        self._dist_trainer = ParallelTrainer(
            self.network, lambda out, y: loss_fn(out, y), self._optimizer,
            strategy=strategy,
            compute_dtype="bfloat16" if self._amp_level in ("O1", "O2") else None,
        )
        return self._dist_trainer

    def _dist_sync(self):
        tr = getattr(self, "_dist_trainer", None)
        if tr is not None:
            tr.sync_to_model()

    def train_batch(self, inputs, labels=None, update=True):
        """One eager train step; returns [loss] (+ metric results)."""
        self.network.train()
        ins = [_to_tensor(x) for x in _to_list(inputs)]
        lbls = [_to_tensor(x) for x in _to_list(labels)]
        routable = update and len(ins) == 1 and len(lbls) == 1
        trainer = self._maybe_dist_trainer() if routable else None
        if trainer is not None:
            loss = trainer.step(ins[0], lbls[0])
            return [float(np.asarray(loss._data))]
        if not routable and getattr(self, "_dist_trainer", None) is not None:
            # a trainer exists from earlier single-input steps but this call
            # can't route: sync its progress back and retire it so a later
            # _dist_sync can't clobber the eager training done from here on
            import warnings

            warnings.warn(
                "Model.train_batch: multi-input/label batch cannot route "
                "through the distributed trainer — continuing on the eager "
                "loop", RuntimeWarning, stacklevel=2)
            self._dist_sync()
            self._dist_trainer = None
            self._dist_failed = True
        outputs = self._forward(ins)
        loss = self._compute_loss(outputs, lbls)
        loss.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m_in = m.compute(*(_to_list(outputs) + lbls))
            metrics.append(m.update(*_to_list(m_in)))
        return ([float(loss._data)], metrics) if metrics else [float(loss._data)]

    def eval_batch(self, inputs, labels=None):
        from ..autograd import tape

        self._dist_sync()  # trained shards -> eager weights
        self.network.eval()
        ins = [_to_tensor(x) for x in _to_list(inputs)]
        lbls = [_to_tensor(x) for x in _to_list(labels)]
        with tape.no_grad():
            outputs = self._forward(ins)
            loss = self._compute_loss(outputs, lbls) if self._loss else None
        metrics = []
        for m in self._metrics:
            m_in = m.compute(*(_to_list(outputs) + lbls))
            metrics.append(m.update(*_to_list(m_in)))
        lv = [float(loss._data)] if loss is not None else []
        return (lv, metrics) if metrics else lv

    def predict_batch(self, inputs):
        from ..autograd import tape

        self._dist_sync()
        self.network.eval()
        ins = [_to_tensor(x) for x in _to_list(inputs)]
        with tape.no_grad():
            outputs = self.network(*ins)
        return [np.asarray(o._data) for o in _to_list(outputs)]

    # ------------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers, drop_last=False,
                single_pass=False):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        if not single_pass and iter(data) is data:
            # a bare iterator/generator would be exhausted after one epoch;
            # materialize so every epoch sees the data. Single-pass consumers
            # (evaluate/predict) stream it instead — no buffering.
            return list(data)
        return data  # any (re-)iterable of batches

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            # declared specs drive the split (reference: _update_inputs by
            # the inputs/labels InputSpec counts); default: last = label
            n_in = len(_to_list(self._inputs)) or None
            n_lb = len(_to_list(self._labels)) or None
            if n_in and len(batch) >= n_in:
                return list(batch[:n_in]), list(batch[n_in:])
            if n_lb and len(batch) > n_lb:
                return list(batch[:-n_lb]), list(batch[-n_lb:])
            if len(batch) >= 2:
                return list(batch[:-1]), list(batch[-1:])
            return list(batch), []  # 1-tuple: unwrap, unlabeled
        return [batch], []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        """Parity: hapi/model.py:1556."""
        assert self._optimizer is not None and self._loss is not None, \
            "call prepare(optimizer, loss) before fit"
        self._save_dir = save_dir
        loader = self._loader(train_data, batch_size, shuffle, num_workers,
                              drop_last=drop_last)
        eval_loader = self._loader(eval_data, batch_size, False, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs, steps=steps,
                                log_freq=log_freq, verbose=verbose,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=self._metrics_names())
        self.stop_training = False
        cbks.on_train_begin()
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                ins, lbls = self._split_batch(batch)
                res = self.train_batch(ins, lbls)
                logs = self._result_logs(res)
                cbks.on_train_batch_end(step, logs)
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size, verbose=0,
                              callbacks=cbks)
        self._dist_sync()  # leave the eager weights trained
        cbks.on_train_end(logs if "logs" in dir() else None)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._loader(eval_data, batch_size, False, num_workers,
                              single_pass=True)
        if callbacks is None or isinstance(callbacks, (list, tuple)):
            cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                    metrics=self._metrics_names())
        else:  # an already-configured CallbackList (fit's eval leg)
            cbks = callbacks
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        loss_sum, loss_n = 0.0, 0
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, lbls = self._split_batch(batch)
            res = self.eval_batch(ins, lbls)
            logs = self._result_logs(res, prefix="")
            if "loss" in logs:
                bs = len(np.asarray(ins[0] if not isinstance(ins[0], Tensor)
                                    else ins[0]._data))
                loss_sum += logs["loss"] * bs
                loss_n += bs
            cbks.on_eval_batch_end(step, logs)
        # sample-weighted mean loss (reference averages eval loss) +
        # final accumulated metrics
        if loss_n:
            logs["loss"] = loss_sum / loss_n
        for m in self._metrics:
            logs[self._mname(m)] = m.accumulate()
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1):
        loader = self._loader(test_data, batch_size, False, num_workers,
                              single_pass=True)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch) if isinstance(batch, (list, tuple)) \
                else ([batch], [])
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # ------------------------------------------------------------------
    def _mname(self, m):
        n = m.name()
        return n[0] if isinstance(n, (list, tuple)) else n

    def _metrics_names(self):
        return ["loss"] + [self._mname(m) for m in self._metrics]

    def _result_logs(self, res, prefix=""):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
        else:
            losses, metrics = res, []
        if losses:
            logs[prefix + "loss"] = losses[0]
        for m, val in zip(self._metrics, metrics):
            logs[prefix + self._mname(m)] = val
        return logs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        """training=True: model.pdparams (+ .pdopt) like hapi save
        (model.py:1265). training=False: inference export through jit.save
        (StableHLO program + params — the reference's save_inference_model
        leg), using the declared ``inputs`` InputSpec."""
        self._dist_sync()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if not training:
            from ..jit.save_load import save as jit_save

            spec = _to_list(self._inputs) or None
            jit_save(self.network, path, input_spec=spec)
            return
        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(fio.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)
