"""paddle.flops parity: /root/reference/python/paddle/hapi/dynamic_flops.py.
Forward-hook FLOP counting for the common layer types."""
from __future__ import annotations

import numpy as np

from ..nn import layers as L
from ..nn.layer import Layer
from ..tensor import Tensor

__all__ = ["flops"]


def _count_conv(layer, x, y):
    out = y[0] if isinstance(y, (list, tuple)) else y
    kernel_ops = int(np.prod(layer._kernel_size)) * (layer._in_channels // layer._groups)
    bias_ops = 1 if layer.bias is not None else 0
    out_numel = int(np.prod(out.shape))
    return out_numel * (kernel_ops + bias_ops)


def _count_linear(layer, x, y):
    out = y[0] if isinstance(y, (list, tuple)) else y
    out_numel = int(np.prod(out.shape))
    return out_numel * layer.weight.shape[0] + (out_numel if layer.bias is not None else 0)


def _count_bn(layer, x, y):
    out = y[0] if isinstance(y, (list, tuple)) else y
    return 2 * int(np.prod(out.shape))


def _count_act(layer, x, y):
    out = y[0] if isinstance(y, (list, tuple)) else y
    return int(np.prod(out.shape))


def _count_pool(layer, x, y):
    out = y[0] if isinstance(y, (list, tuple)) else y
    return int(np.prod(out.shape))


def flops(net: Layer, input_size, custom_ops=None, print_detail=False) -> int:
    """Total multiply-add count for one forward pass."""
    counters = {
        L.conv._ConvNd: _count_conv,
        L.common.Linear: _count_linear,
        L.norm._BatchNormBase: _count_bn,
        L.norm.LayerNorm: _count_bn,
        L.pooling._Pool: _count_pool,
        L.pooling._AvgPool: _count_pool,
        L.activation.ReLU: _count_act,
        L.activation.ReLU6: _count_act,
        L.activation.LeakyReLU: _count_act,
        L.activation.Sigmoid: _count_act,
        L.activation.Tanh: _count_act,
        L.activation.GELU: _count_act,
    }
    if custom_ops:
        counters.update(custom_ops)
    total = {"flops": 0}
    rows = []
    hooks = []

    def make_hook(name, fn, lyr):
        def hook(layer, inputs, outputs):
            n = int(fn(layer, inputs, outputs))
            total["flops"] += n
            rows.append((name, type(layer).__name__, n))
        return hook

    for name, sub in net.named_sublayers():
        for cls, fn in counters.items():
            if isinstance(sub, cls):
                hooks.append(sub.register_forward_post_hook(make_hook(name, fn, sub)))
                break

    size = tuple(1 if d in (None, -1) else d for d in input_size)
    was_training = net.training
    net.eval()
    try:
        net(Tensor(np.zeros(size, np.float32)))
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    if print_detail:
        for name, cls, n in rows:
            print(f"{name} ({cls}): {n:,}")
    print(f"Total Flops: {total['flops']:,}")
    return total["flops"]
