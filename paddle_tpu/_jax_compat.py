"""Version portability shims for the jax surface this repo targets.

The codebase is written against the current jax spelling (top-level
``jax.shard_map`` with ``check_vma``, ``jax.lax.axis_size``); older
installs (<= 0.4.x) spell these ``jax.experimental.shard_map`` /
``check_rep`` and have no ``axis_size``.  ``distributed/spmd.py`` owns the
shard_map wrapper; this module backfills the one missing ``lax`` function
so the many call sites keep the modern spelling.

``lax.axis_size(name)`` == ``lax.psum(1, name)`` — psum of a Python
constant is folded statically, so the result is a concrete int inside
shard_map exactly like the real axis_size.
"""
from __future__ import annotations

from jax import lax as _lax


def _axis_size_fallback(axis_name):
    return _lax.psum(1, axis_name)


def install():
    if not hasattr(_lax, "axis_size"):
        _lax.axis_size = _axis_size_fallback


install()
