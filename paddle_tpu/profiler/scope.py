"""Region annotation + host timer registry (the r6 pipeline-profiling layer).

Two composable pieces, both zero-cost when idle:

* :func:`scope` / :func:`annotate` — name a region of a program.  Inside a
  jax trace the name is attached via ``jax.named_scope`` so it survives into
  the lowered XLA/HLO metadata (and thence into perfetto/xplane device
  traces); that path exists only at trace time and compiles away entirely —
  a jitted function annotated with ``scope`` lowers to the identical
  computation.  Outside a trace, when timers are enabled, the span is
  additionally wall-clocked into the :class:`TimerRegistry` and bracketed
  with ``jax.profiler.TraceAnnotation`` so host spans line up with device
  trace rows.  When timers are disabled (the default) the host path does no
  clock reads and touches no shared state.

* :class:`TimerRegistry` — aggregate host-side wall times by name, queried
  by ``bench.py`` and the pipeline driver for the per-step breakdown
  (dispatch vs. blocked-on-device time).  Off by default; ``enable_timers``
  arms it.

Parity role: the reference's ``platform::RecordEvent`` spans already exist
in this package (``RecordEvent`` in ``__init__``); ``scope`` is the
trace-aware sibling that reaches THROUGH jit into the compiled program,
which RecordEvent (host-only, nanosecond stack) cannot.
"""
from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Dict, Optional

__all__ = [
    "scope",
    "annotate",
    "TimerRegistry",
    "timer_registry",
    "enable_timers",
    "disable_timers",
    "timers_enabled",
    "timer_report",
    "reset_timers",
]

_timers_enabled = False

_trace_state_clean = None


def _resolve_trace_probe():
    """``trace_state_clean`` moved between jax versions (public jax.core on
    0.4.x, internal-but-stable jax._src.core on newer); resolve whichever
    this install has ONCE and cache it."""
    global _trace_state_clean
    for modname in ("jax.core", "jax._src.core"):
        try:
            import importlib

            fn = getattr(importlib.import_module(modname),
                         "trace_state_clean", None)
            if fn is not None:
                fn()  # probe it actually works
                _trace_state_clean = fn
                return fn
        except Exception:
            continue
    _trace_state_clean = lambda: True  # last resort: assume not tracing
    return _trace_state_clean


def _tracing() -> bool:
    """True while inside a jax trace (jit/scan/vmap tracing pass)."""
    fn = _trace_state_clean or _resolve_trace_probe()
    return not fn()


class TimerRegistry:
    """Thread-safe name → (count, total seconds) aggregation."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total: Dict[str, float] = {}
        self._count: Dict[str, int] = {}
        self._last: Dict[str, float] = {}

    def record(self, name: str, seconds: float):
        with self._lock:
            self._total[name] = self._total.get(name, 0.0) + seconds
            self._count[name] = self._count.get(name, 0) + 1
            self._last[name] = seconds

    def totals(self) -> Dict[str, dict]:
        """{name: {count, total_s, avg_s}} snapshot."""
        with self._lock:
            return {
                n: {
                    "count": self._count[n],
                    "total_s": self._total[n],
                    "avg_s": self._total[n] / self._count[n],
                }
                for n in self._total
            }

    def total(self, name: str) -> float:
        with self._lock:
            return self._total.get(name, 0.0)

    def count(self, name: str) -> int:
        with self._lock:
            return self._count.get(name, 0)

    def last(self, name: str) -> Optional[float]:
        """Most recent recorded duration for ``name`` (None if never)."""
        with self._lock:
            return self._last.get(name)

    def averages(self, prefix: str = "") -> Dict[str, float]:
        """{name: mean seconds per recorded span}, optionally filtered by
        name prefix — the measured side of the perf doctor's scope join."""
        with self._lock:
            return {n: self._total[n] / self._count[n]
                    for n in self._total if n.startswith(prefix)}

    def reset(self):
        with self._lock:
            self._total.clear()
            self._count.clear()
            self._last.clear()

    def save_state(self) -> dict:
        """Opaque snapshot of the accumulated spans (pair with
        :meth:`restore_state` so a tool that needs a clean registry —
        the perf doctor — can borrow it without destroying a live
        process's measurements)."""
        with self._lock:
            return {"total": dict(self._total),
                    "count": dict(self._count),
                    "last": dict(self._last)}

    def restore_state(self, state: dict):
        with self._lock:
            self._total = dict(state["total"])
            self._count = dict(state["count"])
            self._last = dict(state["last"])


timer_registry = TimerRegistry()


def enable_timers():
    """Arm the host-span side of :func:`scope` (off by default — the
    disabled path reads no clocks and records nothing)."""
    global _timers_enabled
    _timers_enabled = True


def disable_timers():
    global _timers_enabled
    _timers_enabled = False


def timers_enabled() -> bool:
    return _timers_enabled


def timer_report() -> Dict[str, dict]:
    return timer_registry.totals()


def reset_timers():
    timer_registry.reset()


@contextlib.contextmanager
def scope(name: str):
    """``with profiler.scope("pp.stage_compute"):`` — see module docstring.

    Inside a trace: pure HLO-metadata naming (compiles away).  Outside a
    trace with timers enabled: wall-clocked host span + TraceAnnotation.
    Outside a trace with timers disabled: HLO-metadata naming only.

    Unified-telemetry integration (r12): with the observability plane's
    tracing armed, the same host interval ALSO lands as a span in the
    trace ring (inheriting the ambient trace context), so profiler
    regions and request traces share one timeline.  The host-side clock
    reads are gated on the SAME not-``_tracing()`` probe as the timers —
    a ``scope`` hit while jax is tracing a jitted program contributes
    HLO metadata only, so enabling tracing cannot perturb the jaxpr
    (pinned by the trainer/pipeline jaxpr-identity tests).
    """
    import jax

    from ..observability import trace as _obs

    host = not _tracing()
    want_timer = _timers_enabled and host
    want_span = host and _obs.tracing_enabled()
    if want_timer or want_span:
        ts = time.time()
        t0 = time.perf_counter()
        try:
            if want_timer:
                with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
                    yield
            else:
                with jax.named_scope(name):
                    yield
        finally:
            dur = time.perf_counter() - t0
            if want_timer:
                timer_registry.record(name, dur)
            if want_span:
                _obs.record_span(name, ts=ts, dur=dur)
    else:
        with jax.named_scope(name):
            yield


def annotate(name: Optional[str] = None):
    """Decorator form: ``@profiler.annotate()`` (uses the qualified function
    name) or ``@profiler.annotate("pipeline.local_loss")``."""

    def deco(fn):
        region = name or getattr(fn, "__qualname__", fn.__name__)

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with scope(region):
                return fn(*a, **k)

        return wrapper

    return deco
