"""paddle_tpu.profiler — host span profiler + TPU (xplane) bridge.

Parity role: the reference instruments every op with ``platform::RecordEvent``
RAII spans (platform/profiler.h:130), aggregates them into a summary table on
``DisableProfiler`` (profiler_helper.h), correlates device kernels via CUPTI
(device_tracer.cc), and exports a chrome-trace timeline through
``fluid/profiler.py``. The TPU build keeps that API:

* :class:`RecordEvent` — context-manager/decorator span. Recorded natively
  (paddle_tpu.core prof_push/prof_pop, nanosecond steady clock) when the C++
  core is available, else in Python.
* :func:`start_profiler` / :func:`stop_profiler` / :func:`profiler` — the
  fluid.profiler surface; ``stop_profiler`` prints the aggregate table and
  optionally writes a chrome-trace JSON.
* Device-side tracing is XLA's own: ``tracer_option='All'`` brackets the range
  with ``jax.profiler.start_trace`` so TensorBoard xplane dumps land next to
  the host trace (replacing the CUPTI DeviceTracer).
* :func:`scope` / :func:`annotate` (scope.py) — trace-aware region naming
  that survives into the lowered HLO (and so into xplane/perfetto device
  traces), plus an off-by-default host :class:`TimerRegistry`; zero overhead
  when disabled (the annotations compile away).
* :mod:`pipeline` (pipeline.py) — the per-tick pipeline-step breakdown
  (stage compute vs. boundary ppermute vs. inject/head vs. optimizer apply
  vs. host dispatch) measured by direct probes, feeding
  ``benchmarks/pipeline_profile_r6.json``.
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
from typing import Dict, List, Optional

from .scope import (  # noqa: F401
    TimerRegistry,
    annotate,
    disable_timers,
    enable_timers,
    reset_timers,
    scope,
    timer_registry,
    timer_report,
    timers_enabled,
)

__all__ = [
    "RecordEvent",
    "record_event",
    "start_profiler",
    "stop_profiler",
    "profiler",
    "export_chrome_tracing",
    "summary",
    "reset",
    "scope",
    "annotate",
    "TimerRegistry",
    "timer_registry",
    "enable_timers",
    "disable_timers",
    "timers_enabled",
    "timer_report",
    "reset_timers",
]

_lock = threading.Lock()
_name_to_id: Dict[str, int] = {}
_id_to_name: List[str] = []
_enabled = False
_jax_trace_dir: Optional[str] = None

# python-fallback event store: list of (tid, depth, name_id, t0, t1)
_py_events: List[tuple] = []
_py_stack = threading.local()


def _native():
    from .. import core

    return core.lib() if core.native_available() else None


def _intern(name: str) -> int:
    with _lock:
        i = _name_to_id.get(name)
        if i is None:
            i = len(_id_to_name)
            _name_to_id[name] = i
            _id_to_name.append(name)
        return i


class RecordEvent:
    """``with RecordEvent("forward"):`` — or use as a decorator via
    :func:`record_event`. Nesting builds a flame stack."""

    __slots__ = ("name", "_nid")

    def __init__(self, name: str):
        self.name = name
        self._nid = None

    def begin(self):
        if not _enabled:
            return
        self._nid = _intern(self.name)
        lib = _native()
        if lib is not None:
            lib.prof_push(self._nid)
        else:
            stack = getattr(_py_stack, "s", None)
            if stack is None:
                stack = _py_stack.s = []
            stack.append((self._nid, time.perf_counter_ns()))

    def end(self):
        if self._nid is None:
            return
        lib = _native()
        if lib is not None:
            lib.prof_pop()
        else:
            stack = getattr(_py_stack, "s", [])
            if stack:
                nid, t0 = stack.pop()
                _py_events.append(
                    (threading.get_ident(), len(stack), nid, t0, time.perf_counter_ns())
                )
        self._nid = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def record_event(name: str):
    """Decorator form of :class:`RecordEvent`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            with RecordEvent(name):
                return fn(*a, **k)

        return wrapper

    return deco


def reset():
    global _py_events
    lib = _native()
    if lib is not None:
        lib.prof_clear()
    _py_events = []


def start_profiler(state: str = "All", tracer_option: str = "Default",
                   trace_dir: Optional[str] = None):
    """Parity: fluid.profiler.start_profiler. ``state`` kept for signature
    compatibility ('CPU'|'GPU'|'All' — host spans always; device via XLA).
    ``tracer_option='All'`` (or 'OpDetail') also starts a jax.profiler trace
    into ``trace_dir`` (TensorBoard xplane)."""
    global _enabled, _jax_trace_dir
    reset()
    _enabled = True
    lib = _native()
    if lib is not None:
        lib.prof_enable(1)
    if state in ("GPU", "All") and tracer_option in ("All", "OpDetail"):
        try:
            import jax

            _jax_trace_dir = trace_dir or os.path.join(os.getcwd(), "xplane_trace")
            jax.profiler.start_trace(_jax_trace_dir)
        except Exception:
            _jax_trace_dir = None


def _collect():
    """All finished spans as (tid, depth, name, t0_ns, t1_ns)."""
    out = []
    lib = _native()
    if lib is not None:
        import ctypes

        n = lib.prof_collect(None, 0)
        if n:
            buf = (ctypes.c_uint64 * (4 * n))()
            n = lib.prof_collect(buf, n)
            for i in range(n):
                tid = buf[i * 4]
                packed = buf[i * 4 + 1]
                nid, depth = packed & 0xFFFFFFFF, packed >> 32
                name = _id_to_name[nid] if nid < len(_id_to_name) else f"event_{nid}"
                out.append((tid, depth, name, buf[i * 4 + 2], buf[i * 4 + 3]))
    for tid, depth, nid, t0, t1 in _py_events:
        name = _id_to_name[nid] if nid < len(_id_to_name) else f"event_{nid}"
        out.append((tid, depth, name, t0, t1))
    return out


def summary(sorted_by: str = "total") -> List[dict]:
    """Aggregate table rows (parity: profiler_helper.h summary)."""
    rows: Dict[str, dict] = {}
    total_time = 0.0
    for _tid, depth, name, t0, t1 in _collect():
        dt = (t1 - t0) / 1e6  # ms
        r = rows.setdefault(name, {"name": name, "calls": 0, "total_ms": 0.0,
                                   "min_ms": float("inf"), "max_ms": 0.0})
        r["calls"] += 1
        r["total_ms"] += dt
        r["min_ms"] = min(r["min_ms"], dt)
        r["max_ms"] = max(r["max_ms"], dt)
        if depth == 0:
            total_time += dt
    for r in rows.values():
        r["avg_ms"] = r["total_ms"] / r["calls"]
        r["ratio"] = (r["total_ms"] / total_time) if total_time else 0.0
    key = {"total": "total_ms", "calls": "calls", "max": "max_ms",
           "min": "min_ms", "ave": "avg_ms", "avg": "avg_ms"}.get(sorted_by, "total_ms")
    return sorted(rows.values(), key=lambda r: r[key], reverse=True)


def export_chrome_tracing(path: str):
    """chrome://tracing-loadable JSON of the host spans (parity:
    DeviceTracer GenProfile → timeline; tools/timeline.py)."""
    events = []
    for tid, _depth, name, t0, t1 in _collect():
        events.append({"name": name, "ph": "X", "pid": os.getpid(), "tid": int(tid),
                       "ts": t0 / 1e3, "dur": (t1 - t0) / 1e3, "cat": "host"})
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None,
                  print_table: bool = True):
    """Parity: fluid.profiler.stop_profiler — ends collection, prints the
    summary table, optionally writes chrome trace to ``profile_path``."""
    global _enabled, _jax_trace_dir
    _enabled = False
    lib = _native()
    if lib is not None:
        lib.prof_enable(0)
    if _jax_trace_dir is not None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        _jax_trace_dir = None
    table = summary(sorted_key)
    if print_table and table:
        hdr = f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}{'Min(ms)':>10}{'Max(ms)':>10}{'Ratio':>8}"
        print("-" * len(hdr))
        print(hdr)
        print("-" * len(hdr))
        for r in table:
            print(f"{r['name'][:39]:<40}{r['calls']:>8}{r['total_ms']:>12.3f}"
                  f"{r['avg_ms']:>10.3f}{r['min_ms']:>10.3f}{r['max_ms']:>10.3f}"
                  f"{r['ratio']:>8.2%}")
        print("-" * len(hdr))
    if profile_path:
        export_chrome_tracing(profile_path)
    return table


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None, tracer_option: str = "Default",
             print_table: bool = True):
    """Parity: ``with fluid.profiler.profiler('All', 'total', path):``"""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path, print_table)
