"""Per-tick / per-step breakdown of a pipeline train step by DIRECT probes.

The r5 verdict's complaint about the pipeline gap was that its overhead was
attributed "by elimination".  This module closes that: every number in the
breakdown is its own timed, jitted probe on the SAME mesh with the SAME
shards — nothing is inferred as a residual.

Two levels:

* **per-step regions** — ``forward_backward`` (jitted value_and_grad of the
  schedule loss), ``optimizer_apply`` (jitted ``_apply_updates`` on
  synthetic grads), and ``host_dispatch`` (the host-side async-enqueue span
  recorded by the step wrapper's timer registry).
* **per-tick regions** (forward schedule decomposition) —
  ``stage_compute`` (the tick scan with ONLY the stage bodies),
  ``boundary_ppermute`` (the tick scan with ONLY the activation rotation;
  identically zero at pp=1, where the specialization has no boundary
  transfers), ``inject`` (the m embedding lookups) and ``head_loss`` (the
  m CE heads).

Because the r6 schedule overlaps the boundary permute with the deferred CE
head and the next inject, the sum of independently-timed regions may exceed
the measured total — ``attributed_fraction`` reports the coverage either
way (>= 1.0 means fully attributed with overlap).

Used by ``bench.py`` and ``benchmarks/profile_pipeline_r6.py`` (which
writes ``benchmarks/pipeline_profile_r6.json``).
"""
from __future__ import annotations

import json
import os
import time

from .scope import disable_timers, enable_timers, timer_registry, timers_enabled

PROFILE_SCHEMA = "paddle_tpu.pipeline_profile.v1"

__all__ = ["PROFILE_SCHEMA", "profile_pipeline_step", "write_profile"]


def _interleaved_times(probes, reps=3, inner=2):
    """Per-probe best-case (min) wall times with the timing rounds
    INTERLEAVED round-robin across probes, so machine-load drift during a
    long profile hits every probe equally. The min over rounds is the
    noise-robust estimator for BETWEEN-probe ratios (contention only ever
    adds time); on a quiet accelerator host min ~= median. ``probes``:
    {name: (fn, args)}; one untimed warmup call per probe compiles first."""
    import jax

    for fn, args in probes.values():
        jax.block_until_ready(fn(*args))
    times = {name: [] for name in probes}
    for _ in range(reps):
        for name, (fn, args) in probes.items():
            t0 = time.perf_counter()
            for _ in range(inner):
                out = fn(*args)
            jax.block_until_ready(out)
            times[name].append((time.perf_counter() - t0) / inner)
    return {name: min(ts) for name, ts in times.items()}


def profile_pipeline_step(step, x, y, *, steps: int = 5, reps: int = 3):
    """Breakdown of a built pipeline train step (``build_gpt_pipeline_step``
    / ``build_pipeline_layer_step`` result) into named, directly-measured
    regions.  Returns the profile dict (see PROFILE_SCHEMA).

    NOTE: runs real train steps (donated buffers advance ``step.state``) —
    profile a throwaway step, or accept the extra updates.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..distributed.meta_parallel.pipeline_schedule import (
        DP_AXIS,
        EP_AXIS,
        PP_AXIS,
        SH_AXIS,
        _apply_updates,
    )
    from ..distributed.spmd import P, shard_map

    pipe = step.pipe
    mesh = step.mesh
    compute_dtype = step.compute_dtype
    params = step.state["params"]
    opt_state = step.state["opt"]

    param_specs = {"stages": pipe.stage_specs, "shared": pipe.shared_specs}
    data_axes = tuple(a for a in (DP_AXIS, SH_AXIS, EP_AXIS)
                      if a in mesh.shape)
    data_spec = P(data_axes) if data_axes else P()

    x = jnp.asarray(x)
    y = jnp.asarray(y)
    kd = jax.random.key_data(jax.random.key(0))
    n = int(mesh.shape.get(PP_AXIS, 1))
    v = pipe.num_virtual
    m = pipe.microbatches
    ticks = pipe.schedule_ticks()
    scheduled = not (n == 1 and v == 1)  # else the pp=1 specialization runs

    def cast(tree):
        if compute_dtype is None:
            return tree
        return jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

    def smap(fn, in_specs, out_specs=P()):
        return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))

    # ---- per-step probes -------------------------------------------------
    def loss_of(p, xl, yl, key):
        pc = cast(p)
        return pipe.local_loss(pc["stages"], pc["shared"], xl, yl, key)

    def fwd(p, xl, yl, kd):
        return loss_of(p, xl, yl, jax.random.wrap_key_data(kd))

    def fwd_bwd(p, xl, yl, kd):
        key = jax.random.wrap_key_data(kd)
        loss, grads = jax.value_and_grad(
            lambda pp: loss_of(pp, xl, yl, key))(p)
        # fold the grads into one scalar so the probe's output transfer is
        # negligible but nothing is dead-code-eliminated
        acc = loss
        for grp in grads:
            for g in grads[grp].values():
                acc = acc + jnp.sum(g.astype(jnp.float32)) * 0.0
        return acc

    n_shard = int(mesh.shape.get(SH_AXIS, 1))
    has_sh = SH_AXIS in mesh.shape and n_shard > 1
    has_dp = DP_AXIS in mesh.shape and int(mesh.shape[DP_AXIS]) > 1
    has_ep = EP_AXIS in mesh.shape and int(mesh.shape[EP_AXIS]) > 1
    mesh_axes = set(mesh.shape)
    optimizer = step.optimizer

    def grad_reduce(g, lr):
        # the spmd_step's cross-rank grad combination (shared-param psum
        # over 'pp' + dp/ep/sharding means), alone
        out = jax.tree_util.tree_map(lambda a: lax.psum(a, PP_AXIS),
                                     g["shared"])
        stages = g["stages"]
        if has_dp:
            out = jax.tree_util.tree_map(lambda a: lax.pmean(a, DP_AXIS), out)
            stages = jax.tree_util.tree_map(
                lambda a: lax.pmean(a, DP_AXIS), stages)
        if has_ep:
            out = jax.tree_util.tree_map(lambda a: lax.pmean(a, EP_AXIS), out)
        acc = jnp.zeros((), jnp.float32)
        for tree in (out, stages):
            for leaf in tree.values():
                acc = acc + jnp.sum(leaf.astype(jnp.float32)) * 0.0
        return acc + lr * 0.0

    def opt_apply(p, g, opt, lr):
        new_p, _ = _apply_updates(optimizer, p, g, opt, n_shard, has_sh,
                                  pipe, mesh_axes, lr)
        acc = jnp.zeros((), jnp.float32)
        for grp in new_p:
            for leaf in new_p[grp].values():
                acc = acc + jnp.sum(leaf.astype(jnp.float32)) * 0.0
        return acc

    step_in = (param_specs, data_spec, data_spec, P())

    def _spec_of(a):
        sh = getattr(a, "sharding", None)
        return sh.spec if sh is not None and hasattr(sh, "spec") else P()

    opt_specs = {
        "slots": jax.tree_util.tree_map(_spec_of, opt_state["slots"]),
        "step": P(),
    }

    # full step + the host dispatch span (timer registry armed). Runs
    # FIRST: the real steps donate the old param/slot buffers, so every
    # probe below re-reads the live state afterwards. The caller's timer
    # state is preserved: the dispatch span is read as a DELTA and the
    # registry is neither reset nor left re-armed/disarmed.
    was_enabled = timers_enabled()
    span = "pipeline.step.host_dispatch"
    enable_timers()
    try:
        jax.block_until_ready(step(x, y))  # warm
        before_total = timer_registry.total(span)
        before_count = timer_registry.count(span)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        jax.block_until_ready(loss)
        t_step = (time.perf_counter() - t0) / steps
        d_count = timer_registry.count(span) - before_count
        d_total = timer_registry.total(span) - before_total
        t_dispatch = d_total / d_count if d_count else 0.0
    finally:
        if not was_enabled:
            disable_timers()

    # ---- per-tick probes (the forward schedule, decomposed) --------------
    tick_in = (param_specs, data_spec, data_spec, P())

    def stage_only(p, xl, yl, kd):
        key = jax.random.wrap_key_data(kd)
        pc = cast(p)
        local_stage = pipe._local_stage_view(pc["stages"])
        shared = pc["shared"]
        h_shape, h_dtype = pipe._h0_shape_dtype(shared, xl)
        h0 = jnp.ones(h_shape, h_dtype)
        if not scheduled:
            # the pp=1 specialization's statically-indexed body, m times
            acc = jnp.zeros((), jnp.float32)
            h = h0
            for j in range(m):
                h, aux = pipe._pp1_body(local_stage, h,
                                        jax.random.fold_in(key, j))
                acc = acc + aux
            return jnp.sum(h.astype(jnp.float32)) + acc

        s_idx = lax.axis_index(PP_AXIS)

        def body(h, t):
            c = (t // n) % v  # the chunk sequence the real schedule walks
            h, aux = pipe._stage_apply(local_stage, c, s_idx, h,
                                       jax.random.fold_in(key, t))
            return h, aux

        h, auxs = lax.scan(body, h0, jnp.arange(ticks))
        return jnp.sum(h.astype(jnp.float32)) + jnp.sum(auxs)

    def permute_only(p, xl, yl, kd):
        shared = cast(p)["shared"]
        h_shape, h_dtype = pipe._h0_shape_dtype(shared, xl)
        h0 = jnp.ones(h_shape, h_dtype)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(h, _):
            return lax.ppermute(h, PP_AXIS, perm), None

        h, _ = lax.scan(body, h0, None, length=ticks)
        return jnp.sum(h.astype(jnp.float32))

    def inject_only(p, xl, yl, kd):
        shared = cast(p)["shared"]
        mb = xl.shape[0] // m
        x_mb = xl.reshape((m, mb) + xl.shape[1:])
        acc = jnp.zeros((), jnp.float32)
        for j in range(m):
            h = pipe._inject(shared, x_mb[j], None)
            acc = acc + jnp.sum(h.astype(jnp.float32))
        return acc

    def head_only(p, xl, yl, kd):
        shared = cast(p)["shared"]
        mb = xl.shape[0] // m
        y_mb = yl.reshape((m, mb) + yl.shape[1:])
        h_shape, h_dtype = pipe._h0_shape_dtype(shared, xl)
        h = jnp.ones(h_shape, h_dtype)
        acc = jnp.zeros((), jnp.float32)
        for j in range(m):
            acc = acc + pipe._head_loss(shared, h, y_mb[j])
        return acc

    def bookkeeping_only(p, xl, yl, kd):
        # the tick scan's machinery alone, mirroring the real tick with
        # the stage/inject/head BODIES removed: index math, the microbatch
        # gather, per-tick PRNG folds, the cond dispatches (trivial
        # branches) and the full carry plumbing
        key = jax.random.wrap_key_data(kd)
        shared = cast(p)["shared"]
        mb = xl.shape[0] // m
        x_mb = xl.reshape((m, mb) + xl.shape[1:])
        s_idx = lax.axis_index(PP_AXIS) if scheduled else 0
        h_shape, h_dtype = pipe._h0_shape_dtype(shared, xl)
        h0 = jnp.ones(h_shape, h_dtype)

        def body(carry, t):
            h, prev_mb, prev_live, acc, aux = carry
            acc = acc + lax.cond(
                prev_live,
                lambda i: jnp.sum(x_mb[i]).astype(jnp.float32),
                lambda i: jnp.zeros((), jnp.float32), prev_mb)
            # the REAL schedule's index math, shared so this probe cannot
            # drift from the tick loop
            c, mb_c, valid = pipe._tick_indices(t, s_idx, n)
            h = lax.cond((s_idx == 0) & (c == 0),
                         lambda hp, i: hp + x_mb[i].sum().astype(hp.dtype)
                         * jnp.zeros((), hp.dtype),
                         lambda hp, i: hp, h, mb_c)
            mb_key = jax.random.fold_in(key, mb_c)
            aux = aux + jnp.where(valid,
                                  jax.random.key_data(mb_key).sum()
                                  .astype(jnp.float32) * 0.0, 0.0)
            live = (s_idx == n - 1) & (c == v - 1) & valid
            return (h, mb_c, live, acc, aux), None

        carry0 = (h0, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.bool_),
                  jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        (h, _, _, acc, aux), _ = lax.scan(body, carry0, jnp.arange(ticks))
        return jnp.sum(h.astype(jnp.float32)) * 0.0 + acc + aux

    # all probes timed in ONE interleaved batch on the post-donation live
    # state, so load drift during the run cancels out of the ratios
    params = step.state["params"]
    opt_state = step.state["opt"]
    grads = jax.tree_util.tree_map(lambda a: jnp.zeros_like(a), params)
    lr = jnp.asarray(1e-4, jnp.float32)
    args = (params, x, y, kd)
    probes = {
        "fwd": (smap(fwd, step_in), args),
        "fwd_bwd": (smap(fwd_bwd, step_in), args),
        "opt_apply": (smap(opt_apply,
                           (param_specs, param_specs, opt_specs, P())),
                      (params, grads, opt_state, lr)),
        "grad_reduce": (smap(grad_reduce, (param_specs, P())), (grads, lr)),
        "stage": (smap(stage_only, tick_in), args),
        "inject": (smap(inject_only, tick_in), args),
        "head": (smap(head_only, tick_in), args),
    }
    if scheduled:
        probes["permute"] = (smap(permute_only, tick_in), args)
        probes["bookkeeping"] = (smap(bookkeeping_only, tick_in), args)
    t = _interleaved_times(probes, reps)
    t_fwd, t_fwd_bwd = t["fwd"], t["fwd_bwd"]
    t_opt, t_reduce = t["opt_apply"], t["grad_reduce"]
    t_stage, t_inject, t_head = t["stage"], t["inject"], t["head"]
    # the pp=1 specialization has NO boundary transfers and NO tick scan
    # machinery (statically-indexed python-unrolled microbatches) — both
    # regions are zero by construction, not as a residual
    t_perm = t.get("permute", 0.0)
    t_book = t.get("bookkeeping", 0.0)

    per_tick_total = t_fwd / ticks
    tick_regions = {
        "stage_compute": t_stage / ticks,
        "boundary_ppermute": t_perm / ticks,
        "inject": t_inject / ticks,
        "head_loss": t_head / ticks,
        "tick_bookkeeping": t_book / ticks,
    }
    step_regions = {
        "forward_backward": t_fwd_bwd,
        "grad_reduce": t_reduce,
        "optimizer_apply": t_opt,
    }

    dev = jax.devices()[0]
    return {
        "schema": PROFILE_SCHEMA,
        "device": {"platform": dev.platform,
                   "kind": getattr(dev, "device_kind", "")},
        "config": {
            "pp": n, "microbatches": m, "virtual_stages": v, "ticks": ticks,
            "scheduled_path": scheduled,
            "mesh": {k: int(s) for k, s in mesh.shape.items()},
            "compute_dtype": str(compute_dtype) if compute_dtype else None,
            "batch": int(x.shape[0]), "seq": int(x.shape[-1]),
        },
        "per_step_ms": {
            "total": t_step * 1e3,
            "regions": {k: t * 1e3 for k, t in step_regions.items()},
            # the async-enqueue span; on accelerators it overlaps device
            # execution (on the sync cpu backend it CONTAINS it), so it is
            # reported beside the additive device regions, not summed
            "host_dispatch": t_dispatch * 1e3,
            "attributed_fraction": sum(step_regions.values()) / t_step,
        },
        "per_tick_ms": {
            "total_forward": per_tick_total * 1e3,
            "regions": {k: t * 1e3 for k, t in tick_regions.items()},
            "attributed_fraction":
                sum(tick_regions.values()) / per_tick_total,
        },
    }


def write_profile(path: str, profile: dict) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(profile, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def update_profile(path: str, legs: dict, device=None, generated_by=None,
                   round_no: int = 6) -> str:
    """Read-merge-write the profile artifact: the named ``legs`` are
    updated/added and every other existing leg is PRESERVED, so the two
    writers (bench.py's pp1 leg, profile_pipeline_r6.py's scheduled +
    A/B legs) compose instead of clobbering each other."""
    doc = {"schema": PROFILE_SCHEMA, "round": round_no, "legs": {}}
    try:
        with open(path) as f:
            existing = json.load(f)
        if existing.get("schema") == PROFILE_SCHEMA:
            doc = existing
            doc.setdefault("legs", {})
            doc.setdefault("round", round_no)
    except Exception:
        pass
    doc["legs"].update(legs)
    if device is not None:
        doc["device"] = device
    if generated_by is not None:
        gb = doc.get("generated_by")
        if gb and generated_by not in gb:
            doc["generated_by"] = f"{gb} + {generated_by}"
        else:
            doc["generated_by"] = generated_by
    return write_profile(path, doc)
