"""End-of-round benchmark: GPT pretraining step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: tokens/sec/chip on the largest GPT config that fits a single chip,
with MFU derived from the standard 6*N*T + attention FLOPs estimate.
vs_baseline is MFU / 0.40 (the BASELINE.json north-star 40% MFU target).
"""
from __future__ import annotations

import json
import time

import numpy as np


def _peak_flops_bf16(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v6e": 918e12, "v6": 918e12,
        "v5e": 197e12, "v5litepod": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_config,
    )
    from paddle_tpu.optimizer.optimizers import AdamW

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # measured on v5e-1: recompute OFF at batch 8 is the throughput
        # optimum (33.9k tok/s vs 29.2k with remat; batch 16 OOMs without
        # remat, and remat at 16 is slower than no-remat at 8).
        # Attention path: at this model's head_dim=64 the XLA fused path
        # beats the Pallas flash kernel 2x (8.7 vs 16.6 ms/fwd+bwd at
        # B8 H16 T1024 — 64 lanes under-fill the 128-wide MXU), so the
        # functional_attention dispatch gate (flash only when D%128==0)
        # stands; flash pays off at head_dim>=128 / long T
        cfg = gpt_config("gpt3-350m", hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0, use_recompute=False)
        batch, seq, steps, warmup = 8, 1024, 10, 3
    else:  # CI / CPU smoke: tiny shapes, same code path
        cfg = gpt_config("gpt2-small", vocab_size=256, hidden_size=64,
                         num_layers=2, num_attention_heads=4,
                         max_position_embeddings=64,
                         hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        batch, seq, steps, warmup = 4, 32, 3, 1

    paddle.seed(0)
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters())
    trainer = ParallelTrainer(
        model, lambda out, y: crit(out, y), opt,
        dp_axis=None,
        compute_dtype="bfloat16" if on_tpu else None,
    )

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))

    for _ in range(warmup):
        loss = trainer.step(ids, ids)
    # scalar readback is the only reliable sync through the remote tunnel
    # (block_until_ready acks before remote execution completes)
    float(np.asarray(loss._data))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(ids, ids)
    float(np.asarray(loss._data))
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_sec = tokens / dt

    n_params = sum(int(np.prod(p._data.shape)) for p in model.parameters())
    # 6*N per token (fwd+bwd matmuls) + causal attention: 12*L*seq*hidden/2
    flops_per_token = 6 * n_params + 6 * cfg.num_layers * seq * cfg.hidden_size
    mfu = tok_per_sec * flops_per_token / _peak_flops_bf16(dev)

    print(json.dumps({
        "metric": f"gpt_{'350m' if on_tpu else 'tiny'}_train_tokens_per_sec_chip",
        "value": round(tok_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


if __name__ == "__main__":
    main()
