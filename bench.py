"""End-of-round benchmark: GPT pretraining step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "secondary"}.

Metric: tokens/sec/chip on gpt3-1.3b — the BASELINE.json north-star config,
fitting ONE v5e chip since r3 (f32 params 5.3GB + bf16 Adam moments 5.3GB +
partial rematerialization). vs_baseline is MFU / 0.40 (the north-star 40%
MFU target). "secondary" reports gpt3-760m and gpt3-350m throughput, the
eager per-layer jit-cache speedup, and the ppermute-scan pipeline-step
overhead at pp=1 (VERDICT r2 #5).

MFU accounting (pinned so future rounds can't inflate it):
  flops/token = 6*N + 6*L*T*H
  - 6*N: the PaLM-style rule — each of the N weight-matrix params does one
    MAC in fwd (2 flops) and two in bwd (4 flops) per token.
  - attention scores/values: per layer QK^T and PV are 2 matmuls of
    2*T*H flops/token each (H = hidden = heads*head_dim) => 4*T*H fwd;
    backward recomputes both and adds dQ/dK/dV => ~3x fwd => 12*L*T*H,
    halved for causal masking (only the lower triangle is useful work,
    and the flash kernel actually skips most of the masked blocks)
    => 6*L*T*H. Embedding/LN/softmax flops are excluded (standard MFU).
Peak bf16 flops: v5e 197 TFLOP/s (table below for other generations).
"""
from __future__ import annotations

import json
import time

import numpy as np


def _peak_flops_bf16(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v6e": 918e12, "v6": 918e12,
        "v5e": 197e12, "v5litepod": 197e12, "v5 lite": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class


def _train_tput(name, batch, seq, steps, warmup, on_tpu, recompute=False,
                granularity="full", moment_dtype="bfloat16",
                recompute_interval=1, accumulate_steps=1):
    """tokens/sec for one config; returns (tok_per_sec, n_params, cfg)."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_config,
    )
    from paddle_tpu.optimizer.optimizers import AdamW

    overrides = dict(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                     use_recompute=recompute, recompute_granularity=granularity,
                     recompute_interval=recompute_interval)
    if not on_tpu:  # CI / CPU smoke: tiny shapes, same code path
        overrides.update(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
    cfg = gpt_config(name, **overrides)

    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                moment_dtype=moment_dtype)
    trainer = ParallelTrainer(
        model, lambda out, y: crit(out, y), opt,
        dp_axis=None,
        compute_dtype="bfloat16" if on_tpu else None,
        recompute=False,
        accumulate_steps=accumulate_steps,
    )
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))

    for _ in range(warmup):
        loss = trainer.step(ids, ids)
    # scalar readback is the only reliable sync through the remote tunnel
    # (block_until_ready acks before remote execution completes)
    float(np.asarray(loss._data))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(ids, ids)
    float(np.asarray(loss._data))
    dt = time.perf_counter() - t0

    n_params = sum(int(np.prod(p._data.shape)) for p in model.parameters())
    return batch * seq * steps / dt, n_params, cfg


def _pipeline_tput(name, batch, seq, steps=5, reps=3, profile=False):
    """tokens/s of the ppermute-scan hybrid step on a pp=1 mesh (exercises
    the scan/slice/clip machinery; overhead vs the plain step is the BENCH
    secondary VERDICT r2 #5 asked for). With ``profile=True`` also runs the
    profiler's direct-probe breakdown (per-tick + per-step named regions)
    and refreshes benchmarks/pipeline_profile_r6.json — the r6 artifact
    that replaces attribute-by-elimination."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
        build_gpt_pipeline_step,
    )
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.optimizer.optimizers import AdamW

    cfg = gpt_config(name, hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"pp": 1})
    model = GPTForPretraining(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                moment_dtype="bfloat16")
    step = build_gpt_pipeline_step(model, opt, microbatches=2,
                                   compute_dtype="bfloat16",
                                   remat_policy="selective")
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32")
    float(np.asarray(step(ids, ids)))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, ids)
        float(np.asarray(loss))
        times.append(time.perf_counter() - t0)
    med = sorted(times)[len(times) // 2]
    prof = None
    if profile:
        # profiling must never cost the round its measured throughput —
        # and it MERGES its leg into the artifact (profile_pipeline_r6.py
        # contributes the pp2_scheduled / profiler-A/B legs)
        try:
            from paddle_tpu.profiler.pipeline import profile_pipeline_step

            prof = profile_pipeline_step(step, ids, ids, steps=steps)
        except Exception as e:  # pragma: no cover - device dependent
            import sys

            prof = None
            print(f"# pipeline profiling failed, keeping tput: "
                  f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
        if prof is not None:
            # artifact write failure must not void the in-memory profile
            try:
                import os

                from paddle_tpu.profiler.pipeline import update_profile

                update_profile(
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "benchmarks", "pipeline_profile_r6.json"),
                    {"pp1_bench_arm": prof}, device=prof["device"],
                    generated_by="bench.py _pipeline_tput(profile=True)")
            except Exception as e:  # pragma: no cover - device dependent
                import sys

                print(f"# pipeline profile artifact write failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
    del step, model
    gc.collect()
    tput = batch * seq * steps / med
    return (tput, prof) if profile else tput


def _sentinel_overhead(on_tpu, steps=20, warmup=3):
    """Anomaly-sentinel-enabled vs disabled step time on the SAME config —
    the zero-overhead claim TRACKED, not asserted (ISSUE 2 satellite; the
    jaxpr-identity test proves the disabled case exactly, this measures the
    enabled case). The sentinel cost is per-step fixed (one finite-reduce
    over grads + a scalar state machine), so a small config upper-bounds the
    relative overhead of the scalar part; the grad reduce scales with what
    the step already touches."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_config,
    )
    from paddle_tpu.optimizer.optimizers import AdamW
    from paddle_tpu.resilience import SentinelConfig

    if on_tpu:
        name, batch, seq = "gpt3-350m", 8, 1024
        overrides = {}
    else:
        name, batch, seq, steps, warmup = "gpt2-small", 4, 32, 10, 2
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    per_step = {}
    for mode, sent in (("disabled", None), ("enabled", SentinelConfig())):
        paddle.seed(0)
        clear_mesh()
        gc.collect()
        init_mesh({"dp": 1})
        model = GPTForPretraining(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                    moment_dtype="bfloat16")
        trainer = ParallelTrainer(
            model, lambda out, y: crit(out, y), opt, dp_axis=None,
            compute_dtype="bfloat16" if on_tpu else None, sentinel=sent)
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(
            rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))
        for _ in range(warmup):
            loss = trainer.step(ids, ids)
        float(np.asarray(loss._data))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = trainer.step(ids, ids)
        float(np.asarray(loss._data))
        per_step[mode] = (time.perf_counter() - t0) / steps
    return {
        "sentinel_disabled_step_ms": round(per_step["disabled"] * 1e3, 3),
        "sentinel_enabled_step_ms": round(per_step["enabled"] * 1e3, 3),
        "sentinel_overhead_frac": round(
            per_step["enabled"] / per_step["disabled"] - 1, 4),
    }


def _observability_overhead(on_tpu):
    """Telemetry-plane tax on BOTH hot paths (ISSUE 7 satellite): tok/s
    with tracing + metrics + live gauges armed vs disabled, on the same
    warmed trainer and serving engine. The plane's budget is <2% — the
    ``*_ok`` booleans pin the assertion in the round artifact. One-off
    costs (TrainerTelemetry.prime's static analysis, span-ring resize)
    run OUTSIDE the timed regions; the measured delta is purely the
    per-step/per-tick host bookkeeping."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_config,
    )
    from paddle_tpu.optimizer.optimizers import AdamW
    from paddle_tpu.serving import ContinuousBatchingEngine, Request

    if on_tpu:
        name, batch, seq, steps, warmup = "gpt3-350m", 8, 1024, 20, 3
        overrides = {}
        n_req, max_new, s_len, n_slots, buckets = 16, 32, 512, 8, [64, 128]
        lo, hi = 16, 120
    else:
        name, batch, seq, steps, warmup = "gpt2-small", 4, 32, 10, 2
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
        n_req, max_new, s_len, n_slots, buckets = 8, 8, 64, 4, [8, 16]
        lo, hi = 3, 14
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    obs.disable_tracing()
    out = {}

    # -- trainer arm ---------------------------------------------------------
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                moment_dtype="bfloat16")
    trainer = ParallelTrainer(model, lambda out_, y: crit(out_, y), opt,
                              dp_axis=None,
                              compute_dtype="bfloat16" if on_tpu else None)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))

    def trainer_pass(step_fn):
        for _ in range(warmup):
            loss = step_fn(ids, ids)
        float(np.asarray(loss._data))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step_fn(ids, ids)
        float(np.asarray(loss._data))
        return (time.perf_counter() - t0) / steps

    plain_s = trainer_pass(trainer.step)
    obs.enable_tracing()
    telemetry = obs.TrainerTelemetry(trainer)
    try:
        telemetry.prime(ids, ids)  # one-off static analysis, untimed
    except Exception as e:  # pragma: no cover - must not void the arm
        out["observability_prime_error"] = f"{type(e).__name__}"
    traced_s = trainer_pass(telemetry.step)
    telemetry.refresh_hbm()
    rep = telemetry.report()
    obs.disable_tracing()
    frac = traced_s / plain_s - 1
    out.update({
        "observability_trainer_plain_step_ms": round(plain_s * 1e3, 3),
        "observability_trainer_traced_step_ms": round(traced_s * 1e3, 3),
        "observability_trainer_overhead_frac": round(frac, 4),
        "observability_trainer_overhead_ok": bool(frac < 0.02),
        "observability_live_mfu": (round(rep["mfu"], 4)
                                   if rep.get("mfu") else None),
        "observability_hbm_drift_frac": (
            round(rep["hbm_drift_frac"], 4)
            if rep.get("hbm_drift_frac") is not None else None),
    })
    del trainer, model
    gc.collect()

    # -- serving arm ---------------------------------------------------------
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    smodel = GPTForPretraining(cfg)
    smodel.eval()
    prompts = [rng.integers(0, cfg.vocab_size, (int(l),)).astype("int32")
               for l in rng.integers(lo, hi, size=n_req)]
    eng = ContinuousBatchingEngine(smodel, max_seq_len=s_len,
                                   n_slots=n_slots, prefill_buckets=buckets,
                                   max_queue=n_req)

    def engine_pass():
        reqs = [Request(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        eng.generate_batch(reqs)
        return n_req * max_new / (time.perf_counter() - t0)

    engine_pass()  # warmup: every bucket + the step compile
    plain_tps = engine_pass()
    obs.enable_tracing()
    traced_tps = engine_pass()
    obs.disable_tracing()
    sfrac = plain_tps / traced_tps - 1
    out.update({
        "observability_serving_plain_tokens_per_sec": round(plain_tps, 2),
        "observability_serving_traced_tokens_per_sec": round(traced_tps, 2),
        "observability_serving_overhead_frac": round(sfrac, 4),
        "observability_serving_overhead_ok": bool(sfrac < 0.02),
        "observability_flight_schema_version": obs.FLIGHT_SCHEMA_VERSION,
        # r14: the serving latency histograms carry exemplars now, so the
        # <2% overhead booleans above are measured WITH exemplars enabled
        "observability_exemplars_enabled": True,
    })
    return out


def _analysis_overhead():
    """Wall time of the full static-analysis sweep over the shipped entry
    points (ISSUE 4 satellite): the linter must stay cheap (< a few seconds
    per entry point on CPU) or it falls out of CI. Also records the finding
    counts so a regression that re-introduces a HIGH finding is visible in
    the round artifact, not just the smoke test.

    r10 (ISSUE 5): also times the liveness/memory sweep over the same
    targets (``analysis_memory_s``) and cross-checks the liveness
    estimator against MEASURED live bytes for the eager trainer step —
    jax.live_arrays() delta around building the trainer state (CPU has no
    allocator stats: device_memory_stats is None there, so the live-array
    census + an RSS reading are the proxies)."""
    import time as _time

    from paddle_tpu.analysis.entrypoints import shipped_entry_points
    from paddle_tpu.analysis.memory import memory_estimate
    from paddle_tpu.analysis.rules import analyze_targets

    t0 = _time.perf_counter()
    targets, errors = shipped_entry_points(skip_errors=True)
    build_s = _time.perf_counter() - t0
    # time the liveness sweep FIRST: memory_estimate memoizes per target,
    # so running the (memory-rule-bearing) lint first would zero this out
    t0 = _time.perf_counter()
    peaks = {}
    for t in targets:
        try:
            peaks[t.name] = memory_estimate(t).peak_bytes
        except Exception as e:  # pragma: no cover - must not void the round
            peaks[t.name] = f"failed: {type(e).__name__}"
    memory_s = _time.perf_counter() - t0
    report = analyze_targets(targets)
    out = {
        "analysis_entry_points": len(targets),
        "analysis_build_s": round(build_s, 3),
        "analysis_lint_s": round(
            sum(report.meta["timings_s"].values()), 3),
        "analysis_per_entry_s": report.meta["timings_s"],
        "analysis_findings": report.counts(),
    }
    if errors:
        out["analysis_build_errors"] = errors
    out["analysis_memory_s"] = round(memory_s, 3)
    out["analysis_peak_hbm_bytes"] = peaks
    try:
        out.update(_analysis_estimator_vs_measured())
    except Exception as e:  # pragma: no cover
        out["memory_est_vs_measured"] = f"failed: {type(e).__name__}"
    return out


def _host_analysis():
    """Concurrency-doctor secondary (ISSUE 14): host-lint coverage
    (modules scanned, findings by severity, lock/edge counts, wall time)
    plus the instrumented-lock recorder's measured wall tax on the suites
    it arms. The tax is computed from MEASURED pieces, never modeled
    constants: (acquires recorded by the committed tier-1 journal) x
    (micro-measured per-acquire wrapper delta on this box) / (the
    journal's armed wall seconds) — the <2% acceptance bound gates as a
    boolean."""
    import time as _time

    from paddle_tpu.analysis import lockmodel
    from paddle_tpu.analysis.hostrace import analyze_host, default_journal_path

    report = analyze_host()  # merges the committed journal when present
    counts = report.counts()
    out = {
        "host_analysis_modules": report.meta["n_modules"],
        "host_analysis_locks": report.meta["n_locks"],
        "host_analysis_lint_s": report.meta["total_s"],
        "host_findings_high": counts["HIGH"],
        "host_findings_medium": counts["MEDIUM"],
        "host_findings_low": counts["LOW"],
        "host_findings_info": counts["INFO"],
        "host_lock_graph_acyclic": bool(report.meta["lock_graph_acyclic"]),
        "host_static_edges": report.meta["n_static_edges"],
        "host_runtime_edges": report.meta["n_runtime_edges"],
    }
    import os

    jpath = default_journal_path()
    if not os.path.exists(jpath):
        out["host_journal_overhead_ok"] = "skipped (no journal)"
        return out
    import json as _json

    with open(jpath) as fh:
        jmeta = _json.load(fh).get("meta", {})
    acquires = int(jmeta.get("acquires", 0))
    armed_wall = float(jmeta.get("armed_wall_s", 0.0))

    # per-acquire wrapper delta: tight uncontended acquire/release loop on
    # a bare lock vs an instrumented one (median of 5 reps each)
    n = 200_000

    def loop(lock):
        t0 = _time.perf_counter()
        for _ in range(n):
            lock.acquire()
            lock.release()
        return _time.perf_counter() - t0

    rec = lockmodel.LockOrderRecorder()
    import threading as _threading

    bare = sorted(loop(_threading.Lock()) for _ in range(5))[2]
    wrapped = sorted(
        loop(lockmodel.InstrumentedLock(_threading.Lock(),
                                        ("bench", 0), rec))
        for _ in range(5))[2]
    delta_per_acquire = max((wrapped - bare) / n, 0.0)
    frac = (acquires * delta_per_acquire / armed_wall
            if armed_wall > 0 else 0.0)
    out.update({
        "host_journal_acquires": acquires,
        "host_journal_armed_wall_s": armed_wall,
        "host_journal_per_acquire_delta_us": round(
            delta_per_acquire * 1e6, 4),
        "host_journal_wall_delta_frac": round(frac, 6),
        "host_journal_overhead_ok": bool(frac < 0.02),
    })
    return out


def _determinism_lint():
    """Determinism-doctor secondary (ISSUE 19): host-plane finding counts
    by severity (the jaxpr key-flow plane already rides the default-rule
    counts in ``_analysis_overhead``) plus the replay-certificate seam
    coverage — ``det_findings_high``/``det_findings_medium`` and
    ``det_seams_uncovered`` are count_max baseline classes, so a PR that
    re-introduces a HIGH determinism hazard or strands an inject seam
    without its twin certificate regresses past the lineage maximum and
    gates."""
    from paddle_tpu.analysis import analyze_determinism

    report = analyze_determinism()
    counts = report.counts()
    cov = report.meta.get("seam_coverage", {})
    return {
        "det_modules": report.meta["n_modules"],
        "det_lint_s": report.meta["scan_s"],
        "det_findings_high": counts["HIGH"],
        "det_findings_medium": counts["MEDIUM"],
        "det_findings_low": counts["LOW"],
        "det_findings_info": counts["INFO"],
        "det_seam_points": cov.get("n_points", 0),
        "det_seams_covered": cov.get("n_covered", 0),
        "det_seams_uncovered": (cov.get("n_points", 0)
                                - cov.get("n_covered", 0)),
    }


def _kernel_lint():
    """Pallas kernel doctor secondary (ISSUE 20): findings by severity
    over the shipped kernel manifest (coverage proofs + f32-accumulation
    lint + VMEM budget + registry drift certification) plus the sweep
    row count.  ``kernel_findings_high``/``kernel_findings_medium`` are
    count_max baseline classes — a PR that breaks a BlockSpec coverage
    proof, drops an f32 accumulator cast, or lets a registry model drift
    past tolerance regresses past the lineage maximum and gates.
    ``kernel_drift_max_frac`` records the worst derived-vs-registered
    flops deviation ("drift" → magnitude class)."""
    import time as _time

    from paddle_tpu.analysis.kernels import analyze_kernels, kernel_sweep

    t0 = _time.perf_counter()
    report = analyze_kernels()
    lint_s = _time.perf_counter() - t0
    counts = report.counts()
    drift = 0.0
    for row in report.meta["kernels"]:
        ratio = row.get("flops_ratio")
        if ratio:
            drift = max(drift, abs(ratio - 1.0), abs(1.0 / ratio - 1.0))
    sweep = kernel_sweep()
    return {
        "kernel_manifest_cases": report.meta["n_cases"],
        "kernel_lint_s": round(lint_s, 3),
        "kernel_findings_high": counts["HIGH"],
        "kernel_findings_medium": counts["MEDIUM"],
        "kernel_findings_low": counts["LOW"],
        "kernel_findings_info": counts["INFO"],
        "kernel_drift_max_frac": round(drift, 4),
        "kernel_sweep_rows": len(sweep["rows"]),
    }


def _planner_search(on_tpu):
    """Auto-parallel planner v2 secondary (ISSUE 13): search wall time and
    candidate accounting for a real search (every analysis-priced row is a
    lowered-but-never-executed ShapeDtypeStruct target), the chosen plan
    id, the <0.5% self-consistency drift between the chosen plan's recorded
    peak and a fresh liveness estimate on the same target, and the
    predicted-vs-measured step-time ratio for a candidate this arm can
    actually run (CPU: a tiny GPT, so the ratio records the roofline
    model's CPU-arm bias — info, not a gate; the TPU arm planned against
    the real device spec is the comparable number)."""
    import time as _time

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.analysis.plan import plan_consistency_findings, plan_gpt
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_config,
    )
    from paddle_tpu.optimizer.optimizers import AdamW

    if on_tpu:
        name, seq, batch, n_dev = "gpt3-350m", 1024, 8, 1
        overrides = {}
        steps, warmup = 8, 2
    else:
        name, seq, batch, n_dev = "gpt2-small", 32, 8, 4
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4)
        steps, warmup = 3, 1
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0,
                     max_position_embeddings=seq, **overrides)

    t0 = _time.perf_counter()
    plan = plan_gpt(cfg, n_dev, batch, seq_len=seq, max_lowered=6)
    search_s = _time.perf_counter() - t0
    out = {
        "planner_search_wall_s": round(search_s, 3),
        "planner_candidates_enumerated": plan.n_enumerated,
        "planner_candidates_lowered": plan.n_lowered,
        "planner_candidates_pruned": plan.n_enumerated - plan.n_lowered,
        "planner_chosen_plan": (plan.chosen.spec.plan_id
                                if plan.chosen else None),
        "planner_chosen_feasible": plan.chosen is not None,
    }
    # self-consistency: recorded peak vs a fresh estimate on the SAME
    # lowered target (must be ~0 by construction; classified `drift`,
    # so the watchdog gates it)
    fs = [f for f in plan_consistency_findings(plan)
          if f.rule == "planner-consistency" and "drift" in f.details]
    if fs:
        out["planner_consistency_drift_frac"] = float(
            fs[0].details["drift"])

    # predicted-vs-measured: realize the single-device candidate this arm
    # can run and time it (the plan predicts with the DeviceSpec roofline,
    # so the CPU-arm ratio is a recorded bias, not a gate).  A dedicated
    # 1-device plan guarantees the dp1-mp1 row was analysis-priced even
    # when the main search lowered other candidates first.
    plan1 = (plan if n_dev == 1
             else plan_gpt(cfg, 1, batch, seq_len=seq, max_lowered=2))
    row = next((c for c in plan1.candidates
                if c.priced_by == "analysis" and not c.spec.remat), None)
    if row is not None:
        clear_mesh()
        init_mesh({"dp": 1})
        paddle.seed(0)
        model = GPTForPretraining(cfg)
        crit = GPTPretrainingCriterion(cfg)
        trainer = ParallelTrainer(
            model, lambda o, y: crit(o, y),
            AdamW(learning_rate=1e-4, parameters=model.parameters()),
            dp_axis=None,
            compute_dtype="bfloat16" if on_tpu else None)
        ids = paddle.to_tensor(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (batch, seq)).astype("int32"))
        for _ in range(warmup):
            loss = trainer.step(ids, ids)
        float(np.asarray(loss._data))
        t0 = _time.perf_counter()
        for _ in range(steps):
            loss = trainer.step(ids, ids)
        float(np.asarray(loss._data))
        measured = (_time.perf_counter() - t0) / steps
        out["planner_measured_candidate"] = row.spec.plan_id
        out["planner_pred_vs_measured_step_ratio"] = round(
            row.step_time_s / measured, 4)
        clear_mesh()
    return out


def _analysis_estimator_vs_measured():
    """Liveness-estimator resident bytes vs measured live-array bytes for
    the eager trainer step (ISSUE 5 acceptance tracks <= 15%): build the
    trainer-entry-point config, snapshot jax.live_arrays() before/after
    creating the trainer state + running one (donated) step, and compare
    the delta with the estimator's steady-state residency."""
    import gc

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.amp.grad_scaler import GradScaler
    from paddle_tpu.analysis.graph import AnalysisTarget
    from paddle_tpu.analysis.memory import estimate_memory
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.nn import BatchNorm1D, Linear, ReLU, Sequential
    from paddle_tpu.optimizer.optimizers import SGD
    from paddle_tpu.random import split_key
    from paddle_tpu.resilience import SentinelConfig

    from paddle_tpu.distributed.env import get_mesh, set_mesh

    def live_bytes():
        gc.collect()
        return sum(int(a.nbytes) for a in jax.live_arrays())

    prev_mesh = get_mesh()
    try:
        clear_mesh()
        init_mesh({"dp": 1})
        paddle.seed(0)
        # the model's own arrays exist BEFORE the baseline snapshot — the
        # trainer copies them (donation safety), and only the copies are
        # step state; counting both would double the params
        model = Sequential(Linear(32, 256), BatchNorm1D(256), ReLU(),
                           Linear(256, 8))
        before = live_bytes()
        trainer = ParallelTrainer(
            model, lambda out, y: ((out - y) ** 2).mean(), SGD(0.01),
            dp_axis=None, scaler=GradScaler(init_loss_scaling=1024.0),
            sentinel=SentinelConfig())
        trainer._build()
        xb = jnp.zeros((8, 32), jnp.float32)
        yb = jnp.zeros((8, 8), jnp.float32)
        loss = trainer.step(xb, yb)  # raw arrays: a Tensor wrap would copy
        float(np.asarray(loss._data))
        measured = live_bytes() - before

        args = (trainer.params, trainer.opt_state, trainer.buffers, xb, yb,
                split_key(), trainer.scale_state, trainer.sentinel_state,
                jnp.asarray(0.01, jnp.float32))
        target = AnalysisTarget("bench_trainer", trainer._jit_step, args,
                                mesh_axes={"dp": 1})
        est = estimate_memory(target)
    finally:
        set_mesh(prev_mesh)
    rss_kb = None
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:  # pragma: no cover
        pass
    out = {
        "memory_est_live_bytes": int(est.resident_bytes),
        "memory_measured_live_bytes": int(measured),
        "memory_est_vs_measured": round(
            est.resident_bytes / measured - 1, 4) if measured else None,
        "memory_est_peak_bytes": int(est.peak_bytes),
    }
    if rss_kb:
        out["memory_rss_proxy_kb"] = int(rss_kb)
    return out


def _serving_tput(on_tpu):
    """Continuous batching vs sequential one-by-one decode on one mixed-
    length request trace (ISSUE 3): generated tok/s + p50/p95 TTFT, both
    arms measured after a full warmup pass (compiles excluded both sides).

    Sequential arm semantics: requests all arrive at t=0 and are served
    one-by-one with ``models.generate`` — request i's TTFT is the measured
    completion time of requests 0..i-1 plus i's own measured prefill+first-
    token time (both timed directly, nothing modeled). Engine arm: all
    requests submitted at t=0, each Request clocks its own TTFT."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.models import generate
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.serving import ContinuousBatchingEngine, Request
    from paddle_tpu.serving.metrics import percentile

    if on_tpu:
        name, n_req, max_new, s, n_slots = "gpt3-350m", 32, 32, 1024, 8
        lo, hi, buckets = 64, 512, [64, 128, 256, 512]
        overrides = {}
    else:
        name, n_req, max_new, s, n_slots = "gpt2-small", 10, 8, 64, 4
        lo, hi, buckets = 3, 14, [4, 8, 16]
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (int(l),)).astype("int32")
               for l in rng.integers(lo, hi, size=n_req)]

    # -- sequential arm ------------------------------------------------------
    def seq_pass(measure_first):
        # measure_first: time prefill+1 token separately (TTFT component)
        firsts, fulls = [], []
        for p in prompts:
            x = paddle.to_tensor(p[None])
            if measure_first:
                t0 = time.perf_counter()
                generate(model, x, max_new_tokens=1)
                firsts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            generate(model, x, max_new_tokens=max_new)
            fulls.append(time.perf_counter() - t0)
        return firsts, fulls

    seq_pass(measure_first=True)  # warmup: compile every shape both forms
    firsts, fulls = seq_pass(measure_first=True)
    seq_ttft, acc = [], 0.0
    for fi, fu in zip(firsts, fulls):
        seq_ttft.append(acc + fi)
        acc += fu
    seq_tput = n_req * max_new / sum(fulls)

    # -- continuous-batching arm (SLOT layout: the r8 baseline the paged
    # arm below is judged against — kv_layout now defaults to "paged", so
    # the baseline must ask for the slot cache explicitly) ------------------
    # ONE engine: its jit caches hold the bucket/step programs, so the
    # warmup pass absorbs every compile and the measured pass replays
    eng = ContinuousBatchingEngine(model, max_seq_len=s, n_slots=n_slots,
                                   prefill_buckets=buckets, max_queue=n_req,
                                   kv_layout="slot")

    def engine_pass():
        reqs = [Request(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        eng.generate_batch(reqs)
        return reqs, time.perf_counter() - t0

    engine_pass()  # warmup: buckets + step compile
    reqs, dt = engine_pass()
    cb_ttft = [r.ttft() for r in reqs]
    cb_tput = n_req * max_new / dt

    out = {
        "serving_cb_tokens_per_sec": round(cb_tput, 2),
        "serving_seq_tokens_per_sec": round(seq_tput, 2),
        "serving_cb_speedup": round(cb_tput / seq_tput, 3),
        "serving_cb_ttft_p50_ms": round(percentile(cb_ttft, 50) * 1e3, 2),
        "serving_cb_ttft_p95_ms": round(percentile(cb_ttft, 95) * 1e3, 2),
        "serving_seq_ttft_p50_ms": round(percentile(seq_ttft, 50) * 1e3, 2),
        "serving_seq_ttft_p95_ms": round(percentile(seq_ttft, 95) * 1e3, 2),
        "serving_compiled_programs": eng.trace_count,
        "serving_trace": {"n_requests": n_req, "max_new_tokens": max_new,
                          "n_slots": n_slots, "buckets": buckets},
    }

    # -- paged arm (ISSUE 11): same trace through the block-paged KV pool --
    if on_tpu:
        page_size, px_len, px_tail, px_buckets, px_new, px_n = 32, 416, \
            64, [64, 512], 16, 32
    else:
        page_size, px_len, px_tail, px_buckets, px_new, px_n = 8, 100, 8, \
            [16, 112], 4, 16
    paged = ContinuousBatchingEngine(
        model, max_seq_len=s, n_slots=n_slots, prefill_buckets=buckets,
        max_queue=n_req, page_size=page_size)

    def paged_pass():
        preqs = [Request(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        paged.generate_batch(preqs)
        return preqs, time.perf_counter() - t0

    paged_pass()  # warmup: chunk buckets + step compile
    preqs, pdt = paged_pass()
    paged_tput = n_req * max_new / pdt
    paged_exact = all(pr.tokens == sr.tokens for pr, sr in zip(preqs, reqs))
    out.update({
        "serving_paged_tokens_per_sec": round(paged_tput, 2),
        "serving_paged_speedup_vs_slot": round(paged_tput / cb_tput, 3),
        "serving_paged_exact_vs_slot": bool(paged_exact),
        "serving_paged_compiled_programs": paged.trace_count,
        "serving_paged_compile_bound_ok": bool(
            paged.trace_count <= len(paged.chunk_buckets) + 1),
    })

    # -- paged-flash arm (ISSUE 16): same trace, the Pallas flash-decode
    # kernel in place of the XLA gather. Off-TPU the kernel runs in
    # interpret mode, so the CPU speedup is expected to be < 1 — the CPU
    # number pins greedy exactness vs the gather arm, not a win --------------
    flash = ContinuousBatchingEngine(
        model, max_seq_len=s, n_slots=n_slots, prefill_buckets=buckets,
        max_queue=n_req, page_size=page_size, attn_impl="pallas")

    def flash_pass():
        freqs = [Request(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        flash.generate_batch(freqs)
        return freqs, time.perf_counter() - t0

    flash_pass()  # warmup: chunk buckets + step compile
    freqs, fdt = flash_pass()
    flash_tput = n_req * max_new / fdt
    out.update({
        "serving_paged_flash_tokens_per_sec": round(flash_tput, 2),
        "serving_paged_flash_speedup_vs_gather": round(
            flash_tput / paged_tput, 3),
        "serving_paged_flash_exact_vs_gather": bool(all(
            fr.tokens == pr.tokens for fr, pr in zip(freqs, preqs))),
        "serving_paged_flash_compiled_programs": flash.trace_count,
        "serving_paged_flash_interpret": not on_tpu,
    })

    # secondary 1: per-stream KV HBM — live pages x page bytes vs the slot
    # layout's whole-row share, sampled with every slot active mid-decode
    meter = ContinuousBatchingEngine(
        model, max_seq_len=s, n_slots=n_slots, prefill_buckets=buckets,
        max_queue=n_req, page_size=page_size, prefix_sharing=False)
    meter.generate_batch(
        [Request(p, max_new_tokens=2) for p in prompts[:n_slots]])  # warm
    mreqs = [meter.submit(Request(p, max_new_tokens=max_new))
             for p in prompts[:n_slots]]
    meter.step_once()
    per_stream = meter.kv_bytes_per_stream() or 0.0
    live_pages = max((len(getattr(r, "_pages", [])) for r in mreqs),
                     default=0)
    slot_stream_bytes = (2 * cfg.num_layers * cfg.num_attention_heads
                         * s * cfg.head_dim * 4)  # float32 slot row pair
    out.update({
        "kv_hbm_per_stream_bytes": int(per_stream),
        "kv_hbm_per_stream_slot_bytes": int(slot_stream_bytes),
        "kv_hbm_per_stream_ok": bool(
            per_stream <= live_pages * meter.page_bytes + meter.page_bytes),
    })
    meter.run_until_idle()

    # secondary 2: shared-system-prompt TTFT — every request carries the
    # same long prefix; with radix sharing the repeats skip that prefill.
    # The CPU arm uses a model big enough that prefill COMPUTE dominates
    # host dispatch, so the hit-vs-nohit margin is signal, not noise
    if on_tpu:
        px_model = model
    else:
        px_cfg = gpt_config(name, hidden_dropout_prob=0.0,
                            attention_dropout_prob=0.0,
                            vocab_size=256, hidden_size=256, num_layers=4,
                            num_attention_heads=4,
                            max_position_embeddings=128)
        paddle.seed(0)
        px_model = GPTForPretraining(px_cfg)
        px_model.eval()
    px = rng.integers(0, 256, (px_len,)).astype("int32")
    px_prompts = [np.concatenate(
        [px, rng.integers(0, 256, (int(t),)).astype("int32")])
        for t in rng.integers(1, px_tail + 1, size=px_n)]

    def prefix_ttft_p50(sharing):
        e = ContinuousBatchingEngine(
            px_model, max_seq_len=px_buckets[-1],
            n_slots=n_slots, prefill_buckets=px_buckets,
            max_queue=2 * px_n, page_size=page_size,
            prefix_sharing=sharing)
        # warm BOTH chunk buckets + the step (and, sharing arm, seed the
        # radix tree) so the measured pass replays compiled programs only
        e.generate_batch([Request(px_prompts[0], max_new_tokens=px_new),
                          Request(px_prompts[0][:8], max_new_tokens=1)])
        ttfts = []
        for p in px_prompts:
            r = e.submit(Request(p, max_new_tokens=px_new))
            e.run_until_idle()
            ttfts.append(r.ttft())
        hit_rate = (e.page_state().get("prefix_hits", 0)
                    / max(e.page_state().get("prefix_queries", 1), 1))
        return percentile(ttfts, 50), hit_rate

    hit_p50, hit_rate = prefix_ttft_p50(True)
    nohit_p50, _ = prefix_ttft_p50(False)
    out.update({
        "prefix_hit_ttft_p50_ms": round(hit_p50 * 1e3, 2),
        "prefix_nohit_ttft_p50_ms": round(nohit_p50 * 1e3, 2),
        "prefix_hit_ttft_improved": bool(hit_p50 < nohit_p50),
        "prefix_hit_rate": round(hit_rate, 3),
        "serving_paged_trace": {
            "page_size": page_size, "prefix_len": px_len,
            "chunk_buckets": list(paged.chunk_buckets)},
    })
    return out


def _int8_kv(on_tpu):
    """Int8 paged KV (ISSUE 18): the same mixed-length trace through the
    quantized pool vs the fp pool — per-stream KV HBM (sampled mid-decode
    with every slot live), the page-bytes ratio the admission gate prices,
    and the pinned greedy-divergence certificate. The acceptance bound:
    int8 per-stream bytes <= 55% of the fp layout's."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.serving import ContinuousBatchingEngine, Request

    if on_tpu:
        name, n_req, max_new, s, n_slots = "gpt3-350m", 16, 16, 1024, 8
        lo, hi, buckets, page_size = 64, 512, [64, 128, 256, 512], 32
        overrides = {}
    else:
        name, n_req, max_new, s, n_slots = "gpt2-small", 8, 6, 64, 4
        lo, hi, buckets, page_size = 3, 14, [4, 8, 16], 8
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(18)
    prompts = [rng.integers(0, cfg.vocab_size, (int(l),)).astype("int32")
               for l in rng.integers(lo, hi, size=n_req)]

    def run(kv_dtype):
        kw = {"kv_dtype": kv_dtype} if kv_dtype else {}
        eng = ContinuousBatchingEngine(
            model, max_seq_len=s, n_slots=n_slots, prefill_buckets=buckets,
            max_queue=n_req, page_size=page_size, prefix_sharing=False, **kw)
        eng.generate_batch(
            [Request(p, max_new_tokens=2) for p in prompts[:n_slots]])  # warm
        live = [eng.submit(Request(p, max_new_tokens=max_new))
                for p in prompts[:n_slots]]
        eng.step_once()
        per_stream = eng.kv_bytes_per_stream() or 0.0
        eng.run_until_idle()
        reqs = [Request(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        eng.generate_batch(reqs)
        dt = time.perf_counter() - t0
        del live
        return eng, per_stream, reqs, n_req * max_new / dt

    fp, fp_stream, fp_reqs, fp_tput = run(None)
    q, q_stream, q_reqs, q_tput = run("int8")
    div = sum(int(a != b) for qr, fr in zip(q_reqs, fp_reqs)
              for a, b in zip(qr.tokens, fr.tokens))
    tot = sum(len(r.tokens) for r in fp_reqs)
    ratio = q_stream / fp_stream if fp_stream else 0.0
    return {
        "int8_kv_hbm_per_stream_bytes": int(q_stream),
        "int8_kv_hbm_per_stream_fp_bytes": int(fp_stream),
        "int8_kv_hbm_stream_ratio": round(ratio, 4),
        "int8_kv_hbm_ratio_ok": bool(0.0 < ratio <= 0.55),
        "int8_kv_page_bytes_ratio": round(q.page_bytes / fp.page_bytes, 4),
        "int8_kv_tokens_per_sec": round(q_tput, 2),
        "int8_kv_fp_tokens_per_sec": round(fp_tput, 2),
        "int8_kv_greedy_divergence_rate": round(div / tot, 4),
        "int8_kv_trace": {"n_requests": n_req, "max_new_tokens": max_new,
                          "page_size": page_size, "n_slots": n_slots},
    }


def _spec_decode_tput(on_tpu):
    """Speculative decoding (ISSUE 18): the same trace through the plain
    paged engine and the spec engine under self-speculation (draft ==
    target), where every greedy proposal verifies — so acceptance_rate
    and accepted_per_verify measure the real propose/verify machinery at
    its acceptance ceiling, and exactness vs the plain arm is the replay
    certificate. The acceptance criterion: accepted_per_verify > 1 (each
    batched verify emits more than one token). Off-TPU the draft re-runs
    the full target per proposed token, so tok/s is NOT expected to beat
    the plain arm — the win claim is TPU-arm only."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.serving import (
        ContinuousBatchingEngine,
        Request,
        SpecDecodeConfig,
    )

    if on_tpu:
        name, n_req, max_new, s, n_slots, k = "gpt3-350m", 16, 24, 1024, 8, 4
        lo, hi, buckets, page_size = 64, 512, [64, 128, 256, 512], 32
        overrides = {}
    else:
        name, n_req, max_new, s, n_slots, k = "gpt2-small", 8, 8, 64, 4, 3
        lo, hi, buckets, page_size = 3, 14, [4, 8, 16], 8
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(18)
    prompts = [rng.integers(0, cfg.vocab_size, (int(l),)).astype("int32")
               for l in rng.integers(lo, hi, size=n_req)]

    def run(spec):
        kw = {"spec_decode": SpecDecodeConfig(model, k=k)} if spec else {}
        eng = ContinuousBatchingEngine(
            model, max_seq_len=s, n_slots=n_slots, prefill_buckets=buckets,
            max_queue=n_req, page_size=page_size, **kw)

        def one_pass():
            reqs = [Request(p, max_new_tokens=max_new) for p in prompts]
            t0 = time.perf_counter()
            eng.generate_batch(reqs)
            return reqs, time.perf_counter() - t0

        one_pass()  # warmup: chunk buckets + (draft/verify or step) compile
        reqs, dt = one_pass()
        return eng, reqs, n_req * max_new / dt

    plain_eng, plain_reqs, plain_tput = run(False)
    spec_eng, spec_reqs, spec_tput = run(True)
    sd = spec_eng.metrics.snapshot()["spec_decode"]
    return {
        "spec_decode_tokens_per_sec": round(spec_tput, 2),
        "spec_decode_plain_tokens_per_sec": round(plain_tput, 2),
        "spec_decode_speedup_vs_plain": round(spec_tput / plain_tput, 3),
        "spec_decode_acceptance_rate": round(sd["acceptance_rate"] or 0.0, 4),
        "spec_decode_accepted_per_verify": round(
            sd["accepted_per_verify"] or 0.0, 4),
        "spec_decode_accepted_per_verify_ok": bool(
            (sd["accepted_per_verify"] or 0.0) > 1.0),
        "spec_decode_exact_vs_plain": bool(all(
            sr.tokens == pr.tokens
            for sr, pr in zip(spec_reqs, plain_reqs))),
        "spec_decode_compiled_programs": dict(spec_eng._spec.trace_counts),
        "spec_decode_trace": {"k": k, "n_requests": n_req,
                              "max_new_tokens": max_new, "n_slots": n_slots},
    }


def _kernel_speedups(on_tpu, reps=10):
    """Per-kernel microbench (ISSUE 16): each r20 Pallas kernel against a
    jitted XLA implementation of the same math, both arms compiled and
    warmed, median of ``reps``. Off-TPU the kernels execute in Pallas
    INTERPRET mode, which loses to XLA by construction — the CPU arm
    pins lineage + wiring (both arms run, finite times, same outputs),
    and only the TPU arm's speedup is a performance claim."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention_reference,
        paged_flash_attention,
    )
    from paddle_tpu.ops.pallas.softmax_ce import (
        softmax_ce_loss,
        softmax_ce_reference,
    )

    rng = np.random.default_rng(0)

    def med_ms(fn, *args):
        jax.block_until_ready(fn(*args))  # compile/warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2] * 1e3

    # paged decode attention: one tick over a half-full page table
    if on_tpu:
        b, h, d, ps, mp, n_pages = 8, 16, 128, 32, 16, 512
    else:
        b, h, d, ps, mp, n_pages = 4, 4, 32, 8, 6, 64
    q = jnp.asarray(rng.normal(size=(b, h, 1, d)), jnp.float32)
    pk = jnp.asarray(rng.normal(size=(n_pages, h, ps, d)), jnp.float32)
    pv = jnp.asarray(rng.normal(size=(n_pages, h, ps, d)), jnp.float32)
    pages = jnp.asarray(
        rng.integers(1, n_pages, (b, mp)).astype("int32"))
    pos = jnp.asarray(rng.integers(ps, (mp - 1) * ps, (b,)).astype("int32"))

    flash = jax.jit(lambda q, pk, pv: paged_flash_attention(
        q, pk, pv, pages, pos, page_size=ps))
    gather = jax.jit(lambda q, pk, pv: paged_attention_reference(
        q, pk, pv, pages, pos, page_size=ps))
    pa_pl = med_ms(flash, q, pk, pv)
    pa_xla = med_ms(gather, q, pk, pv)

    # fused softmax-CE head fwd+bwd vs the jnp log-softmax reference
    if on_tpu:
        n, t, v = 8, 1024, 50304
    else:
        n, t, v = 4, 32, 512
    logits = jnp.asarray(rng.normal(size=(n, t, v)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (n, t)).astype("int32"))

    ce_pl = jax.jit(jax.grad(lambda x: jnp.sum(softmax_ce_loss(x, labels))))
    ce_xla = jax.jit(jax.grad(
        lambda x: jnp.sum(softmax_ce_reference(x, labels))))
    ce_pl_ms = med_ms(ce_pl, logits)
    ce_xla_ms = med_ms(ce_xla, logits)

    return {
        "kernel_paged_attn_pallas_ms": round(pa_pl, 3),
        "kernel_paged_attn_xla_ms": round(pa_xla, 3),
        "kernel_paged_attn_speedup": round(pa_xla / pa_pl, 3),
        "kernel_softmax_ce_pallas_ms": round(ce_pl_ms, 3),
        "kernel_softmax_ce_xla_ms": round(ce_xla_ms, 3),
        "kernel_softmax_ce_speedup": round(ce_xla_ms / ce_pl_ms, 3),
        "kernel_bench_interpret": not on_tpu,
    }


def _overload_shed(on_tpu):
    """Overload-protection secondary (ISSUE 8): one engine under 2×
    sustained synthetic overload, shed-policy ON vs OFF (both arms on the
    same warmed model). Tick-driven: each request occupies a slot for
    ~max_new ticks, so the service rate is n_slots/max_new requests per
    tick and arrivals accumulate at exactly twice that. Reports goodput
    (completed tokens/s over the loaded window), p99 TTFT of ADMITTED
    (completed) requests in each arm, the unloaded p99 baseline, and the
    shed/silent-drop counts (the acceptance criterion says sheds are
    visible 429/503-style failures, silent drops are zero, and admitted
    p99 TTFT with shedding stays within 3× unloaded)."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    LoadShedPolicy, Request)
    from paddle_tpu.serving.metrics import percentile

    if on_tpu:
        name, s, n_slots, max_new, rounds = "gpt3-350m", 512, 8, 32, 240
        overrides = {}
    else:
        name, s, n_slots, max_new, rounds = "gpt2-small", 64, 4, 8, 200
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (6,)).astype("int32")

    def build(shed):
        return ContinuousBatchingEngine(
            model, max_seq_len=s, n_slots=n_slots, max_queue=4096,
            shed_policy=LoadShedPolicy(sustain_s=0.01) if shed else None)

    def unloaded_p99(eng, batches=4):
        # first pass absorbs the prefill/step compiles; the MEASURED
        # baseline then pools several warmed batches — a p99 over one
        # batch of n_slots samples is just that batch's max, and a
        # single scheduler hiccup would poison the acceptance ratio
        samples = []
        for i in range(batches + 1):
            reqs = [eng.submit(prompt, max_new_tokens=max_new)
                    for _ in range(eng.n_slots)]
            while any(not r.done for r in reqs):
                eng.step_once()
            if i > 0:
                samples.extend(r.ttft() for r in reqs)
        return percentile(samples, 99)

    def overload_arm(eng):
        rate = 2.0 * eng.n_slots / max_new
        reqs, acc = [], 0.0
        t0 = time.perf_counter()
        for _ in range(rounds):
            acc += rate
            while acc >= 1.0:
                reqs.append(eng.submit(prompt, max_new_tokens=max_new))
                acc -= 1.0
            eng.step_once()
        # BOUNDED drain: a request removed from the queue without being
        # finished (the silent-drop regression this metric exists to
        # catch) leaves step_once with nothing to do forever — break on
        # sustained idle and report the leftovers instead of hanging
        idle = 0
        while any(not r.done for r in reqs) and idle < 1000:
            idle = 0 if eng.step_once() else idle + 1
        dt = time.perf_counter() - t0
        done = [r for r in reqs if r.state == Request.DONE]
        failed = [r for r in reqs if r.state == Request.FAILED]
        silent = [r for r in reqs if not r.done]
        admitted_killed = [r for r in failed if r.tokens]
        return {
            "submitted": len(reqs),
            "completed": len(done),
            "shed": len(failed),
            "silent_drops": len(silent),
            "admitted_killed_by_shed": len(admitted_killed),
            "goodput_tokens_per_sec": round(
                sum(len(r.tokens) for r in done) / dt, 2),
            "admitted_ttft_p99_ms": round(
                percentile([r.ttft() for r in done], 99) * 1e3, 2),
        }

    eng_shed = build(shed=True)
    base_p99 = unloaded_p99(eng_shed)  # warmed: compiles out of the way
    shed_arm = overload_arm(eng_shed)
    eng_noshed = build(shed=False)
    unloaded_p99(eng_noshed)  # warm this engine's caches identically
    noshed_arm = overload_arm(eng_noshed)
    ratio = shed_arm["admitted_ttft_p99_ms"] / (base_p99 * 1e3)
    return {
        "overload_unloaded_ttft_p99_ms": round(base_p99 * 1e3, 2),
        "overload_shed_arm": shed_arm,
        "overload_noshed_arm": noshed_arm,
        "overload_shed_ttft_ratio_vs_unloaded": round(ratio, 3),
        "overload_shed_ttft_within_3x": bool(ratio <= 3.0),
        "overload_zero_silent_drops": bool(
            shed_arm["silent_drops"] == 0
            and shed_arm["admitted_killed_by_shed"] == 0),
    }


def _router_failover(on_tpu):
    """Serving-router chaos secondary (ISSUE 6): two engine replicas behind
    the health-checked router, the loaded replica killed abruptly (no
    drain — the in-process equivalent of a replica SIGKILL) while a queued
    request streams. Records recovery time (kill → first token of the
    failed-over request on the survivor) and how many queued requests were
    dropped (the acceptance criterion says zero)."""
    import gc
    import threading

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.serving import (ContinuousBatchingEngine, Request,
                                    ServingRouter, ServingServer)

    if on_tpu:
        overrides = {}
        name, max_new, s = "gpt3-350m", 64, 512
    else:
        name, max_new, s = "gpt2-small", 48, 128
        overrides = dict(vocab_size=64, hidden_size=16, num_layers=1,
                         num_attention_heads=2, max_position_embeddings=128)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)

    def replica():
        eng = ContinuousBatchingEngine(model, max_seq_len=s, n_slots=1,
                                       prefill_buckets=[8], max_queue=16)
        return ServingServer(eng).start()

    servers = {srv.addr: srv for srv in (replica(), replica())}
    addrs = list(servers)
    prompt = rng.integers(0, cfg.vocab_size, (4,)).tolist()
    try:
        with ServingRouter(addrs, health_interval_s=0.1, cooldown_s=30.0,
                           request_timeout=10.0) as router:
            router.check_health()
            # warm both replicas: compiles out of the recovery-time path
            for rr in [router.submit(prompt, max_new_tokens=2)
                       for _ in range(2)]:
                router.wait(rr, timeout=600)
            router.check_health()
            # n_slots=1: each replica holds one runner + queued extras
            rrs = [router.submit(prompt, max_new_tokens=max_new)
                   for _ in range(4)]
            placed = {}
            for rr in rrs:
                placed.setdefault(rr.replica_addr, []).append(rr)
            victim = next(a for a, v in placed.items() if len(v) >= 2)
            queued = placed[victim][-1]
            tokens = []
            thread = threading.Thread(
                target=lambda: tokens.extend(router.stream(queued)))
            thread.start()
            time.sleep(0.05)
            t_kill = time.perf_counter()
            servers[victim].kill()
            thread.join(600)
            # None = the kill race did not leave a queued request to
            # re-home (it had already started generating) — recording
            # thread-join time as "recovery" would be meaningless
            recovery_s = (
                round(queued.failover_first_token_at - t_kill, 4)
                if queued.failover_first_token_at is not None else None)
            for rr in rrs:
                try:
                    router.wait(rr, timeout=600)
                except TimeoutError:
                    pass
            dropped = sum(1 for rr in rrs
                          if rr.state == Request.FAILED and not rr.tokens)
            snap = router.snapshot()
            return {
                "router_failover_recovery_s": recovery_s,
                "router_failover_dropped_requests": dropped,
                "router_failover_resubmits": snap["resubmits"],
                "router_failover_inflight_failures":
                    snap["inflight_failures"],
                "router_failover_streamed_tokens": len(tokens),
            }
    finally:
        for srv in servers.values():
            try:
                srv.kill()
            except Exception:
                pass


def _stream_resurrection(on_tpu):
    """Zero-loss stream secondary (ISSUE 17): two engine replicas behind
    the router, the replica holding an IN-FLIGHT stream killed abruptly
    after it has streamed tokens. The router resurrects the stream on the
    survivor as a continuation join; records how many observed tokens the
    resurrection preserved, the recovery time (kill → first CONTINUED
    token on the survivor) and the duplicate count (the zero-loss
    acceptance says zero dropped AND zero duplicated)."""
    import gc
    import threading

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.serving import (ContinuousBatchingEngine, Request,
                                    ServingRouter, ServingServer)

    if on_tpu:
        overrides = {}
        name, max_new, s = "gpt3-350m", 64, 512
    else:
        name, max_new, s = "gpt2-small", 48, 128
        overrides = dict(vocab_size=64, hidden_size=16, num_layers=1,
                         num_attention_heads=2, max_position_embeddings=128)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)

    def replica():
        eng = ContinuousBatchingEngine(model, max_seq_len=s, n_slots=1,
                                       prefill_buckets=[8], max_queue=16)
        return ServingServer(eng).start()

    servers = {srv.addr: srv for srv in (replica(), replica())}
    addrs = list(servers)
    prompt = rng.integers(0, cfg.vocab_size, (4,)).tolist()
    try:
        with ServingRouter(addrs, health_interval_s=0.1, cooldown_s=30.0,
                           request_timeout=10.0) as router:
            router.check_health()
            # warm both replicas: compiles out of the recovery-time path
            for rr in [router.submit(prompt, max_new_tokens=2)
                       for _ in range(2)]:
                router.wait(rr, timeout=600)
            router.check_health()
            rr = router.submit(prompt, max_new_tokens=max_new,
                               temperature=0.9, seed=17)
            victim = rr.replica_addr
            got = []
            thread = threading.Thread(
                target=lambda: got.extend(router.stream(rr)))
            thread.start()
            # kill only after the stream is visibly mid-generation: the
            # resurrection path (not the queued-resubmit path) must run
            deadline = time.perf_counter() + 600
            while len(got) < 5:
                if time.perf_counter() > deadline:
                    raise TimeoutError("stream never reached 5 tokens")
                time.sleep(0.002)
            preserved = len(rr.tokens)
            t_kill = time.perf_counter()
            servers[victim].kill()
            thread.join(600)
            snap = router.snapshot()
            recovery_s = (
                round(rr.failover_first_token_at - t_kill, 4)
                if rr.failover_first_token_at is not None else None)
            return {
                "stream_resurrection_recovery_s": recovery_s,
                "stream_resurrection_tokens_preserved": preserved,
                # got is the caller-visible stream across the death;
                # equality with the settled transcript means zero
                # duplicated AND zero dropped tokens
                "stream_resurrection_duplicate_tokens":
                    len(got) - len(rr.tokens),
                "stream_resurrection_dropped_tokens":
                    max_new - len(got),
                "stream_resurrection_resurrections":
                    snap["resurrections"],
            }
    finally:
        for srv in servers.values():
            try:
                srv.kill()
            except Exception:
                pass


def _store_failover(on_tpu):
    """Coordination-store chaos secondary (ISSUE 12): a 3-replica quorum
    store with a heartbeating client, the LEADER killed abruptly.
    Records recovery time (kill → first successful heartbeat through the
    surviving replicas — the acceptance bound is lease TTL + one election
    round), acknowledged-writes-lost across the failover (must be 0), and
    how many elections the cluster ran. Identical on both arms (pure
    host/store path, no device)."""
    del on_tpu  # store plane is device-independent
    from paddle_tpu.distributed.fleet.elastic.manager import _TcpStore
    from paddle_tpu.distributed.fleet.utils.replicated_store import (
        ReplicatedStoreCluster,
    )

    lease_ttl = 0.5
    with ReplicatedStoreCluster(3, lease_ttl=lease_ttl) as cl:
        lead = cl.leader(timeout=30)
        epoch0 = lead.epoch
        st = _TcpStore(cl.addr_spec, "benchjob", ttl=2.5, retries=5)
        st.register("node_a", "1.2.3.4:1")
        # acknowledged writes: every one of these returned success to the
        # client, so every one must survive the failover
        acked = {}
        for i in range(50):
            st.put(f"key{i}", f"val{i}")
            acked[f"key{i}"] = f"val{i}"
        st.heartbeat("node_a")  # warm: dials + leader discovery done
        t_kill = time.perf_counter()
        lead.kill()
        st.heartbeat("node_a")  # blocks through redirects + election
        recovery_s = time.perf_counter() - t_kill
        new = cl.leader(timeout=30)
        survivors = {k: (v or "") for k, (v, _a) in st.scan().items()}
        lost = sum(1 for k, v in acked.items() if survivors.get(k) != v)
        return {
            "store_failover_recovery_s": round(recovery_s, 4),
            "store_failover_acked_writes_lost": lost,
            "store_failover_elections": int(new.epoch - epoch0),
            "store_failover_lease_ttl_s": lease_ttl,
            "store_failover_within_bound": bool(
                recovery_s <= lease_ttl + 1.0),
        }


def _ckpt_durability(on_tpu):
    """Replicated checkpoint data plane secondary (ISSUE 15): (a) steady-
    state replication tax — per-step wall time of a 2-rank elastic dp
    cohort with the replicated plane (per-rank shard snapshots + K=1 peer
    pushes + manifest commits) vs the replication-OFF single-writer path,
    on identical workloads (`ckpt_replication_overhead_ok` bounds it at
    2% of step time); (b) disk-loss recovery — SIGKILL-equivalent injected
    kill AND directory wipe of one of 3 ranks mid-run, a replacement rank
    with an empty disk rejoins from peer replicas; recovery_s = death →
    first post-recovery step; (c) `ckpt_acked_snapshots_lost` — every
    manifest ever committed must still reassemble CRC-clean from the
    survivors afterwards (must be 0). Identical on both arms (pure
    host/store path, no device)."""
    del on_tpu  # checkpoint plane is device-independent
    import contextlib
    import os
    import shutil
    import tempfile
    import threading

    from paddle_tpu.distributed.fleet.elastic.manager import (
        ElasticManager,
        _TcpStore,
    )
    from paddle_tpu.distributed.fleet.utils.http_server import KVServer
    from paddle_tpu.resilience import (
        DurabilityConfig,
        FaultSchedule,
        InjectedDeath,
    )
    from paddle_tpu.resilience.durability import CheckpointDataPlane
    from paddle_tpu.resilience.elastic_trainer import ElasticDPTrainer

    W_STAR = np.arange(32.0 * 16).reshape(32, 16) / 100.0

    def grad_fn(params, step, rank, world):
        rng = np.random.default_rng(700000 + 1000 * step + 10 * world + rank)
        X = rng.standard_normal((16, 32))
        E = X @ params["w"] - X @ W_STAR
        return float((E ** 2).mean()), {"w": 2 * X.T @ E / E.size}

    def init_params():
        return {"w": np.zeros((32, 16))}

    def durability_cfg():
        return DurabilityConfig(replicas=1, push_confirm_timeout_s=0.25,
                                manifest_timeout_s=20.0)

    def run_cohort(n, total, base, replicated, save_every=2,
                   victim_step=None, ttl=1.2):
        srv = KVServer().start()
        addr = f"127.0.0.1:{srv.port}"
        stamps = {}   # node -> [(wall, step, world)]
        events = {}   # node -> [(wall, message)]
        errors = {}
        threads = {}

        def start_rank(idx, node, schedule=None, wait_world=None):
            stamps.setdefault(node, [])
            events.setdefault(node, [])

            def run():
                st = _TcpStore(addr, "benchckpt", ttl=ttl, retries=1)
                mgr = ElasticManager(store=st)
                mgr.endpoint = f"127.0.0.1:{7900 + idx}"
                mgr.node_id = node
                ckpt_dir = (os.path.join(base, node) if replicated
                            else os.path.join(base, "shared"))
                tr = ElasticDPTrainer(
                    mgr, ckpt_dir, grad_fn, init_params, lr=0.2,
                    momentum=0.9, min_ranks=1, save_every=save_every,
                    step_timeout=60, rendezvous_timeout=60,
                    durability=durability_cfg() if replicated else None,
                    on_step=lambda s, w, _l: stamps[node].append(
                        (time.perf_counter(), s, w)),
                    on_event=lambda m: events[node].append(
                        (time.perf_counter(), m)))
                ctx = (schedule.scope() if schedule is not None
                       else contextlib.nullcontext())
                try:
                    with ctx:
                        tr.run(total, wait_world=wait_world)
                except InjectedDeath:
                    stamps[node].append((time.perf_counter(), -1, 0))
                    events[node].append((time.perf_counter(), "DIED"))
                    return
                except Exception as e:  # pragma: no cover - surfaced below
                    errors[node] = f"{type(e).__name__}: {e}"
                    return
                tr.close()

            t = threading.Thread(target=run, daemon=True)
            threads[node] = t
            t.start()

        try:
            for i in range(n):
                start_rank(i, f"node_{i}",
                           schedule=(FaultSchedule(seed=17).add(
                               "ckpt.disk.loss", "kill",
                               match={"step": victim_step})
                               if victim_step is not None and i == n - 1
                               else None),
                           wait_world=n)
            if victim_step is not None:
                victim = f"node_{n - 1}"
                deadline = time.monotonic() + 120
                while (time.monotonic() < deadline
                       and not any(m == "DIED"
                                   for _t, m in events[victim])):
                    time.sleep(0.01)
                start_rank(n, f"node_{n}", wait_world=1)
            for t in threads.values():
                t.join(240)
            manifests = {}
            if replicated:
                manifests = dict(_TcpStore(addr, "benchckpt", ttl=5.0,
                                           retries=1).scan(prefix="ckmf:"))
        finally:
            srv.stop()
        if errors:
            raise RuntimeError(f"bench cohort rank failures: {errors}")
        return stamps, events, manifests

    def median_step_s(stamps, node="node_0", skip=2):
        ts = [w for w, _s, _v in stamps[node]]
        diffs = [b - a for a, b in zip(ts[:-1], ts[1:])][skip:]  # warmup off
        diffs.sort()
        return diffs[len(diffs) // 2]

    STEPS = 24
    with tempfile.TemporaryDirectory() as base_on:
        on_stamps, _ev, _mf = run_cohort(2, STEPS, base_on, replicated=True)
        step_on = median_step_s(on_stamps)
    with tempfile.TemporaryDirectory() as base_off:
        off_stamps, _ev, _mf = run_cohort(2, STEPS, base_off,
                                          replicated=False)
        step_off = median_step_s(off_stamps)
    overhead = step_on / step_off - 1.0

    # disk-loss chaos: kill + wipe one of 3 ranks, empty-disk replacement
    base_chaos = tempfile.mkdtemp()
    try:
        stamps, events, manifests = run_cohort(
            3, 12, base_chaos, replicated=True, save_every=1,
            victim_step=6)
        victim = "node_2"
        t_death = next(w for w, s, _v in stamps[victim] if s == -1)
        # recovery end = node_0's first completed step AFTER its
        # post-death restore event. A step already in flight when the
        # victim died can land after t_death, which would credit recovery
        # before detection/rendezvous/restore even began.
        t_restore = min((t for t, m in events["node_0"]
                         if t > t_death and m.startswith("restore:")),
                        default=float("nan"))
        t_rec = min((w for w, _s, _v in stamps["node_0"] if w > t_restore),
                    default=float("nan"))
        recovery_s = t_rec - t_death
        # acked-durability audit: every committed manifest must still
        # assemble from the survivors (victim's disk is gone)
        lost = 0
        n_manifests = len(manifests)
        srv = KVServer().start()
        planes = []
        try:
            vstore = _TcpStore(f"127.0.0.1:{srv.port}", "verify",
                               ttl=5.0, retries=1)
            for k, (v, _age) in manifests.items():
                vstore.put(k, v)
            for node in ("node_0", "node_1", "node_3"):
                d = os.path.join(base_chaos, node)
                if os.path.exists(d):
                    planes.append(CheckpointDataPlane(
                        _TcpStore(f"127.0.0.1:{srv.port}", "verify",
                                  ttl=5.0, retries=1), node, d,
                        durability_cfg()))
            with tempfile.TemporaryDirectory() as vdir:
                verifier = CheckpointDataPlane(
                    _TcpStore(f"127.0.0.1:{srv.port}", "verify",
                              ttl=5.0, retries=1), "verifier", vdir,
                    durability_cfg())
                planes.append(verifier)
                for s in verifier.manifest_steps():
                    try:
                        verifier.load_step(s, timeout=15)
                    except Exception:
                        lost += 1
        finally:
            for p in planes:
                p.close()
            srv.stop()
    finally:
        shutil.rmtree(base_chaos, ignore_errors=True)

    return {
        "ckpt_replication_step_seconds": round(step_on, 5),
        "ckpt_baseline_step_seconds": round(step_off, 5),
        "ckpt_replication_overhead_frac": round(overhead, 4),
        "ckpt_replication_overhead_ok": bool(overhead < 0.02),
        "ckpt_disk_loss_recovery_s": round(recovery_s, 3),
        "ckpt_acked_snapshots_lost": lost,
        "ckpt_manifests_committed": n_manifests,
    }


def _eager_jit_speedup():
    """Eager GPT-block fwd+bwd: op-by-op dispatch vs the transparent
    per-layer jit cache (FLAGS_eager_layer_jit) — SURVEY §7 hard-part 4."""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.models.gpt import GPTDecoderLayer, gpt_config

    cfg = gpt_config("gpt3-350m", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    paddle.seed(0)
    block = GPTDecoderLayer(cfg)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(
        rng.standard_normal((8, 1024, cfg.hidden_size)).astype("float32"))

    def fwd_bwd():
        out = block(x)
        loss = (out * out).mean()
        loss.backward()
        for p in block.parameters():
            p.clear_grad()
        return loss

    results = {}
    try:
        # >= 10 iterations BOTH arms (VERDICT r4 weak #6: 3-iteration slow
        # arms swung 27x..68x between rounds); median of 3 reps
        for mode, iters in (("false", 10), ("force", 30)):
            paddle.set_flags({"FLAGS_eager_layer_jit": mode})
            float(np.asarray(fwd_bwd()._data))  # compile/warm
            reps = []
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(iters):
                    loss = fwd_bwd()
                float(np.asarray(loss._data))
                reps.append((time.perf_counter() - t0) / iters)
            results[mode] = sorted(reps)[1]
    finally:
        paddle.set_flags({"FLAGS_eager_layer_jit": "true"})
    return results["false"] / results["force"]


def main():
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    peak = _peak_flops_bf16(dev)

    def mfu(tok_per_sec, n_params, cfg, seq):
        flops_per_token = 6 * n_params + 6 * cfg.num_layers * seq * cfg.hidden_size
        return tok_per_sec * flops_per_token / peak

    if on_tpu:
        seq = 1024
        secondary = {}
        # north star: GPT-3 1.3B (BASELINE.json config #4), b4 + core_attn
        # remat every 3rd block — r5's flash-saveable checkpoint_name tags
        # mean the remat'd blocks re-run dots but NOT the flash forward
        # (15.1k vs 14.6k tok/s at full+i3, benchmarks/sweep_r5.jsonl)
        tput, n_params, cfg = _train_tput(
            "gpt3-1.3b", 4, seq, 10, 2, True, recompute=True,
            granularity="core_attn", moment_dtype="bfloat16",
            recompute_interval=3)
        metric = "gpt3_1.3b_train_tokens_per_sec_chip"
        try:
            t760, n760, c760 = _train_tput("gpt3-760m", 8, seq, 10, 2, True)
            secondary["gpt3_760m_tokens_per_sec_chip"] = round(t760, 2)
            secondary["gpt3_760m_mfu"] = round(mfu(t760, n760, c760, seq), 4)
        except Exception as e:  # pragma: no cover - device dependent
            secondary["gpt3_760m_tokens_per_sec_chip"] = f"failed: {type(e).__name__}"
        try:
            t350, n350, c350 = _train_tput("gpt3-350m", 8, seq, 20, 2, True)
            secondary["gpt3_350m_tokens_per_sec_chip"] = round(t350, 2)
            secondary["gpt3_350m_mfu"] = round(mfu(t350, n350, c350, seq), 4)
        except Exception as e:  # pragma: no cover - device dependent
            secondary["gpt3_350m_tokens_per_sec_chip"] = f"failed: {type(e).__name__}"
        try:
            secondary["eager_layer_jit_block_speedup"] = round(
                _eager_jit_speedup(), 2)
        except Exception as e:  # pragma: no cover - device dependent
            secondary["eager_layer_jit_block_speedup"] = f"failed: {type(e).__name__}"
        try:
            # resilience: sentinel-enabled vs disabled step time (ISSUE 2 —
            # the overhead claim is tracked in the round artifact)
            secondary.update(_sentinel_overhead(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["sentinel_overhead_frac"] = f"failed: {type(e).__name__}"
        try:
            # serving: continuous batching vs sequential decode (ISSUE 3)
            secondary.update(_serving_tput(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["serving_cb_tokens_per_sec"] = f"failed: {type(e).__name__}"
        try:
            # quantization: int8 paged-KV HBM + divergence (ISSUE 18)
            secondary.update(_int8_kv(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["int8_kv_hbm_per_stream_bytes"] = \
                f"failed: {type(e).__name__}"
        try:
            # speculative decoding vs plain paged decode (ISSUE 18)
            secondary.update(_spec_decode_tput(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["spec_decode_tokens_per_sec"] = \
                f"failed: {type(e).__name__}"
        try:
            # per-kernel Pallas-vs-XLA microbench (ISSUE 16)
            secondary.update(_kernel_speedups(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["kernel_paged_attn_speedup"] = f"failed: {type(e).__name__}"
        try:
            # static analysis: lint wall-time + finding counts (ISSUE 4)
            secondary.update(_analysis_overhead())
        except Exception as e:  # pragma: no cover - device dependent
            secondary["analysis_lint_s"] = f"failed: {type(e).__name__}"
        try:
            # concurrency doctor: host lint + lock-journal tax (ISSUE 14)
            secondary.update(_host_analysis())
        except Exception as e:  # pragma: no cover - device dependent
            secondary["host_analysis_lint_s"] = f"failed: {type(e).__name__}"
        try:
            # determinism doctor: host findings + seam coverage (ISSUE 19)
            secondary.update(_determinism_lint())
        except Exception as e:  # pragma: no cover - device dependent
            secondary["det_lint_s"] = f"failed: {type(e).__name__}"
        try:
            # Pallas kernel doctor: coverage/dtype/VMEM/drift (ISSUE 20)
            secondary.update(_kernel_lint())
        except Exception as e:  # pragma: no cover - device dependent
            secondary["kernel_lint_s"] = f"failed: {type(e).__name__}"
        try:
            # robustness: replica-kill failover recovery time (ISSUE 6)
            secondary.update(_router_failover(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["router_failover_recovery_s"] = f"failed: {type(e).__name__}"
        try:
            # robustness: in-flight stream resurrected as a continuation
            # join on replica death (ISSUE 17)
            secondary.update(_stream_resurrection(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["stream_resurrection_recovery_s"] = \
                f"failed: {type(e).__name__}"
        try:
            # observability: telemetry-plane tax on both hot paths (ISSUE 7)
            secondary.update(_observability_overhead(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["observability_trainer_overhead_frac"] = \
                f"failed: {type(e).__name__}"
        try:
            # robustness: goodput + admitted-TTFT under 2× overload,
            # shed-policy on vs off (ISSUE 8)
            secondary.update(_overload_shed(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["overload_shed_arm"] = f"failed: {type(e).__name__}"
        try:
            # robustness: coordination-store leader-kill recovery (ISSUE 12)
            secondary.update(_store_failover(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["store_failover_recovery_s"] = f"failed: {type(e).__name__}"
        try:
            # robustness: replicated checkpoint plane — replication tax +
            # disk-loss recovery + acked-durability audit (ISSUE 15)
            secondary.update(_ckpt_durability(True))
        except Exception as e:  # pragma: no cover - device dependent
            secondary["ckpt_disk_loss_recovery_s"] = f"failed: {type(e).__name__}"
        try:
            # auto-parallel planner v2 search (ISSUE 13)
            secondary.update(_planner_search(True))
        except Exception as e:  # pragma: no cover
            secondary["planner_chosen_plan"] = f"failed: {type(e).__name__}"
        try:
            # same-remat, same-accumulation A/B (VERDICT r4 weak #3): the
            # plain arm runs selective remat AND 2-step gradient merge, so
            # pipeline_step_ratio isolates the schedule machinery itself.
            # This block is the ONE round-of-record pipeline number —
            # README/PARITY must quote it verbatim (r5's bench-vs-sweep
            # 0.78/0.835 split traced to an unlogged sweep denominator).
            tp, prof = _pipeline_tput("gpt3-350m", 8, seq, profile=True)
            secondary["pipeline_step_tokens_per_sec"] = round(tp, 2)
            t350s, _, _ = _train_tput(
                "gpt3-350m", 8, seq, 20, 2, True, recompute=True,
                granularity="selective", accumulate_steps=2)
            secondary["gpt3_350m_selective_acc2_tokens_per_sec"] = round(t350s, 2)
            secondary["pipeline_step_ratio"] = round(tp / t350s, 4)
            secondary["pipeline_step_overhead"] = round(t350s / tp - 1, 4)
            if prof is not None:
                secondary["pipeline_profile"] = {
                    "per_tick_ms": {
                        k: round(v, 4)
                        for k, v in prof["per_tick_ms"]["regions"].items()
                    },
                    "per_tick_attributed_fraction": round(
                        prof["per_tick_ms"]["attributed_fraction"], 4),
                    "per_step_ms": {
                        k: round(v, 4)
                        for k, v in prof["per_step_ms"]["regions"].items()
                    },
                    "per_step_total_ms": round(
                        prof["per_step_ms"]["total"], 4),
                }
        except Exception as e:  # pragma: no cover - device dependent
            secondary["pipeline_step_tokens_per_sec"] = f"failed: {type(e).__name__}"
    else:
        seq, steps, warmup = 32, 3, 1
        tput, n_params, cfg = _train_tput("gpt2-small", 4, seq, steps, warmup, False)
        secondary = {}
        try:
            secondary.update(_sentinel_overhead(False))
        except Exception as e:  # pragma: no cover
            secondary["sentinel_overhead_frac"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_serving_tput(False))
        except Exception as e:  # pragma: no cover
            secondary["serving_cb_tokens_per_sec"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_int8_kv(False))
        except Exception as e:  # pragma: no cover
            secondary["int8_kv_hbm_per_stream_bytes"] = \
                f"failed: {type(e).__name__}"
        try:
            secondary.update(_spec_decode_tput(False))
        except Exception as e:  # pragma: no cover
            secondary["spec_decode_tokens_per_sec"] = \
                f"failed: {type(e).__name__}"
        try:
            secondary.update(_kernel_speedups(False))
        except Exception as e:  # pragma: no cover
            secondary["kernel_paged_attn_speedup"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_analysis_overhead())
        except Exception as e:  # pragma: no cover
            secondary["analysis_lint_s"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_host_analysis())
        except Exception as e:  # pragma: no cover
            secondary["host_analysis_lint_s"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_determinism_lint())
        except Exception as e:  # pragma: no cover
            secondary["det_lint_s"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_kernel_lint())
        except Exception as e:  # pragma: no cover
            secondary["kernel_lint_s"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_router_failover(False))
        except Exception as e:  # pragma: no cover
            secondary["router_failover_recovery_s"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_stream_resurrection(False))
        except Exception as e:  # pragma: no cover
            secondary["stream_resurrection_recovery_s"] = \
                f"failed: {type(e).__name__}"
        try:
            secondary.update(_observability_overhead(False))
        except Exception as e:  # pragma: no cover
            secondary["observability_trainer_overhead_frac"] = \
                f"failed: {type(e).__name__}"
        try:
            secondary.update(_overload_shed(False))
        except Exception as e:  # pragma: no cover
            secondary["overload_shed_arm"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_store_failover(False))
        except Exception as e:  # pragma: no cover
            secondary["store_failover_recovery_s"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_ckpt_durability(False))
        except Exception as e:  # pragma: no cover
            secondary["ckpt_disk_loss_recovery_s"] = f"failed: {type(e).__name__}"
        try:
            secondary.update(_planner_search(False))
        except Exception as e:  # pragma: no cover
            secondary["planner_chosen_plan"] = f"failed: {type(e).__name__}"
        metric = "gpt_tiny_train_tokens_per_sec_chip"

    payload = {
        "metric": metric,
        "value": round(tput, 2),
        "unit": "tokens/s",
        # arm tag (r15): baselines and bench-diff are arm-segregated —
        # CPU smoke values share metric names with the on-chip lineage
        # but are not comparable to it
        "arm": "tpu" if on_tpu else "cpu",
        "vs_baseline": round(mfu(tput, n_params, cfg, seq) / 0.40, 4),
        "secondary": secondary,
    }
    try:
        # bench regression watchdog (ISSUE 9): trailing self-check of this
        # round's numbers against the committed lineage baseline — the
        # same compare `python -m paddle_tpu.observability bench-diff`
        # gates CI with. Self-referential by design: the verdict rides in
        # the payload AFTER comparison, so it never compares itself.
        import os

        from paddle_tpu.observability.baseline import compare, load_baseline

        bl_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "benchmarks", "bench_baseline.json")
        # both arms self-check (r15): compare() picks the band set
        # matching the payload's arm, so a CPU smoke run is judged only
        # against the committed CPU-arm lineage
        if not os.path.exists(bl_path):
            # a round that never ran its self-check must say so — an
            # absent key would be indistinguishable from pre-r14 rounds
            secondary["bench_diff"] = "skipped (no bench_baseline.json)"
        else:
            verdict = compare(payload, load_baseline(bl_path))
            secondary["bench_diff"] = {
                "ok": verdict["ok"],
                "compared": verdict["compared"],
                "regressions": [r["describe"]
                                for r in verdict["regressions"]],
            }
    except Exception as e:  # pragma: no cover - must not void the round
        secondary["bench_diff"] = f"failed: {type(e).__name__}"
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
