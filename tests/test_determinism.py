"""Determinism doctor (ISSUE 19): PRNG key-flow lint, host-nondeterminism
rules, replay-certificate seam coverage, and the divergence bisector.

Per-rule contract (mirrors test_analysis.py): one minimal planted program
that triggers exactly that rule with the correct eqn/scope attribution,
plus a clean twin with zero findings — no rule is allowed to pass by
never firing.  The twin-certificate section is itself the coverage
artifact: the ``det-seam-coverage`` audit statically counts the
parametrized two-run identical-fired-log tests below, so every seam in
``resilience/inject.POINTS`` is replay-certified and the registry↔tests
mapping is pinned tier-1.

Pre-fix findings fixed this round (regression-pinned below):

* ``key-nonuniform`` was blind inside ``shard_map`` — jax 0.4.x lowers
  ``psum``/``all_gather`` there to ``psum2``/``all_gather_invariant``,
  which ``analysis/graph.py`` did not classify as collectives, so no
  axes were recorded and rank-divergent sampling could never be proven.
* ``det-seam-coverage`` misread the five ``store.*`` seams as dead
  registry entries — ``replicated_store.py`` fires through a local
  ``_fire`` wrapper the scanner did not treat as a fire function.
* ``det-wallclock`` false-positived on ``serving/engine.py:843`` where a
  clock value is only a telemetry-span *argument* (``record_span(dur=
  time.perf_counter() - t0)``) and the guarded branch tests span
  presence, not time.
"""
import json
import textwrap
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddle_tpu import analysis as an
from paddle_tpu.analysis import (
    AnalysisTarget,
    BisectConfig,
    Severity,
    bisect_runs,
    demo_divergence,
    diff_fired_logs,
    seam_coverage,
)
from paddle_tpu.analysis.cli import main as analysis_main
from paddle_tpu.analysis.determinism import coverage_findings, run_det_rules
from paddle_tpu.analysis.keyflow import (
    DRAWING_PRIMS,
    RANDOM_PRIMS,
    ClosureKeyRule,
    KeyDiscardRule,
    KeyReuseRule,
    NonuniformKeyRule,
)
from paddle_tpu.distributed.fleet.elastic.manager import _TcpStore
from paddle_tpu.distributed.fleet.utils.http_server import KVServer
from paddle_tpu.profiler import scope
from paddle_tpu.resilience import inject
from paddle_tpu.resilience.inject import POINTS, FaultSchedule


@pytest.fixture(autouse=True)
def _no_leftover_schedule():
    yield
    sched = inject.active_schedule()
    if sched is not None:
        sched.disarm()


def _sev(findings, severity):
    return [f for f in findings if f.severity == severity]


def _mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(np.array(jax.devices()[:8]), ("x",))


# ---------------------------------------------------------------------------
# key-flow rule: key-reuse
# ---------------------------------------------------------------------------
class TestKeyReuse:
    def test_double_draw_of_one_key_flags_high_with_scope(self):
        def f(k):
            with scope("serving.sample"):
                a = jax.random.normal(k, (4,))
            b = jax.random.uniform(k, (4,))
            return a + b

        t = AnalysisTarget("t", f, (jax.random.PRNGKey(0),))
        fs = KeyReuseRule().run(t)
        assert len(fs) == 1 and fs[0].severity == Severity.HIGH
        assert fs[0].details["consumer_prims"] == ["random_bits",
                                                   "random_bits"]
        assert len(fs[0].details["consumers"]) == 2
        # eqn/scope attribution: the first consumption site is the scoped
        # draw, and the finding names both eqns
        assert "serving.sample" in fs[0].details["first_scope"]
        assert "eqn #" in fs[0].message

    def test_split_before_each_draw_is_clean(self):
        def f(k):
            k1, k2 = jax.random.split(k)
            return jax.random.normal(k1, (4,)) + jax.random.uniform(k2, (4,))

        fs = KeyReuseRule().run(
            AnalysisTarget("t", f, (jax.random.PRNGKey(0),)))
        assert fs == []

    def test_sibling_cond_branches_are_exempt(self):
        def f(p, k):
            return jax.lax.cond(
                p, lambda: jax.random.normal(k, (2,)),
                lambda: jax.random.uniform(k, (2,)))

        fs = KeyReuseRule().run(AnalysisTarget(
            "t", f, (jnp.asarray(True), jax.random.PRNGKey(0))))
        assert fs == []


# ---------------------------------------------------------------------------
# key-flow rule: key-discard
# ---------------------------------------------------------------------------
class TestKeyDiscard:
    def test_dropped_subkey_flags_with_slice_index(self):
        def f(k):
            k1, k2 = jax.random.split(k)
            return jax.random.normal(k1, (2,))

        fs = KeyDiscardRule().run(
            AnalysisTarget("t", f, (jax.random.PRNGKey(0),)))
        assert len(fs) == 1 and fs[0].severity == Severity.MEDIUM
        assert "subkey discarded" in fs[0].message
        # the exact discarded output is named: split()[1]
        assert fs[0].details["slice_start"][0] == 1

    def test_whole_split_discarded_flags(self):
        def f(k):
            jax.random.split(k, 3)
            return jnp.ones(2)

        fs = KeyDiscardRule().run(
            AnalysisTarget("t", f, (jax.random.PRNGKey(0),)))
        assert len(fs) == 1
        assert "entirely discarded" in fs[0].message

    def test_consumed_and_escaping_subkeys_are_clean(self):
        def f(k):
            k1, k2 = jax.random.split(k)
            return jax.random.normal(k1, (2,)), k2  # k2 escapes (carry)

        fs = KeyDiscardRule().run(
            AnalysisTarget("t", f, (jax.random.PRNGKey(0),)))
        assert fs == []


# ---------------------------------------------------------------------------
# key-flow rule: key-closure-const
# ---------------------------------------------------------------------------
class TestClosureKey:
    def test_closure_captured_key_flags_high(self):
        baked = jax.random.PRNGKey(7)

        def f(x):
            return x + jax.random.normal(baked, (4,))

        fs = ClosureKeyRule().run(
            AnalysisTarget("t", f, (jnp.ones(4),)))
        assert fs and all(f.severity == Severity.HIGH for f in fs)
        assert any("closure" in f.message for f in fs)

    def test_literal_seed_flags_high(self):
        def f(x):
            return x * jax.random.uniform(jax.random.PRNGKey(0), (3,))

        fs = ClosureKeyRule().run(
            AnalysisTarget("t", f, (jnp.ones(3),)))
        assert any(f.severity == Severity.HIGH
                   and "trace time" in f.message for f in fs)

    def test_key_threaded_as_argument_is_clean(self):
        def f(x, k):
            return x + jax.random.normal(k, (4,))

        fs = ClosureKeyRule().run(AnalysisTarget(
            "t", f, (jnp.ones(4), jax.random.PRNGKey(0))))
        assert fs == []


# ---------------------------------------------------------------------------
# key-flow rule: key-nonuniform (+ the psum2 pre-fix regression)
# ---------------------------------------------------------------------------
class TestNonuniformKey:
    def test_rank_divergent_draw_feeding_psum_flags_high(self):
        """Pre-fix finding: this planted positive was invisible until
        graph.py learned that shard_map lowers psum to 'psum2'."""
        mesh = _mesh8()

        @partial(shard_map, mesh=mesh, in_specs=(P(),), out_specs=P())
        def body(key):
            k = jax.random.fold_in(key, jax.lax.axis_index("x"))
            v = jax.random.uniform(k, ())
            return jax.lax.psum(v, "x")

        fs = NonuniformKeyRule().run(
            AnalysisTarget("t", body, (jax.random.PRNGKey(0),)))
        assert len(fs) == 1 and fs[0].severity == Severity.HIGH
        assert fs[0].details["key_axes"] == ["x"]
        assert fs[0].details["collective_prim"] in ("psum2", "psum")
        assert fs[0].details["collective_axes"] == ["x"]

    def test_uniform_key_feeding_psum_is_clean(self):
        mesh = _mesh8()

        @partial(shard_map, mesh=mesh, in_specs=(P(),), out_specs=P())
        def body(key):
            v = jax.random.uniform(key, ())
            return jax.lax.psum(v, "x")

        fs = NonuniformKeyRule().run(
            AnalysisTarget("t", body, (jax.random.PRNGKey(0),)))
        assert fs == []

    def test_rank_local_draw_not_reaching_collective_is_clean(self):
        mesh = _mesh8()

        @partial(shard_map, mesh=mesh, in_specs=(P(),), out_specs=P(),
                 check_rep=False)  # the output is genuinely rank-varying
        def body(key):
            k = jax.random.fold_in(key, jax.lax.axis_index("x"))
            v = jax.random.uniform(k, ())      # stays rank-local
            u = jax.lax.psum(jnp.float32(1.0), "x")
            return v + 0.0 * u

        fs = NonuniformKeyRule().run(
            AnalysisTarget("t", body, (jax.random.PRNGKey(0),)))
        assert fs == []

    def test_psum2_registered_as_collective(self):
        """Regression pin for the graph.py blind spot itself."""
        from paddle_tpu.analysis.graph import (
            COLLECTIVE_PRIMS,
            UNIFORMIZING_PRIMS,
        )

        assert "psum2" in COLLECTIVE_PRIMS
        assert "psum2" in UNIFORMIZING_PRIMS
        assert "all_gather_invariant" in COLLECTIVE_PRIMS


# ---------------------------------------------------------------------------
# host AST rules
# ---------------------------------------------------------------------------
def _det(tmp_path, src, name="planted"):
    p = tmp_path / f"{name}.py"
    p.write_text(textwrap.dedent(src))
    return run_det_rules([(name, str(p))])


class TestUnorderedIter:
    def test_set_iteration_in_ordering_function_is_high(self, tmp_path):
        fs = _det(tmp_path, """
            def admit_order(items):
                ready = set(items)
                out = []
                for s in ready:
                    out.append(s)
                return out
        """)
        hits = [f for f in fs if f.rule == "det-unordered-iter"]
        assert hits and hits[0].severity == Severity.HIGH
        assert "admit_order" in hits[0].message

    def test_set_iteration_elsewhere_is_medium(self, tmp_path):
        fs = _det(tmp_path, """
            def collect(items):
                ready = set(items)
                return [s for s in ready]
        """)
        hits = [f for f in fs if f.rule == "det-unordered-iter"]
        assert hits and hits[0].severity == Severity.MEDIUM

    def test_sorted_set_is_clean(self, tmp_path):
        fs = _det(tmp_path, """
            def admit_order(items):
                ready = set(items)
                return [s for s in sorted(ready)]
        """)
        assert [f for f in fs if f.rule == "det-unordered-iter"] == []


class TestWallclock:
    def test_clock_in_ordering_branch_is_high(self, tmp_path):
        fs = _det(tmp_path, """
            import time

            def next_tick(self, deadline):
                if time.monotonic() > deadline:
                    return None
                return 1
        """)
        hits = [f for f in fs if f.rule == "det-wallclock"]
        assert hits and hits[0].severity == Severity.HIGH
        assert "next_tick" in hits[0].message

    def test_clock_derived_value_in_branch_is_flagged(self, tmp_path):
        fs = _det(tmp_path, """
            import time

            def schedule(self):
                now = time.monotonic() + 0.5
                if now > self.deadline:
                    return None
                return 1
        """)
        hits = [f for f in fs if f.rule == "det-wallclock"]
        assert hits and "'now'" in hits[0].message

    def test_telemetry_span_argument_is_clean(self, tmp_path):
        """Regression for the pre-fix serving/engine.py:843 false
        positive: a clock as another call's argument is not a time value,
        and the branch tests span presence."""
        fs = _det(tmp_path, """
            import time

            def tick_span(rec, t0):
                span = rec.record_span("prefill",
                                       dur=time.perf_counter() - t0)
                if span is not None:
                    return span
                return None
        """)
        assert [f for f in fs if f.rule == "det-wallclock"] == []


class TestAmbientRng:
    def test_module_global_random_is_high(self, tmp_path):
        fs = _det(tmp_path, """
            import random

            def pick(xs):
                return xs[int(random.random() * len(xs))]
        """)
        hits = [f for f in fs if f.rule == "det-ambient-rng"]
        assert hits and hits[0].severity == Severity.HIGH

    def test_uuid4_and_hash_are_medium(self, tmp_path):
        fs = _det(tmp_path, """
            import uuid

            def ids(x):
                return uuid.uuid4(), hash(x)
        """)
        hits = [f for f in fs if f.rule == "det-ambient-rng"]
        assert len(hits) == 2
        assert all(f.severity == Severity.MEDIUM for f in hits)

    def test_seeded_random_instance_is_clean(self, tmp_path):
        fs = _det(tmp_path, """
            import random

            def jitter(seed):
                rng = random.Random(seed)
                return rng.random()
        """)
        assert [f for f in fs if f.rule == "det-ambient-rng"] == []

    def test_det_ok_annotation_downgrades_to_info(self, tmp_path):
        fs = _det(tmp_path, """
            import random

            def backoff():
                # det-ok: decorrelated jitter is the point
                return random.random()
        """)
        hits = [f for f in fs if f.rule == "det-ambient-rng"]
        assert len(hits) == 1 and hits[0].severity == Severity.INFO
        assert hits[0].details["det_ok"] == \
            "decorrelated jitter is the point"
        assert "audited" in hits[0].message


# ---------------------------------------------------------------------------
# seam-coverage scan fidelity (planted package + tests)
# ---------------------------------------------------------------------------
class TestSeamScanFidelity:
    def _plant(self, tmp_path, test_src):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "m.py").write_text(
            "from paddle_tpu.resilience.inject import fire\n"
            "def go():\n"
            "    fire('engine.tick', slot=1)\n")
        tests = tmp_path / "tests"
        tests.mkdir()
        (tests / "test_x.py").write_text(textwrap.dedent(test_src))
        return seam_coverage(pkg_root=str(pkg), tests_dir=str(tests))

    def test_real_twin_counts(self, tmp_path):
        cov = self._plant(tmp_path, """
            def test_twin(sched):
                log_a = sched.fired_log()
                log_b = sched.fired_log()
                assert log_a == log_b
                assert "engine.tick"
        """)
        assert cov["covered"]["engine.tick"] == ["test_x::test_twin"]
        assert "engine.tick" not in cov["uncovered"]
        assert "engine.tick" not in cov["never_fired"]

    def test_one_sided_assert_does_not_count(self, tmp_path):
        cov = self._plant(tmp_path, """
            def test_not_twin(sched):
                log = sched.fired_log()
                assert log == [{"point": "engine.tick"}]
        """)
        assert "engine.tick" in cov["uncovered"]

    def test_unregistered_fire_literal_reported(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "m.py").write_text(
            "from paddle_tpu.resilience.inject import fire\n"
            "def go():\n"
            "    fire('engine.tock')\n")  # misspelled seam
        cov = seam_coverage(pkg_root=str(pkg),
                            tests_dir=str(tmp_path / "absent"))
        assert cov["unregistered_fire_literals"] == ["engine.tock"]
        assert "engine.tick" in cov["never_fired"]
        fs = coverage_findings(cov)
        assert any(f.severity == Severity.MEDIUM
                   and "engine.tock" in f.message for f in fs)


# ---------------------------------------------------------------------------
# twin certificates: every registered seam, fire-level, two identical runs
# ---------------------------------------------------------------------------
# the full POINTS registry, spelled as literals so the static coverage
# scan can see them; test_registry_mapping_pinned fails when the registry
# and this list drift (a new seam must add its certificate here)
_TWIN_SEAMS = [
    "elastic.store.register",
    "elastic.store.heartbeat",
    "elastic.store.deregister",
    "elastic.store.kv.put",
    "elastic.store.kv.get",
    "elastic.store.kv.delete",
    "elastic.store.kv.scan",
    "elastic.store.rpc.register",
    "elastic.store.rpc.heartbeat",
    "elastic.store.rpc.deregister",
    "elastic.store.rpc.put",
    "elastic.store.rpc.get",
    "elastic.store.rpc.delete",
    "elastic.store.rpc.scan",
    "elastic.store.rpc.scan_kv",
    "store.replica.append",
    "store.lease.renew",
    "store.replica.kill",
    "store.election.start",
    "store.election.won",
    "checkpoint.write",
    "ckpt.replica.push",
    "ckpt.scrub.corrupt",
    "ckpt.disk.loss",
    "engine.tick",
    "replica.tick",
    "serving.pages.exhausted",
    "serving.spec.verify",
    "router.transport",
    "router.resurrect",
    "router.migrate",
    "elastic.rank.step",
    "preemption.update",
]


class TestTwinCertificates:
    @pytest.mark.parametrize("seam", sorted(_TWIN_SEAMS))
    def test_seam_twin_certificate(self, seam):
        """Two replays of one scripted workload under one armed schedule
        produce bit-identical fired logs for this seam — trigger counts,
        label matching, every/max_fires bookkeeping and the log records
        themselves all replay.  This is the certificate the
        det-seam-coverage audit counts per seam."""
        sched = FaultSchedule(seed=19)
        sched.add(seam, "raise", at=(2, 5))
        sched.add(seam, "raise", every=4, max_fires=2, match={"op": "b"})

        def leg():
            with sched.scope():
                for i in range(10):
                    try:
                        inject.fire(seam, attempt=i,
                                    op=("a" if i % 2 else "b"))
                    except inject.InjectedFault:
                        pass
            return sched.fired_log()

        log_a = leg()
        sched.reset()
        log_b = leg()
        assert log_a == log_b
        assert len(log_a) == 3
        assert all(f["point"] == seam for f in log_a)
        assert [f["count"] for f in log_a] == [2, 5, 4]

    def test_elastic_store_real_twin_certificate(self):
        """Real-seam twin: a live _TcpStore against a fresh KVServer per
        leg, same schedule (message-level drops + an attempt-level raise
        absorbed by the retry layer); the fired logs must match
        bit-for-bit across the two legs."""
        sched = (FaultSchedule(seed=3)
                 .add("elastic.store.heartbeat", "drop", at=1)
                 .add("elastic.store.kv.put", "drop", at=1)
                 .add("elastic.store.rpc.get", "raise", at=1))

        def leg():
            srv = KVServer().start()
            try:
                st = _TcpStore(f"127.0.0.1:{srv.port}", "twinjob",
                               ttl=5.0, retries=2)
                with sched.scope():
                    st.register("n0", "ep0")
                    st.heartbeat("n0")      # dropped: beat silently lost
                    st.put("k", "v1")       # dropped: write lost
                    st.put("k", "v2")
                    assert st.get("k") == "v2"  # attempt 1 raises → retry
                    st.deregister("n0")
            finally:
                srv.stop()
            return sched.fired_log()

        log_a = leg()
        sched.reset()
        log_b = leg()
        assert log_a == log_b
        assert [f["point"] for f in log_a] == [
            "elastic.store.heartbeat",
            "elastic.store.kv.put",
            "elastic.store.rpc.get",
        ]

    def test_registry_mapping_pinned(self):
        """The inject-registry audit, pinned tier-1: every POINTS seam is
        twin-certified, fired somewhere in the package, and no fire site
        uses an unregistered literal (dead/misspelled seams).  The
        _TWIN_SEAMS list and the registry must stay in lockstep."""
        assert set(_TWIN_SEAMS) == set(POINTS)
        cov = seam_coverage()
        assert cov["uncovered"] == []
        assert cov["never_fired"] == []
        assert cov["unregistered_fire_literals"] == []
        assert cov["n_covered"] == cov["n_points"] == len(POINTS)
        assert coverage_findings(cov) == []

    def test_fire_wrapper_sites_are_seen(self):
        """Regression for the pre-fix scan blind spot: store.* seams fire
        through replicated_store's local _fire wrapper and must not read
        as dead registry entries."""
        cov = seam_coverage()
        for seam in ("store.replica.append", "store.lease.renew",
                     "store.election.won"):
            assert seam in cov["fired_in"], seam


# ---------------------------------------------------------------------------
# divergence bisector
# ---------------------------------------------------------------------------
class TestBisector:
    def test_planted_desync_localized_to_tick_scope_and_prim(self):
        res = demo_divergence(n_ticks=6, desync_tick=3)
        assert not res.identical
        r = res.first
        assert r.tick == 3                       # the exact planted tick
        assert r.scope == "serving.sample"       # the profiler scope
        assert r.prim in RANDOM_PRIMS            # the key chain itself
        assert r.kind == "value"
        assert r.n_diff > 0 and r.n_total >= r.n_diff
        d = r.to_dict()
        assert d["where"].startswith("serving.sample")

    def test_identical_transcripts_report_identical(self):
        res = demo_divergence(n_ticks=4, desync_tick=None)
        assert res.identical and res.first is None
        assert res.checked_ticks == 4 and res.checked_eqns > 0

    def test_scan_divergence_localized_to_exact_iteration(self):
        def f(c, xs):
            def body(c, x):
                c = c * 2.0 + x
                return c, c
            out, ys = jax.lax.scan(body, c, xs)
            return out + jnp.sum(ys)

        xs_a = jnp.arange(8, dtype=jnp.float32)
        xs_b = xs_a.at[5].add(1e-3)
        res = bisect_runs(f, [(jnp.float32(0.0), xs_a)],
                          [(jnp.float32(0.0), xs_b)])
        assert not res.identical
        assert res.first.path == ("scan",)
        assert res.first.iteration == 5          # the exact iteration
        assert res.first.prim == "add"

    def test_while_divergence_carries_iteration(self):
        def h(n):
            return jax.lax.while_loop(
                lambda c: c[0] < n,
                lambda c: (c[0] + 1, c[1] * 2.0),
                (jnp.int32(0), jnp.float64(1.0)))[1]

        res = bisect_runs(h, [(jnp.int32(3),)], [(jnp.int32(4),)])
        assert not res.identical
        assert res.first.iteration == 3

    def test_nan_agreeing_runs_are_identical(self):
        def q(x):
            return x / x                          # 0/0 → NaN in both

        z = jnp.float32(0.0)
        res = bisect_runs(q, [(z,)], [(z,)])
        assert res.identical

    def test_mismatched_transcript_lengths_rejected(self):
        with pytest.raises(ValueError, match="tick-for-tick"):
            bisect_runs(lambda x: x, [(jnp.float32(1),)], [])

    def test_chunked_flush_finds_same_divergence(self):
        a = demo_divergence(n_ticks=6, desync_tick=2,
                            config=BisectConfig(check_every=1))
        b = demo_divergence(n_ticks=6, desync_tick=2,
                            config=BisectConfig(check_every=256))
        assert (a.first.tick, a.first.eqn_index, a.first.prim) == \
            (b.first.tick, b.first.eqn_index, b.first.prim)

    def test_diff_fired_logs(self):
        base = [{"point": "engine.tick", "kind": "raise", "count": 1}]
        assert diff_fired_logs(base, [dict(base[0])]) is None
        d = diff_fired_logs(base, [dict(base[0], count=2)])
        assert d["index"] == 0 and d["fields"] == ["count"]
        d = diff_fired_logs(base, base + [dict(base[0], count=2)])
        assert d["fields"] == ["length"] and d["extra_in"] == "b"


# ---------------------------------------------------------------------------
# CLI: the --determinism artifact + exit contract
# ---------------------------------------------------------------------------
class TestDeterminismCLI:
    def test_full_run_is_high_clean_and_demo_localizes(self, tmp_path):
        """The zero-HIGH smoke over every shipped entry point (including
        serving_spec_verify), the 100% seam coverage, and the bisector
        demo — one CLI invocation, exit 0."""
        out = tmp_path / "det.json"
        rc = analysis_main(["--determinism", "--bisect-demo",
                            "--out", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["counts"]["HIGH"] == 0
        assert payload["meta"]["build_errors"] == {}
        assert "serving_spec_verify" in payload["meta"]["entry_points"]
        cov = payload["meta"]["seam_coverage"]
        assert cov["n_covered"] == cov["n_points"]
        assert cov["uncovered"] == []
        demo = payload["bisect_demo"]
        assert not demo["identical"]
        first = demo["first_divergence"]
        assert first["tick"] == demo["planted_tick"] == 3
        assert first["scope"] == "serving.sample"
        assert first["prim"] in RANDOM_PRIMS

    def test_fail_on_info_gates_exit_1(self, tmp_path):
        """The audited det-ok INFO findings exist by design; gating at
        info must flip the exit code (the exit contract is severity-
        driven, not hardwired)."""
        rc = analysis_main(["--determinism", "--only", "static_program",
                            "--fail-on", "info",
                            "--out", str(tmp_path / "d.json")])
        assert rc == 1

    def test_bisect_demo_requires_determinism_mode(self, tmp_path):
        with pytest.raises(SystemExit) as e:
            analysis_main(["--bisect-demo", "--out",
                           str(tmp_path / "x.json")])
        assert e.value.code == 2

    def test_host_plane_is_audited_not_suppressed(self):
        """Every surviving host-plane finding is an INFO carrying its
        det-ok audit reason — nothing was silently filtered, and nothing
        HIGH remains."""
        report = an.analyze_determinism()
        assert report.high() == []
        ast_findings = [f for f in report.findings
                        if f.rule in ("det-unordered-iter",
                                      "det-wallclock", "det-ambient-rng")]
        assert ast_findings, "the audited sites should still be reported"
        for f in ast_findings:
            assert f.severity == Severity.INFO
            assert f.details.get("det_ok")
