"""Flash-attention Pallas kernel vs plain XLA reference (fwd + grads).

Mirrors the reference's OpTest pattern (numeric comparison against a
reference implementation) for the fused-attention kernel
(/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu).
Runs in pallas interpret mode on CPU.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import flash_attention


def ref_attention(q, k, v, causal, scale):
    s = jnp.einsum("bhtd,bhsd->bhts", q, k) * scale
    if causal:
        t, s_len = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t, s_len), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,s_len", [(256, 256), (384, 256)])
def test_forward_matches_reference(causal, t, s_len):
    rng = np.random.default_rng(0)
    b, h, d = 2, 2, 128
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s_len, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s_len, d)), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = ref_attention(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    rng = np.random.default_rng(1)
    b, h, t, d = 1, 2, 256, 128
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = ref_attention(q, k, v, causal, scale)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=3e-4, rtol=3e-4)


def test_bf16_forward():
    rng = np.random.default_rng(2)
    b, h, t, d = 1, 1, 256, 128
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = ref_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                        v.astype(jnp.float32), True, 1.0 / np.sqrt(d))
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_padded_head_dim_96_fwd_and_grads():
    """D=96 (GPT-3 760M) is zero-padded to 128 inside the wrapper; fwd and
    grads must stay exact vs the unpadded reference."""
    rng = np.random.default_rng(3)
    b, h, t, d = 1, 2, 256, 96
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = ref_attention(q, k, v, True, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=True).sum()

    def f_ref(q, k, v):
        return ref_attention(q, k, v, True, scale).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5)


def test_ragged_causal_tail_padding():
    """T=320 (not a 128-multiple), causal: tail zero-padding is exact —
    padded keys are causally masked, padded query rows' cotangent is zero."""
    rng = np.random.default_rng(5)
    b, h, t, d = 1, 2, 320, 64
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.shape == (b, h, t, d)
    ref = ref_attention(q, k, v, True, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    g_flash = jax.grad(lambda q, k, v: flash_attention(
        q, k, v, causal=True, interpret=True).sum(), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: ref_attention(
        q, k, v, True, scale).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("policy_name", ["selective", "core_attn", "full"])
def test_grads_under_remat_policies(policy_name):
    """Residuals-as-inputs remat design (SAVEABLE_NAMES): grads under
    jax.checkpoint with the flash-saveable policies must match the plain
    XLA reference. 'selective' composes dots+names, 'core_attn' names-only,
    'full' saves nothing (forces the recompute path through the
    stop_gradient'd pallas forward)."""
    from paddle_tpu.ops.pallas.flash_attention import saveable_policy

    rng = np.random.default_rng(7)
    b, h, t, d = 1, 2, 256, 64
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((d, d)) * 0.1, jnp.float32)
    scale = 1.0 / np.sqrt(d)

    if policy_name == "selective":
        policy = saveable_policy(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif policy_name == "core_attn":
        policy = saveable_policy()
    else:
        policy = None

    def block(w, x, attn):
        y = jnp.einsum("bhtd,de->bhte", x, w)
        o = attn(y, y, y)
        return x + o

    def make_loss(attn):
        def loss(w, x):
            f = jax.checkpoint(lambda w, h: block(w, h, attn), policy=policy)
            h = f(w, x)
            h = f(w, h)
            return jnp.sum(h * jnp.sin(h))
        return loss

    flash = lambda q, k, v: flash_attention(q, k, v, causal=True,
                                            interpret=True)
    ref = lambda q, k, v: ref_attention(q, k, v, True, scale)
    gw_f, gx_f = jax.grad(make_loss(flash), argnums=(0, 1))(w, q)
    gw_r, gx_r = jax.grad(make_loss(ref), argnums=(0, 1))(w, q)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r),
                               atol=3e-4, rtol=3e-4)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r),
                               atol=3e-4, rtol=3e-4)


def test_remat_saves_flash_forward():
    """Structural check: under save_only_these_names the backward jaxpr
    contains exactly one forward flash pallas_call (the primal one) — the
    saved o/lse feed the backward kernels without a forward replay."""
    from paddle_tpu.ops.pallas.flash_attention import saveable_policy

    b, h, t, d = 1, 2, 256, 64
    q = jnp.ones((b, h, t, d), jnp.float32)

    def loss(x):
        f = jax.checkpoint(
            lambda h: flash_attention(h, h, h, causal=True, interpret=True),
            policy=saveable_policy())
        return jnp.sum(f(x) ** 2)

    jaxpr = str(jax.make_jaxpr(jax.grad(loss))(q))
    # one fwd pallas_call + dq + dkv backward calls — no forward replay
    assert jaxpr.count("pallas_call") == 3, jaxpr.count("pallas_call")
    assert "flash_out" in jaxpr and "flash_lse" in jaxpr

    def loss_dots(x):
        f = jax.checkpoint(
            lambda h: flash_attention(h, h, h, causal=True, interpret=True),
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        return jnp.sum(f(x) ** 2)

    # contrast: a policy blind to the names re-runs the flash forward in
    # backward (4th pallas_call) — the exact recompute the tags eliminate
    jaxpr_dots = str(jax.make_jaxpr(jax.grad(loss_dots))(q))
    assert jaxpr_dots.count("pallas_call") == 4, jaxpr_dots.count("pallas_call")
