"""text / utils / inference / asp package tests."""
import numpy as np
import pytest

import paddle_tpu as paddle


# ---------------------------------------------------------------------------
# text
# ---------------------------------------------------------------------------
def test_text_datasets_synthetic():
    from paddle_tpu.text import Imdb, Imikolov, UCIHousing, WMT14

    housing = UCIHousing(mode="train")
    x, y = housing[0]
    assert x.shape == (13,) and y.shape == (1,)
    assert len(UCIHousing(mode="test")) > 0

    imdb = Imdb(mode="train")
    seq, label = imdb[0]
    assert seq.dtype == np.int64 and label in (0, 1)

    ng = Imikolov(window_size=5)
    ctx, tgt = ng[0]
    assert ctx.shape == (4,)

    src, tin, tout = WMT14()[0]
    assert len(tin) == len(tout)


def test_text_dataset_missing_file_raises(tmp_path):
    from paddle_tpu.text import UCIHousing

    with pytest.raises(FileNotFoundError):
        UCIHousing(data_file=str(tmp_path / "nope.data"))


def test_viterbi_decode_matches_bruteforce():
    from paddle_tpu.text import ViterbiDecoder

    rng = np.random.RandomState(0)
    B, T, N = 3, 5, 4
    pot = rng.randn(B, T, N).astype("float32")
    trans = rng.randn(N, N).astype("float32")
    lengths = np.array([5, 3, 4], "int64")

    dec = ViterbiDecoder(paddle.to_tensor(trans), include_bos_eos_tag=False)
    scores, paths = dec(paddle.to_tensor(pot), paddle.to_tensor(lengths))
    scores, paths = scores.numpy(), paths.numpy()

    # brute force per sequence
    import itertools

    for b in range(B):
        L = int(lengths[b])
        best, best_path = -1e30, None
        for assign in itertools.product(range(N), repeat=L):
            s = pot[b, 0, assign[0]]
            for t in range(1, L):
                s += trans[assign[t - 1], assign[t]] + pot[b, t, assign[t]]
            if s > best:
                best, best_path = s, assign
        np.testing.assert_allclose(scores[b], best, rtol=1e-5)
        np.testing.assert_array_equal(paths[b, :L], best_path)


# ---------------------------------------------------------------------------
# utils
# ---------------------------------------------------------------------------
def test_utils_try_import_and_version():
    from paddle_tpu.utils import require_version, try_import

    assert try_import("json") is not None
    with pytest.raises(ImportError):
        try_import("definitely_not_a_module_xyz")
    assert require_version("0.0.1")
    with pytest.raises(Exception):
        require_version("999.0.0")


def test_utils_run_check(capsys):
    from paddle_tpu.utils import run_check

    run_check()
    out = capsys.readouterr().out
    assert "works" in out


def test_utils_download_cache_only(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_HOME", str(tmp_path))
    from paddle_tpu.utils import get_weights_path_from_url

    with pytest.raises(FileNotFoundError):
        get_weights_path_from_url("https://example.com/w.pdparams")
    target = tmp_path / "weights" / "w.pdparams"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_bytes(b"hi")
    assert get_weights_path_from_url("https://example.com/w.pdparams") == str(target)


def test_deprecated_decorator():
    from paddle_tpu.utils import deprecated

    @deprecated(update_to="new_fn", since="0.1")
    def old_fn():
        return 5

    with pytest.warns(DeprecationWarning):
        assert old_fn() == 5


# ---------------------------------------------------------------------------
# inference predictor
# ---------------------------------------------------------------------------
def test_inference_predictor_roundtrip(tmp_path):
    from paddle_tpu import inference, static

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            lin = paddle.nn.Linear(4, 3)
            out = lin(x)
        exe = static.Executor()
        x_np = np.random.rand(2, 4).astype("float32")
        (ref,) = exe.run(main, feed={"x": x_np}, fetch_list=[out])
        prefix = str(tmp_path / "model")
        static.save_inference_model(prefix, [x], [out], exe)
    finally:
        paddle.disable_static()

    cfg = inference.Config(prefix + ".pdmodel")
    pred = inference.create_predictor(cfg)
    assert pred.get_input_names() == ["x"]
    h = pred.get_input_handle("x")
    h.copy_from_cpu(x_np)
    pred.run()
    got = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(got, ref, rtol=1e-5)

    # pass pipeline (analysis_predictor.cc:179 analog): the default passes
    # are real and deletable; outputs identical with every combination
    assert "stablehlo_jit_cache" in cfg.pass_builder().all_passes()
    assert pred._jitted is not None

    cfg2 = inference.Config(prefix + ".pdmodel")
    cfg2.enable_memory_optim()
    assert "input_buffer_donation" in cfg2.pass_builder().all_passes()
    pred2 = inference.create_predictor(cfg2)
    (got2,) = pred2.run([x_np])
    np.testing.assert_allclose(got2, ref, rtol=1e-5)

    cfg3 = inference.Config(prefix + ".pdmodel")
    cfg3.switch_ir_optim(False)
    pred3 = inference.create_predictor(cfg3)
    assert pred3._jitted is None  # un-optimized replay path
    (got3,) = pred3.run([x_np])
    np.testing.assert_allclose(got3, ref, rtol=1e-5)


# ---------------------------------------------------------------------------
# asp 2:4 sparsity
# ---------------------------------------------------------------------------
def test_asp_mask_and_prune():
    from paddle_tpu.incubate import asp

    w = np.random.randn(8, 16).astype("float32")
    mask = asp.create_mask(w)
    assert asp.check_mask_1d(mask)
    assert abs(asp.calculate_density(mask) - 0.5) < 1e-6
    # mask keeps the 2 largest magnitudes per group of 4
    groups = (np.abs(w).reshape(-1, 4)).argsort(axis=1)[:, 2:]
    kept = mask.reshape(-1, 4)
    for g, idx in zip(kept, groups):
        assert g[idx].all()

    net = paddle.nn.Sequential(paddle.nn.Linear(16, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 4))
    masks = asp.prune_model(net)
    assert len(masks) == 2
    assert asp.check_mask_1d(net[0].weight.numpy())


def test_asp_optimizer_preserves_sparsity():
    from paddle_tpu.incubate import asp

    net = paddle.nn.Linear(8, 8, bias_attr=False)
    asp.prune_model(net)
    opt = asp.decorate(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
        model=net,
    )
    x = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))
    for _ in range(3):
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert asp.check_mask_1d(net.weight.numpy())


def test_sysconfig_and_onnx(tmp_path):
    import os

    from paddle_tpu import sysconfig

    assert os.path.isdir(sysconfig.get_lib())

    from paddle_tpu import onnx as ponnx
    from paddle_tpu.jit import InputSpec

    net = paddle.nn.Linear(3, 2)
    with pytest.warns(UserWarning):
        ponnx.export(net, str(tmp_path / "m"),
                     input_spec=[InputSpec([-1, 3], "float32")])
    import paddle_tpu.jit as jit

    loaded = jit.load(str(tmp_path / "m"))
    x = paddle.to_tensor(np.random.rand(4, 3).astype("float32"))
    np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(), rtol=1e-5)
