"""DGC + LocalSGD meta-optimizer strategies.

Parity model: reference test_dgc_optimizer.py / test_dgc_momentum_op.py and
test_fleet_localsgd_meta_optimizer.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.meta_optimizers import (
    AdaptiveLocalSGDOptimizer,
    DGCMomentum,
    LocalSGDOptimizer,
)


def _train(net, opt, data, steps):
    losses = []
    for i in range(steps):
        x, y = data[i % len(data)]
        loss = ((net(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _make_net(seed=0):
    paddle.seed(seed)
    return paddle.nn.Linear(6, 1, bias_attr=False)


def _make_data(n=8):
    rng = np.random.RandomState(0)
    w = rng.randn(6, 1).astype("float32")
    return [(x := rng.rand(16, 6).astype("float32"), x @ w) for _ in range(n)]


def test_dgc_dense_phase_matches_momentum():
    data = _make_data()
    n1, n2 = _make_net(1), _make_net(1)
    m = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                  parameters=n1.parameters())
    d = DGCMomentum(learning_rate=0.05, momentum=0.9, parameters=n2.parameters(),
                    rampup_begin_step=100)  # stays dense for all 10 steps
    _train(n1, m, data, 10)
    _train(n2, d, data, 10)
    np.testing.assert_allclose(n1.weight.numpy(), n2.weight.numpy(), rtol=1e-5)


def test_dgc_sparse_phase_masks_updates():
    # one step in sparse phase: only ~top-(1-s) of coordinates may change
    net = _make_net(2)
    opt = DGCMomentum(learning_rate=0.1, momentum=0.0, parameters=net.parameters(),
                      rampup_begin_step=0, rampup_step=1, sparsity=[0.5])
    x = np.random.rand(4, 6).astype("float32")
    y = np.random.rand(4, 1).astype("float32")
    w0 = net.weight.numpy().copy()
    loss = ((net(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
    loss.backward()
    opt.step()
    changed = (net.weight.numpy() != w0).sum()
    assert changed <= 3 + 1, f"sparse step changed {changed}/6 coords"


def test_dgc_still_converges():
    data = _make_data()
    net = _make_net(3)
    opt = DGCMomentum(learning_rate=0.05, momentum=0.9, parameters=net.parameters(),
                      rampup_begin_step=5, rampup_step=10, sparsity=[0.5, 0.75])
    losses = _train(net, opt, data, 120)
    assert losses[-1] < losses[0] * 0.1, f"{losses[0]} -> {losses[-1]}"


def test_localsgd_sync_schedule(monkeypatch):
    data = _make_data()
    net = _make_net(4)
    inner = paddle.optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    opt = LocalSGDOptimizer(inner, k_steps=4, begin_step=2)
    calls = []
    monkeypatch.setattr(opt, "_sync_params", lambda: calls.append(opt._step_count))
    _train(net, opt, data, 12)
    assert calls == [4, 8, 12]


def test_localsgd_world1_trains():
    data = _make_data()
    net = _make_net(5)
    opt = LocalSGDOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
        k_steps=2,
    )
    losses = _train(net, opt, data, 60)
    assert losses[-1] < losses[0] * 0.1
    # delegation surface
    assert opt.get_lr() == pytest.approx(0.1)


def test_adaptive_localsgd_k_grows_as_loss_drops():
    inner = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=_make_net(6).parameters())
    opt = AdaptiveLocalSGDOptimizer(inner, init_k_steps=2, max_k_steps=8)
    opt.record_loss(4.0)
    assert opt._current_k() == 2
    opt.record_loss(0.04)   # loss / 100 -> k x10, clipped to max
    assert opt._current_k() == 8


def test_fleet_strategy_selects_dgc_and_localsgd():
    import paddle_tpu.distributed.fleet as fleet_mod
    from paddle_tpu.distributed.fleet import DistributedStrategy

    net = _make_net(7)
    strategy = DistributedStrategy()
    strategy.dgc = True
    strategy.dgc_configs = {"rampup_begin_step": 3, "sparsity": [0.9]}
    base = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                     parameters=net.parameters())
    fleet_mod.fleet.init(is_collective=True, strategy=strategy)
    wrapped = fleet_mod.distributed_optimizer(base, strategy)
    assert isinstance(wrapped._inner_opt, DGCMomentum)
    assert wrapped._inner_opt._rampup_begin == 3

    strategy2 = DistributedStrategy()
    strategy2.localsgd = True
    strategy2.localsgd_configs = {"k_steps": 3}
    wrapped2 = fleet_mod.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.01, parameters=net.parameters()),
        strategy2,
    )
    assert isinstance(wrapped2._inner_opt, LocalSGDOptimizer)
    assert wrapped2._inner_opt.k_steps == 3


def test_dgc_rewrap_preserves_weight_decay_and_nesterov():
    import paddle_tpu.distributed.fleet as fleet_mod
    from paddle_tpu.distributed.fleet import DistributedStrategy

    net = _make_net(8)
    strategy = DistributedStrategy()
    strategy.dgc = True
    base = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                     weight_decay=1e-4, use_nesterov=True,
                                     parameters=net.parameters())
    fleet_mod.fleet.init(is_collective=True, strategy=strategy)
    wrapped = fleet_mod.distributed_optimizer(base, strategy)
    inner = wrapped._inner_opt
    assert isinstance(inner, DGCMomentum)
    assert inner._weight_decay_coeff == pytest.approx(1e-4)
    assert inner._use_nesterov is True


def test_adaptive_localsgd_records_via_minimize():
    net = _make_net(9)
    inner = paddle.optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    opt = AdaptiveLocalSGDOptimizer(inner, init_k_steps=2)
    x = paddle.to_tensor(np.random.rand(4, 6).astype("float32"))
    y = paddle.to_tensor(np.random.rand(4, 1).astype("float32"))
    loss = ((net(x) - y) ** 2).mean()
    opt.minimize(loss)
    assert opt._loss0 is not None and opt._last_loss is not None
