"""OpTest-style numpy-parity tests for paddle_tpu.vision.detection.

Each test re-derives the reference op's semantics in plain numpy (the
OpTest pattern, unittests/op_test.py:277) and compares against the XLA
implementation. Reference kernels: paddle/fluid/operators/detection/*."""
import math

import numpy as np
import pytest

from paddle_tpu.tensor import Tensor
from paddle_tpu.vision import detection as D


def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x)


def _rand_boxes(rng, n, lo=0.0, hi=60.0):
    x1 = rng.uniform(lo, hi, n)
    y1 = rng.uniform(lo, hi, n)
    w = rng.uniform(1.0, 20.0, n)
    h = rng.uniform(1.0, 20.0, n)
    return np.stack([x1, y1, x1 + w, y1 + h], axis=1).astype(np.float32)


# ---------------------------------------------------------------------------
# generators
# ---------------------------------------------------------------------------

def _np_prior_box(fh, fw, ih, iw, min_sizes, max_sizes, ars, flip, offset,
                  mmorder):
    out_ars = [1.0]
    for ar in ars:
        if any(abs(ar - o) < 1e-6 for o in out_ars):
            continue
        out_ars.append(ar)
        if flip:
            out_ars.append(1.0 / ar)
    step_w, step_h = iw / fw, ih / fh
    boxes = []
    for hh in range(fh):
        for ww in range(fw):
            cx = (ww + offset) * step_w
            cy = (hh + offset) * step_h
            for si, mn in enumerate(min_sizes):
                exts = []
                if mmorder:
                    exts.append((mn / 2, mn / 2))
                    if max_sizes:
                        m = math.sqrt(mn * max_sizes[si])
                        exts.append((m / 2, m / 2))
                    for ar in out_ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        exts.append((mn * math.sqrt(ar) / 2,
                                     mn / math.sqrt(ar) / 2))
                else:
                    for ar in out_ars:
                        exts.append((mn * math.sqrt(ar) / 2,
                                     mn / math.sqrt(ar) / 2))
                    if max_sizes:
                        m = math.sqrt(mn * max_sizes[si])
                        exts.append((m / 2, m / 2))
                for bw, bh in exts:
                    boxes.append([(cx - bw) / iw, (cy - bh) / ih,
                                  (cx + bw) / iw, (cy + bh) / ih])
    p = len(boxes) // (fh * fw)
    return np.asarray(boxes, np.float32).reshape(fh, fw, p, 4)


@pytest.mark.parametrize("mmorder", [False, True])
def test_prior_box(mmorder):
    feat = np.zeros((1, 8, 4, 6), np.float32)
    img = np.zeros((1, 3, 64, 96), np.float32)
    got, var = D.prior_box(feat, img, min_sizes=[8.0, 16.0], max_sizes=[16.0, 32.0],
                           aspect_ratios=[2.0], flip=True, offset=0.5,
                           min_max_aspect_ratios_order=mmorder)
    want = _np_prior_box(4, 6, 64.0, 96.0, [8.0, 16.0], [16.0, 32.0], [2.0],
                         True, 0.5, mmorder)
    np.testing.assert_allclose(_np(got), want, rtol=1e-5, atol=1e-6)
    assert _np(var).shape == want.shape
    np.testing.assert_allclose(_np(var)[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_anchor_generator():
    feat = np.zeros((1, 8, 3, 5), np.float32)
    got, var = D.anchor_generator(feat, anchor_sizes=[32.0, 64.0],
                                  aspect_ratios=[0.5, 1.0],
                                  variances=[0.1, 0.1, 0.2, 0.2],
                                  stride=[16.0, 16.0], offset=0.5)
    # independent re-derivation (anchor_generator_op.h)
    want = np.zeros((3, 5, 4, 4), np.float32)
    for hi in range(3):
        for wi in range(5):
            xc = wi * 16.0 + 0.5 * 15.0
            yc = hi * 16.0 + 0.5 * 15.0
            i = 0
            for ar in (0.5, 1.0):
                for size in (32.0, 64.0):
                    base_w = round(math.sqrt(16 * 16 / ar))
                    base_h = round(base_w * ar)
                    aw = size / 16.0 * base_w
                    ah = size / 16.0 * base_h
                    want[hi, wi, i] = [xc - 0.5 * (aw - 1), yc - 0.5 * (ah - 1),
                                       xc + 0.5 * (aw - 1), yc + 0.5 * (ah - 1)]
                    i += 1
    np.testing.assert_allclose(_np(got), want, rtol=1e-5, atol=1e-5)


def test_density_prior_box_shapes_and_centers():
    feat = np.zeros((1, 8, 2, 2), np.float32)
    img = np.zeros((1, 3, 32, 32), np.float32)
    boxes, var = D.density_prior_box(feat, img, densities=[2], fixed_sizes=[8.0],
                                     fixed_ratios=[1.0])
    b = _np(boxes)
    assert b.shape == (2, 2, 4, 4)  # density^2 priors per cell
    # all priors are 8x8 squares (fixed_ratio 1) in normalized coords
    w = (b[..., 2] - b[..., 0]) * 32.0
    np.testing.assert_allclose(w, 8.0, rtol=1e-5)


# ---------------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------------

def _np_box_coder_encode(tb, pb, var, normalized):
    off = 0.0 if normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + off
    ph = pb[:, 3] - pb[:, 1] + off
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    tw = tb[:, 2] - tb[:, 0] + off
    th = tb[:, 3] - tb[:, 1] + off
    tcx = (tb[:, 2] + tb[:, 0]) / 2
    tcy = (tb[:, 3] + tb[:, 1]) / 2
    out = np.stack([
        (tcx[:, None] - pcx[None, :]) / pw[None, :],
        (tcy[:, None] - pcy[None, :]) / ph[None, :],
        np.log(np.abs(tw[:, None] / pw[None, :])),
        np.log(np.abs(th[:, None] / ph[None, :])),
    ], axis=-1)
    if var is not None:
        out = out / var[None, :, :]
    return out


@pytest.mark.parametrize("normalized", [True, False])
def test_box_coder_encode_decode_roundtrip(normalized):
    rng = np.random.default_rng(0)
    pb = _rand_boxes(rng, 6)
    tb = _rand_boxes(rng, 4)
    pbv = rng.uniform(0.1, 0.3, (6, 4)).astype(np.float32)

    enc = D.box_coder(pb, pbv, tb, "encode_center_size", box_normalized=normalized)
    want = _np_box_coder_encode(tb, pb, pbv, normalized)
    np.testing.assert_allclose(_np(enc), want, rtol=1e-4, atol=1e-5)

    # decode(encode(x)) == x: deltas [1, 4, 4] where column j holds target
    # j's encoding on prior j; axis=0 applies prior j to column j
    diag = _np(enc)[np.arange(4), np.arange(4)][None]  # [1, 4, 4]
    dec = D.box_coder(pb[:4], pbv[:4], diag, "decode_center_size",
                      box_normalized=normalized, axis=0)
    full = _np(dec)  # [1, 4, 4]
    # non-normalized roundtrip carries the reference's half-pixel shift:
    # encode centers use (x1+x2)/2 while decode reconstructs corners from
    # the (+1)-width convention (box_coder_op.h Encode/DecodeCenterSize)
    shift = 0.0 if normalized else 0.5
    for j in range(4):
        np.testing.assert_allclose(full[0, j], tb[j] - shift,
                                   rtol=1e-3, atol=1e-3)


def test_iou_similarity():
    rng = np.random.default_rng(1)
    a = _rand_boxes(rng, 5)
    b = _rand_boxes(rng, 7)
    got = _np(D.iou_similarity(a, b))
    want = np.zeros((5, 7), np.float32)
    for i in range(5):
        for j in range(7):
            ix1 = max(a[i, 0], b[j, 0]); iy1 = max(a[i, 1], b[j, 1])
            ix2 = min(a[i, 2], b[j, 2]); iy2 = min(a[i, 3], b[j, 3])
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            a1 = (a[i, 2] - a[i, 0]) * (a[i, 3] - a[i, 1])
            a2 = (b[j, 2] - b[j, 0]) * (b[j, 3] - b[j, 1])
            want[i, j] = inter / (a1 + a2 - inter + 1e-10)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_box_clip():
    rng = np.random.default_rng(2)
    boxes = _rand_boxes(rng, 8, lo=-10, hi=80)[None]  # [1, 8, 4]
    im_info = np.array([[40.0, 50.0, 1.0]], np.float32)
    got = _np(D.box_clip(boxes, im_info))
    want = boxes.copy()
    want[..., 0] = np.clip(want[..., 0], 0, 49)
    want[..., 1] = np.clip(want[..., 1], 0, 39)
    want[..., 2] = np.clip(want[..., 2], 0, 49)
    want[..., 3] = np.clip(want[..., 3], 0, 39)
    np.testing.assert_allclose(got, want, rtol=1e-5)


# ---------------------------------------------------------------------------
# matching
# ---------------------------------------------------------------------------

def _np_bipartite(dist):
    r, c = dist.shape
    match = np.full(c, -1, np.int32)
    mdist = np.zeros(c, np.float32)
    row_free = np.ones(r, bool)
    for _ in range(min(r, c)):
        masked = np.where(row_free[:, None] & (match < 0)[None, :]
                          & (dist > 1e-6), dist, -1.0)
        i, j = np.unravel_index(np.argmax(masked), masked.shape)
        if masked[i, j] <= 0:
            break
        match[j] = i
        mdist[j] = dist[i, j]
        row_free[i] = False
    return match, mdist


def test_bipartite_match():
    rng = np.random.default_rng(3)
    dist = rng.uniform(0, 1, (5, 9)).astype(np.float32)
    idx, md = D.bipartite_match(dist)
    want_idx, want_dist = _np_bipartite(dist)
    np.testing.assert_array_equal(_np(idx)[0], want_idx)
    np.testing.assert_allclose(_np(md)[0], want_dist, rtol=1e-5)


def test_bipartite_match_per_prediction():
    rng = np.random.default_rng(4)
    dist = rng.uniform(0, 1, (4, 10)).astype(np.float32)
    idx, md = D.bipartite_match(dist, match_type="per_prediction",
                                dist_threshold=0.6)
    want_idx, want_dist = _np_bipartite(dist)
    best = dist.max(0)
    arg = dist.argmax(0)
    fill = (want_idx < 0) & (best >= 0.6)
    want_idx[fill] = arg[fill]
    want_dist[fill] = best[fill]
    np.testing.assert_array_equal(_np(idx)[0], want_idx)
    np.testing.assert_allclose(_np(md)[0], want_dist, rtol=1e-5)


def test_target_assign():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((6, 3)).astype(np.float32)
    match = np.array([[2, -1, 0], [5, 1, -1]], np.int32)
    out, w = D.target_assign(x, match, mismatch_value=0)
    o = _np(out)
    np.testing.assert_allclose(o[0, 0], x[2])
    np.testing.assert_allclose(o[0, 1], 0.0)
    np.testing.assert_allclose(o[1, 0], x[5])
    np.testing.assert_array_equal(_np(w), [[1, 0, 1], [1, 1, 0]])


def test_target_assign_negative_indices():
    """Hard-negative slots keep mismatch_value but get weight 1
    (NegTargetAssignFunctor in target_assign_op.h)."""
    rng = np.random.default_rng(55)
    x = rng.standard_normal((6, 2)).astype(np.float32)
    match = np.array([[0, -1, 2], [-1, 1, -1]], np.int32)
    neg = np.array([1, 0, 2])          # image 0: prior 1; image 1: priors 0, 2
    neg_lens = np.array([1, 2])
    out, w = D.target_assign(x, match, negative_indices=neg,
                             negative_lengths=neg_lens, mismatch_value=0)
    np.testing.assert_array_equal(_np(w), [[1, 1, 1], [1, 1, 1]])
    np.testing.assert_allclose(_np(out)[0, 1], 0.0)  # still mismatch_value


def test_sigmoid_focal_loss():
    rng = np.random.default_rng(6)
    x = rng.standard_normal((5, 3)).astype(np.float32)
    label = np.array([1, 0, 3, 2, 0], np.int32)[:, None]
    fg = np.array([3], np.int32)
    got = _np(D.sigmoid_focal_loss(x, label, fg, alpha=0.25, gamma=2.0))
    p = 1 / (1 + np.exp(-x))
    tgt = (label == np.arange(1, 4)[None, :]).astype(np.float32)
    ce = -(tgt * np.log(p) + (1 - tgt) * np.log(1 - p))
    w = tgt * 0.25 * (1 - p) ** 2 + (1 - tgt) * 0.75 * p ** 2
    want = w * ce / 3.0
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------

def _np_nms(boxes, scores, valid, thr):
    order = np.argsort(-np.where(valid, scores, -np.inf), kind="stable")
    kept = []
    for i in order:
        if not valid[i]:
            continue
        ok = True
        for j in kept:
            ix1 = max(boxes[i, 0], boxes[j, 0]); iy1 = max(boxes[i, 1], boxes[j, 1])
            ix2 = min(boxes[i, 2], boxes[j, 2]); iy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            a1 = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            a2 = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            if inter / (a1 + a2 - inter + 1e-10) > thr:
                ok = False
                break
        if ok:
            kept.append(i)
    return kept


def _np_multiclass_nms(bboxes, scores, score_thr, nms_thr, nms_top_k,
                       keep_top_k, background):
    """Reference MultiClassNMS (multiclass_nms_op.cc): per-class NMS then
    global keep_top_k, output rows class-ascending / score-desc."""
    c, m = scores.shape
    sel = []  # (cls, box, score)
    for cl in range(c):
        if cl == background:
            continue
        s = scores[cl]
        valid = s > score_thr
        if nms_top_k > -1 and valid.sum() > nms_top_k:
            kth = np.sort(-s[valid])[:nms_top_k][-1]
            valid = valid & (s >= -kth)
        kept = _np_nms(bboxes, s, valid, nms_thr)
        for i in kept:
            sel.append((cl, i, s[i]))
    if keep_top_k > -1 and len(sel) > keep_top_k:
        sel.sort(key=lambda t: -t[2])
        sel = sel[:keep_top_k]
    sel.sort(key=lambda t: (t[0], -t[2]))
    return sel


def test_multiclass_nms3_parity():
    rng = np.random.default_rng(7)
    n, m, c = 2, 24, 4
    boxes = np.stack([_rand_boxes(rng, m, hi=40) for _ in range(n)])
    scores = rng.uniform(0, 1, (n, c, m)).astype(np.float32)
    out, index, cnt = D.multiclass_nms3(boxes, scores, score_threshold=0.3,
                                        nms_top_k=12, keep_top_k=8,
                                        nms_threshold=0.4, return_index=True)
    out, index, cnt = _np(out), _np(index), _np(cnt)
    k = out.shape[0] // n
    for b in range(n):
        want = _np_multiclass_nms(boxes[b], scores[b], 0.3, 0.4, 12, 8, 0)
        assert cnt[b] == len(want), (b, cnt[b], len(want))
        rows = out[b * k: b * k + cnt[b]]
        idxs = index[b * k: b * k + cnt[b]]
        for r, (cl, i, s) in enumerate(want):
            assert rows[r, 0] == cl
            np.testing.assert_allclose(rows[r, 1], s, rtol=1e-5)
            np.testing.assert_allclose(rows[r, 2:], boxes[b, i], rtol=1e-5)
            assert idxs[r] == b * m + i
        # padding rows carry label -1
        assert np.all(out[b * k + cnt[b]: (b + 1) * k, 0] == -1)


def test_multiclass_nms_wrappers():
    rng = np.random.default_rng(8)
    boxes = _rand_boxes(rng, 10, hi=30)[None]
    scores = rng.uniform(0, 1, (1, 3, 10)).astype(np.float32)
    out1, cnt1 = D.multiclass_nms(boxes, scores, score_threshold=0.2)
    out2, idx2, cnt2 = D.multiclass_nms2(boxes, scores, score_threshold=0.2)
    np.testing.assert_allclose(_np(out1), _np(out2))
    assert int(_np(cnt1)[0]) == int(_np(cnt2)[0])


def test_matrix_nms_parity():
    rng = np.random.default_rng(9)
    m, c = 16, 3
    boxes = _rand_boxes(rng, m, hi=40)[None]
    scores = rng.uniform(0, 1, (1, c, m)).astype(np.float32)
    out, idx, cnt = D.matrix_nms(boxes, scores, score_threshold=0.3,
                                 post_threshold=0.2, nms_top_k=10,
                                 keep_top_k=8, return_index=True)
    out, idx, cnt = _np(out), _np(idx), _np(cnt)

    # numpy re-derivation of NMSMatrix (matrix_nms_op.cc)
    def iou(a, b):
        ix1 = max(a[0], b[0]); iy1 = max(a[1], b[1])
        ix2 = min(a[2], b[2]); iy2 = min(a[3], b[3])
        inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
        a1 = (a[2] - a[0]) * (a[3] - a[1]); a2 = (b[2] - b[0]) * (b[3] - b[1])
        return inter / (a1 + a2 - inter + 1e-10)

    sel = []
    for cl in range(1, c):  # skip background 0
        s = scores[0, cl]
        perm = [i for i in np.argsort(-s, kind="stable") if s[i] > 0.3][:10]
        if not perm:
            continue
        iou_max = [0.0]
        for i in range(1, len(perm)):
            iou_max.append(max(iou(boxes[0, perm[i]], boxes[0, perm[j]])
                               for j in range(i)))
        if s[perm[0]] > 0.2:
            sel.append((cl, perm[0], s[perm[0]]))
        for i in range(1, len(perm)):
            md = 1.0
            for j in range(i):
                v = iou(boxes[0, perm[i]], boxes[0, perm[j]])
                md = min(md, (1 - v) / (1 - iou_max[j] + 1e-10))
            ds = md * s[perm[i]]
            if ds > 0.2:
                sel.append((cl, perm[i], ds))
    sel.sort(key=lambda t: -t[2])
    sel = sel[:8]
    sel.sort(key=lambda t: (t[0], -t[2]))
    assert cnt[0] == len(sel)
    for r, (cl, i, s) in enumerate(sel):
        assert out[r, 0] == cl
        np.testing.assert_allclose(out[r, 1], s, rtol=1e-4)
        np.testing.assert_allclose(out[r, 2:], boxes[0, i], rtol=1e-5)
        assert idx[r] == i


# ---------------------------------------------------------------------------
# proposals + FPN
# ---------------------------------------------------------------------------

def test_generate_proposals_v2():
    rng = np.random.default_rng(10)
    n, a, h, w = 1, 3, 4, 4
    scores = rng.uniform(0, 1, (n, a, h, w)).astype(np.float32)
    deltas = (rng.standard_normal((n, 4 * a, h, w)) * 0.1).astype(np.float32)
    img_size = np.array([[64.0, 64.0]], np.float32)
    anchors, variances = D.anchor_generator(
        np.zeros((1, 8, h, w), np.float32), anchor_sizes=[16.0, 32.0],
        aspect_ratios=[1.0, 2.0], variances=[1.0, 1.0, 1.0, 1.0],
        stride=[16.0, 16.0])
    anchors = _np(anchors)[:, :, :a]
    variances = _np(variances)[:, :, :a]
    rois, rscores, cnt = D.generate_proposals_v2(
        scores, deltas, img_size, anchors, variances, pre_nms_top_n=30,
        post_nms_top_n=10, nms_thresh=0.5, min_size=2.0)
    rois, rscores, cnt = _np(rois), _np(rscores), _np(cnt)
    assert rois.shape == (10, 4) and cnt.shape == (1,)
    k = int(cnt[0])
    assert 0 < k <= 10
    # valid rois are inside the image and at least min_size
    v = rois[:k]
    assert np.all(v[:, 0] >= 0) and np.all(v[:, 2] <= 63.0)
    assert np.all(v[:, 2] - v[:, 0] + 1 >= 2.0)
    # scores are descending
    assert np.all(np.diff(rscores[:k]) <= 1e-6)
    # padding is zero
    assert np.all(rois[k:] == 0)


def test_generate_proposals_v1_im_info():
    rng = np.random.default_rng(11)
    scores = rng.uniform(0, 1, (1, 2, 3, 3)).astype(np.float32)
    deltas = (rng.standard_normal((1, 8, 3, 3)) * 0.1).astype(np.float32)
    im_info = np.array([[48.0, 48.0, 1.0]], np.float32)
    anchors, variances = D.anchor_generator(
        np.zeros((1, 8, 3, 3), np.float32), anchor_sizes=[16.0],
        aspect_ratios=[1.0, 2.0], variances=[1.0, 1.0, 1.0, 1.0],
        stride=[16.0, 16.0])
    rois, rscores, cnt = D.generate_proposals(
        scores, deltas, im_info, _np(anchors), _np(variances),
        post_nms_top_n=6)
    assert _np(rois).shape == (6, 4)
    assert int(_np(cnt)[0]) > 0


def test_distribute_fpn_proposals():
    rng = np.random.default_rng(12)
    sizes = np.array([8, 16, 32, 64, 128, 224, 16, 100], np.float32)
    x1 = rng.uniform(0, 10, sizes.shape[0]).astype(np.float32)
    rois = np.stack([x1, x1, x1 + sizes, x1 + sizes], axis=1)
    multi_rois, restore, counts = D.distribute_fpn_proposals(
        rois, min_level=2, max_level=5, refer_level=4, refer_scale=224)
    counts = _np(counts)
    # numpy reference
    scale = np.sqrt((sizes + 1.0) ** 2)
    lvl = np.floor(np.log2(scale / 224 + 1e-6)) + 4
    lvl = np.clip(lvl, 2, 5).astype(int)
    for li in range(4):
        want_rows = rois[lvl == li + 2]
        got = _np(multi_rois[li])[: counts[li]]
        np.testing.assert_allclose(got, want_rows, rtol=1e-5)
    # restore index reorders the packed concat back to the original order
    packed = np.concatenate(
        [_np(multi_rois[li])[: counts[li]] for li in range(4)], axis=0)
    np.testing.assert_allclose(packed[_np(restore)[:, 0]], rois, rtol=1e-5)


def test_distribute_fpn_proposals_rois_num():
    """Packed multi-image input: per-level-per-image counts come back, and
    padded inputs are rejected loudly."""
    sizes = np.array([8, 224, 16, 100], np.float32)
    x1 = np.zeros(4, np.float32)
    rois = np.stack([x1, x1, x1 + sizes, x1 + sizes], axis=1)
    multi_rois, restore, per = D.distribute_fpn_proposals(
        rois, min_level=2, max_level=5, refer_level=4, refer_scale=224,
        rois_num=np.array([2, 2]))
    per = _np(per)  # [4 levels, 2 images]
    assert per.shape == (4, 2)
    assert per.sum() == 4
    # image 0 contributes the size-8 (level 2) and size-224 (level 4) rois
    assert per[0, 0] == 1 and per[2, 0] == 1
    with pytest.raises(ValueError):
        D.distribute_fpn_proposals(rois, 2, 5, 4, 224,
                                   rois_num=np.array([1, 2]))


def test_collect_fpn_proposals():
    rng = np.random.default_rng(13)
    r1 = _rand_boxes(rng, 5)
    r2 = _rand_boxes(rng, 5)
    s1 = rng.uniform(0, 1, 5).astype(np.float32)
    s2 = rng.uniform(0, 1, 5).astype(np.float32)
    counts = np.array([4, 3], np.int32)  # last rows of each level = padding
    rois, cnt = D.collect_fpn_proposals([r1, r2], [s1, s2], 2, 3,
                                        post_nms_top_n=5,
                                        rois_num_per_level=counts)
    allr = np.concatenate([r1[:4], r2[:3]])
    alls = np.concatenate([s1[:4], s2[:3]])
    order = np.argsort(-alls, kind="stable")[:5]
    np.testing.assert_allclose(_np(rois), allr[order], rtol=1e-5)
    assert int(_np(cnt)) == 5


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

def test_polygon_box_transform():
    rng = np.random.default_rng(14)
    x = rng.standard_normal((1, 8, 3, 4)).astype(np.float32)
    got = _np(D.polygon_box_transform(x))
    want = np.empty_like(x)
    for c in range(8):
        for hh in range(3):
            for ww in range(4):
                idx = ww if c % 2 == 0 else hh
                want[0, c, hh, ww] = 4 * idx - x[0, c, hh, ww]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_box_decoder_and_assign():
    rng = np.random.default_rng(15)
    m, c = 4, 3
    pb = _rand_boxes(rng, m)
    pbv = np.full((m, 4), 0.1, np.float32)
    tb = (rng.standard_normal((m, 4 * c)) * 0.2).astype(np.float32)
    sc = rng.uniform(0, 1, (m, c)).astype(np.float32)
    dec, assigned = D.box_decoder_and_assign(pb, pbv, tb, sc)
    dec, assigned = _np(dec), _np(assigned)
    assert dec.shape == (m, 4 * c) and assigned.shape == (m, 4)
    best = sc[:, 1:].argmax(1) + 1
    for i in range(m):
        np.testing.assert_allclose(assigned[i], dec[i, best[i] * 4:(best[i] + 1) * 4],
                                   rtol=1e-5)
    # spot-check one decode against the formula
    pw = pb[0, 2] - pb[0, 0] + 1
    cx = pb[0, 0] + 0.5 * pw + tb[0, 0] * 0.1 * pw
    w = np.exp(tb[0, 2] * 0.1) * pw
    np.testing.assert_allclose(dec[0, 0], cx - w / 2, rtol=1e-4)


def test_mine_hard_examples():
    loss = np.array([[0.9, 0.1, 0.8, 0.4, 0.7],
                     [0.2, 0.3, 0.1, 0.6, 0.5]], np.float32)
    match = np.array([[0, -1, -1, -1, -1],
                      [-1, 1, -1, 2, -1]], np.int32)
    sel, n_neg = D.mine_hard_examples(loss, match, neg_pos_ratio=2.0)
    sel, n_neg = _np(sel), _np(n_neg)
    # image 0: 1 positive → 2 negatives, the highest-loss unmatched: idx 2, 4
    assert n_neg[0] == 2 and set(np.where(sel[0])[0]) == {2, 4}
    # image 1: 2 positives → 4 negatives but only 3 unmatched exist
    assert n_neg[1] == 3 and set(np.where(sel[1])[0]) == {0, 2, 4}


def test_rpn_target_assign():
    """RPN fg/bg assignment + encoded targets (rpn_target_assign_op.cc
    semantics: argmax-per-gt anchors are fg even below the threshold;
    straddling anchors excluded; deterministic under paddle.seed)."""
    import paddle_tpu as paddle

    paddle.seed(0)
    anchors = np.array([
        [0, 0, 15, 15],      # IoU-matched to gt0
        [0, 0, 31, 31],      # partial overlap (argmax for gt0? no)
        [40, 40, 55, 55],    # far: bg
        [-20, -20, 5, 5],    # straddles: excluded
    ], np.float32)
    gt = np.array([[0, 0, 15, 15]], np.float32)
    im_info = np.array([[64, 64, 1.0]], np.float32)
    (res,) = D.rpn_target_assign(anchors, gt, im_info,
                                 rpn_batch_size_per_im=4,
                                 rpn_positive_overlap=0.7,
                                 rpn_negative_overlap=0.3,
                                 use_random=False)
    assert 0 in res["loc_index"]          # exact-match anchor is fg
    assert 3 not in res["score_index"]    # straddler excluded
    assert 2 in res["score_index"]        # far anchor sampled as bg
    fg_pos = list(res["score_index"]).index(0)
    assert res["tgt_label"][fg_pos] == 1
    # exact match → zero deltas
    np.testing.assert_allclose(res["tgt_bbox"][0], 0.0, atol=1e-6)
    np.testing.assert_allclose(res["bbox_inside_weight"], 1.0)

    # degenerate: no positive anchors → one zero-weight placeholder
    gt_far = np.array([[60, 60, 63, 63]], np.float32)
    (res2,) = D.rpn_target_assign(anchors[:3], gt_far, im_info,
                                  rpn_batch_size_per_im=4, use_random=False)
    assert res2["bbox_inside_weight"].sum() == 0.0


def test_rpn_target_assign_edge_cases():
    """Review r4: all-straddling images return empty targets; the
    degenerate placeholder is removed from bg (no duplicate score_index)."""
    import paddle_tpu as paddle

    paddle.seed(0)
    im_info = np.array([[8, 8, 1.0]], np.float32)
    big = np.array([[-10, -10, 30, 30]], np.float32)  # always straddles
    gt = np.array([[0, 0, 5, 5]], np.float32)
    (res,) = D.rpn_target_assign(big, gt, im_info, use_random=False)
    assert len(res["score_index"]) == 0 and len(res["loc_index"]) == 0

    anchors = np.array([[0, 0, 3, 3], [4, 4, 7, 7]], np.float32)
    gt_far = np.zeros((0, 4), np.float32)
    (res2,) = D.rpn_target_assign(anchors, gt_far, im_info,
                                  gt_counts=np.array([0]),
                                  rpn_batch_size_per_im=4, use_random=False)
    si = list(res2["score_index"])
    assert len(si) == len(set(si)), "no duplicate anchors in score_index"
    assert res2["bbox_inside_weight"].sum() == 0.0


def test_locality_aware_nms():
    """Numpy re-derivation of locality_aware_nms_op.cc: the sequential
    score-weighted merge pass followed by greedy NMS, single class."""
    boxes = np.array([
        [0.0, 0.0, 10.0, 10.0],
        [1.0, 1.0, 11.0, 11.0],   # merges into box 0 (IoU ~0.68)
        [20.0, 20.0, 30.0, 30.0],
        [21.0, 21.0, 31.0, 31.0],  # merges into box 2
        [50.0, 50.0, 60.0, 60.0],
    ], np.float32)
    scores = np.array([[0.9, 0.6, 0.8, 0.7, 0.3]], np.float32)  # [C=1, M]

    def np_iou(a, b):
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        iw, ih = max(ix2 - ix1, 0), max(iy2 - iy1, 0)
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1]) +
              (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    # reference merge pass
    bx, sc = boxes.copy(), scores[0].copy()
    skip = np.ones(5, bool)
    head = -1
    for i in range(5):
        if head > -1:
            ov = np_iou(bx[i], bx[head])
            if ov > 0.5:
                bx[head] = (bx[i] * sc[i] + bx[head] * sc[head]) / (sc[i] + sc[head])
                sc[head] += sc[i]
            else:
                skip[head] = False
                head = i
        else:
            head = i
    if head > -1:
        skip[head] = False

    out, cnt = D.locality_aware_nms(
        boxes[None], scores[None], score_threshold=0.01, nms_threshold=0.5,
        normalized=True)
    out = np.asarray(out._data if hasattr(out, "_data") else out)
    cnt = np.asarray(cnt._data if hasattr(cnt, "_data") else cnt)
    assert cnt[0] == 3  # three merged clusters survive
    got_rows = out[:3]
    # expected: merged boxes with accumulated scores, score-descending
    exp = sorted(
        [(sc[i], bx[i]) for i in range(5) if not skip[i]],
        key=lambda t: -t[0])
    for row, (es, eb) in zip(got_rows, exp):
        assert row[0] == 0.0  # class label
        np.testing.assert_allclose(row[1], es, rtol=1e-5)
        np.testing.assert_allclose(row[2:], eb, rtol=1e-5)


def test_locality_aware_nms_polygon_raises():
    with pytest.raises(NotImplementedError):
        D.locality_aware_nms(np.zeros((1, 3, 8), np.float32),
                             np.zeros((1, 1, 3), np.float32))


def test_generate_proposal_labels():
    """Numpy re-derivation of generate_proposal_labels_op.cc
    SampleRoisForOneImage (use_random=False for determinism)."""
    import paddle_tpu as paddle

    paddle.seed(0)
    gt_boxes = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
    gt_classes = np.array([3, 7], np.int64)
    is_crowd = np.array([0, 0], np.int64)
    rois = np.array([
        [1, 1, 11, 11],     # IoU with gt0 high -> fg label 3
        [19, 19, 29, 29],   # fg label 7
        [40, 40, 50, 50],   # no overlap -> bg
        [0, 0, 40, 40],     # IoU ~0.07 with gt0 -> bg
    ], np.float32)
    im_info = np.array([[60, 60, 1.0]], np.float32)
    cls = 8
    (res,) = D.generate_proposal_labels(
        rois, gt_classes, is_crowd, gt_boxes, im_info,
        batch_size_per_im=6, fg_fraction=0.5, fg_thresh=0.5,
        bg_thresh_hi=0.5, bg_thresh_lo=0.0,
        bbox_reg_weights=(1.0, 1.0, 1.0, 1.0), class_nums=cls,
        use_random=False)

    # boxes = [gt0, gt1, roi0..roi3]; fg = gt0(label3), gt1(label7),
    # roi0(label3), roi1(label7) but capped at 6*0.5=3 fg -> first 3
    labels = res["labels_int32"]
    assert list(labels[:3]) == [3, 7, 3]
    assert np.all(labels[3:] == 0)
    assert res["rois"].shape[1] == 4
    assert res["bbox_targets"].shape == (len(labels), 4 * cls)
    # fg rows put their delta in the class slot, inside weights 1 there
    for i, lbl in enumerate(labels):
        if lbl > 0:
            sl = res["bbox_inside_weights"][i, 4 * lbl: 4 * lbl + 4]
            np.testing.assert_array_equal(sl, 1.0)
            assert res["bbox_inside_weights"][i].sum() == 4.0
        else:
            assert res["bbox_inside_weights"][i].sum() == 0.0
    # the gt rows ride along as perfect-overlap fg: delta == 0
    np.testing.assert_allclose(res["bbox_targets"][0, 12:16], 0.0, atol=1e-6)
    np.testing.assert_allclose(res["max_overlap_with_gt"][0], 1.0)

    # im_scale round trip: rpn_rois arrive in the scaled image (divided by
    # im_scale internally), gt_boxes stay in original coordinates — 2x-
    # scaled rois with im_scale=2 make the same selection, and output rois
    # come back multiplied by im_scale
    im_info2 = np.array([[120, 120, 2.0]], np.float32)
    (res2,) = D.generate_proposal_labels(
        rois * 2.0, gt_classes, is_crowd, gt_boxes, im_info2,
        batch_size_per_im=6, fg_fraction=0.5, fg_thresh=0.5,
        bg_thresh_hi=0.5, bg_thresh_lo=0.0,
        bbox_reg_weights=(1.0, 1.0, 1.0, 1.0), class_nums=cls,
        use_random=False)
    np.testing.assert_array_equal(res2["labels_int32"], labels)
    np.testing.assert_allclose(res2["rois"], res["rois"] * 2.0, rtol=1e-5)


def test_generate_proposal_labels_crowd_and_cascade():
    gt_boxes = np.array([[0, 0, 10, 10]], np.float32)
    rois = np.array([[1, 1, 11, 11], [2, 2, 12, 12]], np.float32)
    im_info = np.array([[60, 60, 1.0]], np.float32)
    # crowd gt: its own row must not become fg
    (res,) = D.generate_proposal_labels(
        rois, np.array([5]), np.array([1]), gt_boxes, im_info,
        batch_size_per_im=4, use_random=False, class_nums=6)
    assert res["labels_int32"][0] == 0 or len(res["labels_int32"]) <= 3
    # cascade: max_overlap filter keeps only confident rois, no subsample
    (resc,) = D.generate_proposal_labels(
        rois, np.array([5]), np.array([0]), gt_boxes, im_info,
        is_cascade_rcnn=True, max_overlap=np.array([0.9, 0.1]),
        fg_thresh=0.5, use_random=False, class_nums=6)
    # roi1 (overlap 0.1) filtered out; gt + roi0 remain as candidates
    assert len(resc["labels_int32"]) == 2


def test_roi_perspective_transform_axis_aligned():
    """An axis-aligned rectangular quad reduces the perspective warp to a
    plain resize sample of the sub-rectangle — re-derivable in numpy
    (roi_perspective_transform_op.cc get_transform_matrix/get_source_coords).
    """
    rng = np.random.default_rng(8)
    H, W = 12, 12
    img = rng.standard_normal((1, 2, H, W)).astype(np.float32)
    # rectangle (2,3)-(9,3)-(9,8)-(2,8) in clockwise point order
    rois = np.array([[2, 3, 9, 3, 9, 8, 2, 8]], np.float32)
    th, tw = 4, 6
    out, mask, tm = D.roi_perspective_transform(img, rois, th, tw, 1.0)
    out = np.asarray(out._data if hasattr(out, "_data") else out)
    mask = np.asarray(mask._data if hasattr(mask, "_data") else mask)
    tm = np.asarray(tm._data if hasattr(tm, "_data") else tm)
    assert out.shape == (1, 2, th, tw) and mask.shape == (1, 1, th, tw)
    assert tm.shape == (1, 9)

    # numpy re-derivation of the matrix + sampling for this quad
    x0, y0, x1, y1, x2, y2, x3, y3 = rois[0]
    len1 = np.hypot(x0 - x1, y0 - y1); len2 = np.hypot(x1 - x2, y1 - y2)
    len3 = np.hypot(x2 - x3, y2 - y3); len4 = np.hypot(x3 - x0, y3 - y0)
    est_h = (len2 + len4) / 2; est_w = (len1 + len3) / 2
    nh = max(2, th)
    nw = np.round(est_w * (nh - 1) / est_h) + 1
    nw = max(2, min(nw, tw))
    dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
    dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
    den = dx1 * dy2 - dx2 * dy1 + 1e-5
    m = np.zeros(9)
    m[6] = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
    m[7] = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
    m[8] = 1
    m[3] = (y1 - y0 + m[6] * (nw - 1) * y1) / (nw - 1)
    m[4] = (y3 - y0 + m[7] * (nh - 1) * y3) / (nh - 1)
    m[5] = y0
    m[0] = (x1 - x0 + m[6] * (nw - 1) * x1) / (nw - 1)
    m[1] = (x3 - x0 + m[7] * (nh - 1) * x3) / (nh - 1)
    m[2] = x0
    np.testing.assert_allclose(tm[0], m, atol=1e-5, rtol=1e-4)

    def bilinear(img_c, ih, iw):
        iwc, ihc = np.clip(iw, 0, W - 1), np.clip(ih, 0, H - 1)
        wf, hf = int(np.floor(iwc)), int(np.floor(ihc))
        wc, hc = min(wf + 1, W - 1), min(hf + 1, H - 1)
        fw, fh = iwc - wf, ihc - hf
        return (img_c[hf, wf] * (1 - fw) * (1 - fh)
                + img_c[hc, wf] * (1 - fw) * fh
                + img_c[hc, wc] * fw * fh + img_c[hf, wc] * fw * (1 - fh))

    for oh in range(th):
        for ow in range(tw):
            u = m[0] * ow + m[1] * oh + m[2]
            v = m[3] * ow + m[4] * oh + m[5]
            wq = m[6] * ow + m[7] * oh + m[8]
            iw_, ih_ = u / wq, v / wq
            inside = (2 - 1e-4 <= iw_ <= 9 + 1e-4
                      and 3 - 1e-4 <= ih_ <= 8 + 1e-4)
            if mask[0, 0, oh, ow]:
                assert inside
                np.testing.assert_allclose(
                    out[0, 0, oh, ow], bilinear(img[0, 0], ih_, iw_),
                    atol=1e-4, rtol=1e-4)
            else:
                assert out[0, 0, oh, ow] == 0.0


def test_roi_perspective_transform_mask_outside():
    """Grid points the quad doesn't cover are zero/masked."""
    img = np.ones((1, 1, 10, 10), np.float32)
    # narrow diagonal-ish quad leaves grid corners outside
    rois = np.array([[0, 0, 9, 0, 9, 2, 0, 2]], np.float32)
    out, mask, _ = D.roi_perspective_transform(img, rois, 8, 8, 1.0)
    mask = np.asarray(mask._data if hasattr(mask, "_data") else mask)
    out = np.asarray(out._data if hasattr(out, "_data") else out)
    # some rows map below y=2 -> still inside; all sampled values are 1
    assert mask.sum() > 0
    np.testing.assert_allclose(out[0, 0][mask[0, 0] > 0], 1.0)


def test_generate_mask_labels():
    """Rectangle polygons give exact rasterized targets; class-slot
    expansion follows ExpandMaskTarget (-1 elsewhere)."""
    im_info = np.array([[60, 60, 1.0]], np.float32)
    gt_classes = np.array([2, 3], np.int64)
    is_crowd = np.array([0, 0], np.int64)
    # gt0: square (0,0)-(8,8); gt1: square (20,20)-(28,28)
    segms = [[[0.0, 0.0, 8.0, 0.0, 8.0, 8.0, 0.0, 8.0]],
             [[20.0, 20.0, 28.0, 20.0, 28.0, 28.0, 20.0, 28.0]]]
    rois = np.array([
        [0, 0, 8, 8],       # fg on gt0
        [19, 19, 29, 29],   # fg on gt1
        [40, 40, 50, 50],   # bg
    ], np.float32)
    labels = np.array([2, 3, 0], np.int32)
    res = 8
    ncls = 5
    (r,) = D.generate_mask_labels(im_info, gt_classes, is_crowd, segms, rois,
                                  labels, num_classes=ncls, resolution=res)
    assert r["mask_rois"].shape == (2, 4)
    np.testing.assert_array_equal(r["roi_has_mask_int32"], [0, 1])
    mt = r["mask_int32"]
    assert mt.shape == (2, ncls * res * res)
    m_sq = res * res
    # roi0/class2 slot: roi == polygon box -> full ones
    slot = mt[0, m_sq * 2: m_sq * 3].reshape(res, res)
    np.testing.assert_array_equal(slot, 1)
    # other slots stay -1
    assert np.all(mt[0, : m_sq * 2] == -1) and np.all(mt[0, m_sq * 3:] == -1)
    # roi1 covers gt1's square (20..28) within (19..29): interior ones,
    # border ring zeros — check center vs corner
    slot1 = mt[1, m_sq * 3: m_sq * 4].reshape(res, res)
    assert slot1[res // 2, res // 2] == 1
    assert slot1[0, 0] == 0

    # no fg rois: degenerate -1 target
    (r2,) = D.generate_mask_labels(im_info, gt_classes, is_crowd, segms,
                                   rois, np.zeros(3, np.int32),
                                   num_classes=ncls, resolution=res)
    assert r2["mask_int32"].shape == (1, ncls * m_sq)
    assert np.all(r2["mask_int32"] == -1)


def test_deformable_psroi_pooling():
    """Numpy re-derivation of deformable_psroi_pooling_op.cu (forward)."""
    rng = np.random.default_rng(9)
    N, od, gh, gw = 1, 2, 2, 2
    C = od * gh * gw
    H = W = 8
    x = rng.standard_normal((N, C, H, W)).astype(np.float32)
    rois = np.array([[1, 1, 5, 5], [0, 2, 6, 7]], np.float32)
    ph = pw = 2
    sp = 2
    trans = rng.uniform(-0.5, 0.5, (2, 2, ph, pw)).astype(np.float32)
    tstd = 0.1
    out, cnt = D.deformable_psroi_pooling(
        x, rois, trans, spatial_scale=1.0, output_dim=od,
        group_size=(gh, gw), pooled_height=ph, pooled_width=pw,
        sample_per_part=sp, trans_std=tstd)
    out = np.asarray(out._data if hasattr(out, "_data") else out)
    cnt = np.asarray(cnt._data if hasattr(cnt, "_data") else cnt)

    def bilinear(plane, wq, hq):
        wq, hq = min(max(wq, 0.0), W - 1), min(max(hq, 0.0), H - 1)
        wf, hf = int(np.floor(wq)), int(np.floor(hq))
        wc, hc = min(wf + 1, W - 1), min(hf + 1, H - 1)
        fw, fh = wq - wf, hq - hf
        return (plane[hf, wf] * (1 - fw) * (1 - fh)
                + plane[hc, wf] * (1 - fw) * fh
                + plane[hc, wc] * fw * fh + plane[hf, wc] * fw * (1 - fh))

    ncls = trans.shape[1] // 2
    cec = od // ncls
    exp = np.zeros((2, od, ph, pw), np.float32)
    expc = np.zeros((2, od, ph, pw), np.float32)
    for n in range(2):
        r = rois[n]
        rsw, rsh = round(r[0]) - 0.5, round(r[1]) - 0.5
        rew, reh = round(r[2]) + 1 - 0.5, round(r[3]) + 1 - 0.5
        rw, rh = max(rew - rsw, 0.1), max(reh - rsh, 0.1)
        bh, bw = rh / ph, rw / pw
        sbh, sbw = bh / sp, bw / sp
        for ct in range(od):
            cid = ct // cec
            for phi in range(ph):
                for pwi in range(pw):
                    pth = int(np.floor(phi / ph * ph))
                    ptw = int(np.floor(pwi / pw * pw))
                    tx = trans[n, 2 * cid, pth, ptw] * tstd
                    ty = trans[n, 2 * cid + 1, pth, ptw] * tstd
                    ws = pwi * bw + rsw + tx * rw
                    hs = phi * bh + rsh + ty * rh
                    g_w = min(max(pwi * gw // pw, 0), gw - 1)
                    g_h = min(max(phi * gh // ph, 0), gh - 1)
                    ch = (ct * gh + g_h) * gw + g_w
                    s = 0.0; k = 0
                    for ih in range(sp):
                        for iw in range(sp):
                            wq = ws + iw * sbw
                            hq = hs + ih * sbh
                            if (wq < -0.5 or wq > W - 0.5
                                    or hq < -0.5 or hq > H - 0.5):
                                continue
                            s += bilinear(x[0, ch], wq, hq)
                            k += 1
                    exp[n, ct, phi, pwi] = 0.0 if k == 0 else s / k
                    expc[n, ct, phi, pwi] = k
    np.testing.assert_allclose(out, exp, atol=1e-4, rtol=1e-4)
    np.testing.assert_array_equal(cnt, expc)


def test_deformable_psroi_pooling_no_trans_grad():
    """no_trans mode == plain PS-RoI average; grads flow to x and trans."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.vision.detection import deformable_psroi_pooling as dp

    x = jnp.asarray(np.random.default_rng(10).standard_normal((1, 4, 6, 6)),
                    jnp.float32)
    rois = jnp.asarray([[0, 0, 5, 5]], jnp.float32)

    def loss(x):
        out, _ = dp(x, rois, no_trans=True, output_dim=1, group_size=(2, 2),
                    pooled_height=2, pooled_width=2, sample_per_part=2)
        a = out._data if hasattr(out, "_data") else out
        return jnp.sum(a ** 2)

    g = jax.grad(loss)(x)
    assert np.isfinite(np.asarray(g)).all() and np.abs(np.asarray(g)).sum() > 0


def test_roi_perspective_transform_grad_flows():
    """Review r5: the warp is differentiable w.r.t. the feature map (the
    reference registers an X-grad kernel)."""
    import jax
    import jax.numpy as jnp

    img = jnp.asarray(np.random.default_rng(12).standard_normal((1, 1, 10, 10)),
                      jnp.float32)
    rois = np.array([[1, 1, 8, 1, 8, 8, 1, 8]], np.float32)

    def loss(x):
        out, _m, _t = D.roi_perspective_transform(x, rois, 4, 4, 1.0)
        a = out._data if hasattr(out, "_data") else out
        return jnp.sum(a ** 2)

    g = np.asarray(jax.grad(loss)(img))
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_retinanet_target_assign():
    """rpn_target_assign_op.cc RetinanetTargetAssignKernel: no subsampling,
    class labels for fg, crowd gts filtered, fg_num = fg + 1."""
    anchors = np.array([
        [0, 0, 10, 10],    # high IoU with gt0 -> fg class 3
        [20, 20, 30, 30],  # high IoU with gt1 (crowd -> filtered)
        [50, 50, 60, 60],  # no overlap -> bg
        [3, 3, 12, 12],    # IoU ~0.41 with gt0 -> between 0.4/0.5 -> ignored
    ], np.float32)
    gt = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
    gtl = np.array([3, 5], np.int64)
    crowd = np.array([0, 1], np.int64)
    im_info = np.array([[100, 100, 1.0]], np.float32)
    (res,) = D.retinanet_target_assign(anchors, gt, gtl, crowd, im_info,
                                       positive_overlap=0.5,
                                       negative_overlap=0.4)
    assert list(res["loc_index"]) == [0]
    assert res["tgt_label"][0] == 3              # class label, not binary
    # anchor1 no longer matches anything after crowd filtering -> bg;
    # anchor3 sits in the ignore band
    si = set(res["score_index"].tolist())
    assert 1 in si and 2 in si and 3 not in si
    assert res["fg_num"] == 2                    # fg(1) + 1
    # encoded deltas are zero for the exact-match anchor
    np.testing.assert_allclose(res["tgt_bbox"][0], 0.0, atol=1e-6)
