"""Concurrency doctor (ISSUE 14): lock-discipline & race analysis tests.

Covers the four host rules with planted-bug/negative-twin pairs driven
through the real CLI exit contract, the annotation-parsing edge cases
(aliased locks, Condition guards, late lock assignment, finally-released
manual acquire), the runtime instrumented-lock journal (record -> dump ->
merge -> cycle check), the r9 CLI hardening contract, and the shipped
tree itself (the zero-HIGH smoke gate + the regression tests for the
races the pre-fix lint surfaced, most notably the lock-free RadixCache).
"""
import json
import os
import threading
import textwrap

import pytest

from paddle_tpu.analysis import lockmodel
from paddle_tpu.analysis.cli import main as cli_main
from paddle_tpu.analysis.findings import Severity
from paddle_tpu.analysis.hostrace import (
    HOST_SCHEMA_VERSION,
    analyze_host,
    build_context,
)
from paddle_tpu.analysis.rules import HostRule, default_host_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _plant(tmp_path, name, source):
    p = tmp_path / f"{name}.py"
    p.write_text(textwrap.dedent(source))
    return str(p)


def _lint(tmp_path, *paths, extra_args=()):
    """Run the real CLI on planted files; returns (exit_code, report)."""
    out = tmp_path / "host_report.json"
    args = ["--host", "--host-journal", "none", "--out", str(out)]
    for p in paths:
        args += ["--host-path", p]
    args += list(extra_args)
    rc = cli_main(args)
    with open(out) as fh:
        return rc, json.load(fh)


def _rules_hit(report, rule):
    return [f for f in report["findings"] if f["rule"] == rule]


# ---------------------------------------------------------------------------
# planted twins, one per rule class, via the CLI exit contract
# ---------------------------------------------------------------------------
class TestPlantedGuardedBy:
    BUGGY = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.value = 0  # guarded-by: self._lock

        def bump(self):
            with self._lock:
                self.value += 1

        def reset(self):
            self.value = 0
    """
    FIXED = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.value = 0  # guarded-by: self._lock

        def bump(self):
            with self._lock:
                self.value += 1

        def reset(self):
            with self._lock:
                self.value = 0
    """

    def test_planted_violation_exits_1(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "buggy", self.BUGGY))
        assert rc == 1
        hits = _rules_hit(rep, "host-guarded-by")
        assert any(f["severity"] == "HIGH" and "reset" in f["message"]
                   for f in hits)

    def test_negative_twin_exits_0(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "fixed", self.FIXED))
        assert rc == 0
        assert not _rules_hit(rep, "host-guarded-by")


class TestPlantedLockOrder:
    BUGGY = """
    import threading

    class TwoLocks:
        def __init__(self):
            self.alpha_lock = threading.Lock()
            self.beta_lock = threading.Lock()

        def forward(self):
            with self.alpha_lock:
                with self.beta_lock:
                    pass

        def backward(self):
            with self.beta_lock:
                with self.alpha_lock:
                    pass
    """
    FIXED = """
    import threading

    class TwoLocks:
        def __init__(self):
            self.alpha_lock = threading.Lock()
            self.beta_lock = threading.Lock()

        def forward(self):
            with self.alpha_lock:
                with self.beta_lock:
                    pass

        def backward(self):
            with self.alpha_lock:
                with self.beta_lock:
                    pass
    """

    def test_planted_inversion_exits_1(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "buggy", self.BUGGY))
        assert rc == 1
        hits = _rules_hit(rep, "host-lock-order")
        assert hits and hits[0]["severity"] == "HIGH"
        assert "alpha_lock" in hits[0]["message"]

    def test_negative_twin_exits_0(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "fixed", self.FIXED))
        assert rc == 0
        assert not _rules_hit(rep, "host-lock-order")


class TestPlantedBlockingUnderLock:
    BUGGY = """
    import threading
    import time

    class HealthLoop:
        def __init__(self):
            self._lock = threading.Lock()
            self.alive = True  # guarded-by: self._lock

        def probe(self):
            with self._lock:
                time.sleep(0.5)
                self.alive = True
    """
    FIXED = """
    import threading
    import time

    class HealthLoop:
        def __init__(self):
            self._lock = threading.Lock()
            self.alive = True  # guarded-by: self._lock

        def probe(self):
            time.sleep(0.5)
            with self._lock:
                self.alive = True
    """
    INTENTIONAL = """
    import threading
    import time

    class HealthLoop:
        def __init__(self):
            # serializes the whole probe by design
            self._lock = threading.Lock()  # hostrace: blocking-ok

        def probe(self):
            with self._lock:
                time.sleep(0.5)
    """

    def test_planted_blocking_exits_1(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "buggy", self.BUGGY))
        assert rc == 1
        hits = _rules_hit(rep, "host-blocking-under-lock")
        assert any(f["severity"] == "HIGH" and "sleep" in f["message"]
                   for f in hits)

    def test_negative_twin_exits_0(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "fixed", self.FIXED))
        assert rc == 0
        assert not _rules_hit(rep, "host-blocking-under-lock")

    def test_blocking_ok_annotation_downgrades_to_info(self, tmp_path):
        rc, rep = _lint(tmp_path,
                        _plant(tmp_path, "meant", self.INTENTIONAL))
        assert rc == 0  # recognized as intentionally annotated
        hits = _rules_hit(rep, "host-blocking-under-lock")
        assert hits and hits[0]["severity"] == "INFO"
        assert hits[0]["details"]["intentional"] is True


class TestPlantedToctou:
    BUGGY = """
    import threading

    class Gate:
        def __init__(self):
            self._lock = threading.Lock()
            self.budget = 10  # guarded-by: self._lock

        def admit(self, cost):
            with self._lock:
                avail = self.budget
            if avail >= cost:
                with self._lock:
                    self.budget = self.budget - cost
                return True
            return False
    """
    FIXED = """
    import threading

    class Gate:
        def __init__(self):
            self._lock = threading.Lock()
            self.budget = 10  # guarded-by: self._lock

        def admit(self, cost):
            with self._lock:
                avail = self.budget
                if avail >= cost:
                    self.budget = avail - cost
                    return True
                return False
    """

    def test_planted_toctou_exits_1(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "buggy", self.BUGGY))
        assert rc == 1
        hits = _rules_hit(rep, "host-toctou")
        assert hits and hits[0]["severity"] == "HIGH"
        assert hits[0]["details"]["attr"] == "budget"

    def test_negative_twin_exits_0(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "fixed", self.FIXED))
        assert rc == 0
        assert not _rules_hit(rep, "host-toctou")

    def test_atomic_setdefault_is_not_an_act(self, tmp_path):
        src = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}  # guarded-by: self._lock

            def get_or_build(self, key):
                with self._lock:
                    val = self._items.get(key)
                if val is None:
                    val = object()
                    with self._lock:
                        self._items.setdefault(key, val)
                return val
        """
        rc, rep = _lint(tmp_path, _plant(tmp_path, "cache", src))
        assert rc == 0
        assert not _rules_hit(rep, "host-toctou")


# ---------------------------------------------------------------------------
# annotation-parsing edge cases
# ---------------------------------------------------------------------------
class TestAnnotationEdgeCases:
    def test_aliased_lock_counts_as_held(self, tmp_path):
        src = """
        import threading

        class Aliased:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = 0  # guarded-by: self._lock

            def bump(self):
                lock = self._lock
                with lock:
                    self.value += 1
        """
        rc, rep = _lint(tmp_path, _plant(tmp_path, "aliased", src))
        assert rc == 0
        assert not _rules_hit(rep, "host-guarded-by")

    def test_condition_lock_counts_as_guard(self, tmp_path):
        # a Condition wrapping an explicit lock guards the same state as
        # the lock itself: holding EITHER satisfies the declaration
        src = """
        import threading

        class CondGuarded:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self.items = []  # guarded-by: self._lock

            def put(self, x):
                with self._cond:
                    self.items.append(x)
                    self._cond.notify_all()

            def direct(self, x):
                with self._lock:
                    self.items.append(x)
        """
        rc, rep = _lint(tmp_path, _plant(tmp_path, "cond", src))
        assert rc == 0
        assert not _rules_hit(rep, "host-guarded-by")

    def test_bare_condition_as_declared_guard(self, tmp_path):
        src = """
        import threading

        class CondOnly:
            def __init__(self):
                self._cond = threading.Condition()
                self.queue = []  # guarded-by: self._cond

            def put(self, x):
                with self._cond:
                    self.queue.append(x)

            def steal(self):
                return self.queue.pop()
        """
        rc, rep = _lint(tmp_path, _plant(tmp_path, "condonly", src))
        assert rc == 1  # steal() mutates bare -> HIGH
        hits = _rules_hit(rep, "host-guarded-by")
        assert any("steal" in f["message"] for f in hits)

    def test_lock_assigned_after_guarded_attr(self, tmp_path):
        # the annotation names a lock that is only assigned LATER in
        # __init__ — declaration order must not matter
        src = """
        import threading

        class LateLock:
            def __init__(self):
                self.value = 0  # guarded-by: self._lock
                self.other = "config"
                self._lock = threading.Lock()

            def bump(self):
                with self._lock:
                    self.value += 1

            def leak(self):
                self.value = -1
        """
        rc, rep = _lint(tmp_path, _plant(tmp_path, "late", src))
        assert rc == 1
        hits = _rules_hit(rep, "host-guarded-by")
        assert any(f["severity"] == "HIGH" and "leak" in f["message"]
                   for f in hits)
        # the guard resolved (no unknown-lock config finding)
        assert not any("unknown lock" in f["message"] for f in hits)

    def test_finally_released_manual_acquire(self, tmp_path):
        src = """
        import threading

        class Manual:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = 0  # guarded-by: self._lock

            def bump(self):
                self._lock.acquire()
                try:
                    self.value += 1
                finally:
                    self._lock.release()

            def after(self):
                self._lock.acquire()
                self._lock.release()
                return self.value
        """
        rc, rep = _lint(tmp_path, _plant(tmp_path, "manual", src))
        hits = _rules_hit(rep, "host-guarded-by")
        # bump() is clean (held through try body); after() reads PAST the
        # release -> flagged (MEDIUM read, so exit stays 0 at --fail-on
        # high but the finding exists)
        assert not any("bump" in f["message"] for f in hits)
        assert any("after" in f["message"] for f in hits)
        assert rc == 0

    def test_unknown_guard_is_a_config_finding(self, tmp_path):
        src = """
        import threading

        class Typo:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = 0  # guarded-by: self._lokc

            def bump(self):
                with self._lock:
                    self.value += 1
        """
        rc, rep = _lint(tmp_path, _plant(tmp_path, "typo", src))
        hits = _rules_hit(rep, "host-guarded-by")
        assert any("unknown lock" in f["message"] for f in hits)

    def test_requires_annotation_seeds_and_verifies_callers(self, tmp_path):
        src = """
        import threading

        class Helperful:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = 0  # guarded-by: self._lock

            # hostrace: requires(self._lock)
            def _advance(self):
                self.state += 1

            def good(self):
                with self._lock:
                    self._advance()

            def bad(self):
                self._advance()
        """
        rc, rep = _lint(tmp_path, _plant(tmp_path, "helper", src))
        assert rc == 1
        hits = _rules_hit(rep, "host-guarded-by")
        # the helper body itself is clean (seeded held set) ...
        assert not any("_advance()" in f.get("source", "") for f in hits)
        # ... but the bare caller is the HIGH
        assert any("bad" in f["message"] and f["severity"] == "HIGH"
                   for f in hits)


# ---------------------------------------------------------------------------
# runtime journal: record -> dump -> merge -> cycle check
# ---------------------------------------------------------------------------
class TestRuntimeJournal:
    def test_recorder_names_repo_locks_and_merges(self, tmp_path):
        from paddle_tpu.serving.paged import PagePool
        from paddle_tpu.serving.scheduler import FCFSScheduler

        rec = lockmodel.LockOrderRecorder()
        with lockmodel.armed(rec):
            sched = FCFSScheduler([16], max_queue=4)
            pool = PagePool(8)
            # nest: scheduler condition -> pool lock
            with sched._cond:
                pool.alloc(1)
        assert rec.acquires > 0 and rec.locks_created >= 2
        jpath = str(tmp_path / "journal.json")
        lockmodel.write_journal(rec, jpath, meta={"source": "unit"})
        edges = lockmodel.load_journal(jpath)
        assert edges
        # persisted sites are repo-RELATIVE: the committed journal must
        # resolve against the static model on any checkout path
        assert all(not os.path.isabs(e["src_file"])
                   and e["src_file"].startswith("paddle_tpu/")
                   for e in edges)
        model = lockmodel.scan_modules(lockmodel.default_host_paths())
        named = lockmodel.journal_order_edges(model, edges)
        pairs = {(e.src, e.dst) for e in named}
        assert ("serving.scheduler.FCFSScheduler._cond",
                "serving.paged.PagePool._lock") in pairs
        graph = lockmodel.build_order_graph(model, edges)
        assert not graph.cycles()

    def test_runtime_inversion_creates_cycle(self, tmp_path):
        from paddle_tpu.serving.paged import PagePool
        from paddle_tpu.serving.scheduler import FCFSScheduler

        rec = lockmodel.LockOrderRecorder()
        with lockmodel.armed(rec):
            sched = FCFSScheduler([16], max_queue=4)
            pool = PagePool(8)
            with sched._cond:
                with pool._lock:
                    pass
            with pool._lock:
                with sched._cond:
                    pass
        model = lockmodel.scan_modules(lockmodel.default_host_paths())
        graph = lockmodel.build_order_graph(model, [
            dict(e) for e in rec.edge_list()])
        cycles = graph.cycles()
        assert cycles, "planted runtime inversion must surface as a cycle"
        ctx_nodes = {n for cyc in cycles for n in cyc}
        assert "serving.paged.PagePool._lock" in ctx_nodes

    def test_instrumented_lock_is_transparent(self):
        # Condition/wait/notify and with-statements must behave exactly
        # like the real primitives while armed
        from paddle_tpu.serving.scheduler import FCFSScheduler, Request

        rec = lockmodel.LockOrderRecorder()
        with lockmodel.armed(rec):
            sched = FCFSScheduler([16], max_queue=8)
            got = []

            def consumer():
                if sched.wait_for_work(timeout=5.0):
                    got.extend(sched.take_admissions(1))

            t = threading.Thread(target=consumer)
            t.start()
            sched.submit(Request([1, 2, 3]))
            t.join(5.0)
        assert not t.is_alive()
        assert len(got) == 1
        assert sched.in_admission() == 1

    def test_disarm_restores_factories(self):
        before_lock, before_rlock = threading.Lock, threading.RLock
        rec = lockmodel.LockOrderRecorder()
        with lockmodel.armed(rec):
            assert threading.Lock is not before_lock
        assert threading.Lock is before_lock
        assert threading.RLock is before_rlock

    def test_journal_schema_version_enforced(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"schema_version": 999, "edges": []}))
        with pytest.raises(ValueError, match="unsupported lock-journal"):
            lockmodel.load_journal(str(bad))


# ---------------------------------------------------------------------------
# CLI hardening (the r9 contract)
# ---------------------------------------------------------------------------
class TestCliContract:
    def test_unknown_host_only_is_usage_error(self):
        with pytest.raises(SystemExit) as e:
            cli_main(["--host", "--host-only", "no-such-rule"])
        assert e.value.code == 2

    def test_host_flags_require_host_mode(self):
        with pytest.raises(SystemExit) as e:
            cli_main(["--host-only", "host-toctou"])
        assert e.value.code == 2

    def test_missing_host_path_is_error(self, tmp_path):
        rc = cli_main(["--host", "--host-path",
                       str(tmp_path / "nope.py"),
                       "--out", str(tmp_path / "o.json")])
        assert rc == 2

    def test_duplicate_basenames_both_scanned(self, tmp_path):
        # two --host-path files sharing a basename must not shadow each
        # other — a shadowed planted HIGH would silently pass the gate
        d1, d2 = tmp_path / "a", tmp_path / "b"
        d1.mkdir(), d2.mkdir()
        (d1 / "mod.py").write_text(
            textwrap.dedent(TestPlantedLockOrder.FIXED))
        (d2 / "mod.py").write_text(
            textwrap.dedent(TestPlantedLockOrder.BUGGY))
        out = tmp_path / "r.json"
        rc = cli_main(["--host", "--host-journal", "none",
                       "--out", str(out),
                       "--host-path", str(d1), "--host-path", str(d2)])
        assert rc == 1
        with open(out) as fh:
            rep = json.load(fh)
        assert rep["meta"]["n_modules"] == 2
        assert _rules_hit(rep, "host-lock-order")

    def test_missing_journal_is_error(self, tmp_path):
        rc = cli_main(["--host",
                       "--host-journal", str(tmp_path / "no.json"),
                       "--out", str(tmp_path / "o.json")])
        assert rc == 2

    def test_host_only_narrows_rules(self, tmp_path):
        p = _plant(tmp_path, "buggy", TestPlantedLockOrder.BUGGY)
        rc, rep = _lint(tmp_path, p,
                        extra_args=["--host-only", "host-guarded-by"])
        # the inversion is invisible to the guarded-by rule
        assert rc == 0
        assert not _rules_hit(rep, "host-lock-order")

    def test_crashed_rule_reports_medium(self, tmp_path):
        class BrokenRule(HostRule):
            name = "host-broken"

            def run(self, ctx):
                raise RuntimeError("boom")

        report = analyze_host(
            paths=[("planted", _plant(tmp_path, "ok",
                                      TestPlantedLockOrder.FIXED))],
            journal="none", rules=[BrokenRule()])
        crashed = [f for f in report.findings if f.rule == "host-broken"]
        assert crashed and crashed[0].severity == Severity.MEDIUM
        assert "rule crashed" in crashed[0].message

    def test_corrupt_default_journal_degrades_to_medium(
            self, tmp_path, monkeypatch):
        # a stale/corrupt COMMITTED journal is a finding, not a usage
        # error: the lint still runs (static edges only) and says so
        bad = tmp_path / "journal.json"
        bad.write_text("{not json")
        monkeypatch.setattr(
            "paddle_tpu.analysis.hostrace.default_journal_path",
            lambda: str(bad))
        report = analyze_host(
            paths=[("ok", _plant(tmp_path, "ok",
                                 TestPlantedLockOrder.FIXED))])
        hits = [f for f in report.findings if f.rule == "host-journal"]
        assert hits and hits[0].severity == Severity.MEDIUM
        assert report.meta["n_runtime_edges"] == 0

    def test_unparseable_module_reports_medium(self, tmp_path):
        p = tmp_path / "broken.py"
        p.write_text("def nope(:\n")
        report = analyze_host(paths=[("broken", str(p))], journal="none")
        scan = [f for f in report.findings if f.rule == "host-scan"]
        assert scan and scan[0].severity == Severity.MEDIUM

    def test_artifact_is_schema_versioned(self, tmp_path):
        rc, rep = _lint(tmp_path, _plant(tmp_path, "fixed",
                                         TestPlantedLockOrder.FIXED))
        assert rep["meta"]["host_schema_version"] == HOST_SCHEMA_VERSION
        assert "schema_version" in rep

    def test_committed_artifact_matches_schema(self):
        path = os.path.join(REPO, "benchmarks", "analysis_host.json")
        with open(path) as fh:
            doc = json.load(fh)
        assert doc["meta"]["host_schema_version"] == HOST_SCHEMA_VERSION
        assert doc["meta"]["n_modules"] >= 8
        assert doc["counts"]["HIGH"] == 0


# ---------------------------------------------------------------------------
# the shipped tree: zero-HIGH gate + regression tests for the real fixes
# ---------------------------------------------------------------------------
class TestShippedTree:
    def test_real_tree_lints_clean(self):
        report = analyze_host(journal="none")
        assert report.meta["n_modules"] >= 8
        assert report.meta["lock_graph_acyclic"]
        highs = report.high()
        assert not highs, "\n".join(str(f) for f in highs)
        # every surviving finding is an INFO record of an INTENTIONAL,
        # annotated pattern — nothing silently suppressed
        assert all(f.severity == Severity.INFO for f in report.findings), \
            "\n".join(str(f) for f in report.findings)

    def test_default_rules_cover_all_four_classes(self):
        names = {r.name for r in default_host_rules()}
        assert {"host-guarded-by", "host-lock-order",
                "host-blocking-under-lock", "host-toctou"} <= names

    def test_committed_journal_merges_acyclic(self):
        jpath = os.path.join(REPO, "benchmarks", "hostrace_journal.json")
        if not os.path.exists(jpath):
            pytest.skip("no committed journal")
        ctx = build_context(journal=jpath)
        assert ctx.journal_edges, "committed journal has no edges"
        assert not ctx.graph.cycles()
        # the merged graph really contains runtime-origin edges
        assert any(e.origin == "runtime" for e in ctx.graph.edges)

    def test_radix_cache_is_thread_safe_now(self):
        """Regression for the pre-fix HIGH: RadixCache had NO lock while
        peek() (admission pricing, server threads) raced match/insert/
        evict (engine thread). With the lock, concurrent mixed ops must
        neither raise nor corrupt the pool's refcounts."""
        from paddle_tpu.serving.paged import PagePool, RadixCache

        pool = PagePool(512)
        cache = RadixCache(pool, page_size=4)
        prompts = [[i] * 8 for i in range(40)]
        errors = []
        stop = threading.Event()

        def engine_side():
            try:
                for i, p in enumerate(prompts):
                    pages = pool.alloc(2)
                    cache.insert(p, pages)
                    pool.release(pages)  # tree keeps its own reference
                    if i % 5 == 0:
                        cache.evict(1)
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)
            finally:
                stop.set()

        def server_side():
            try:
                while not stop.is_set():
                    for p in prompts:
                        cache.peek(p)
                        got = cache.match(p)
                        if got:
                            pool.release(got)
                    cache.hit_rate()
                    cache.resident_pages()
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        threads = [threading.Thread(target=engine_side)] + [
            threading.Thread(target=server_side) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert not errors, errors
        # refcount invariant: after dropping every tree reference the
        # pool must be exactly full again — any torn retain/release pair
        # under the race would break this
        cache.clear()
        assert pool.free_count() == pool.capacity

    def test_radix_lock_orders_before_pool_lock(self):
        """The fix's documented order (RadixCache._lock before
        PagePool._lock) is what the static model derives — the inverse
        would be a cycle with the evict-under-pressure path."""
        model = lockmodel.scan_modules(lockmodel.default_host_paths())
        edges = {(e.src, e.dst) for e in model.static_edges()}
        assert ("serving.paged.RadixCache._lock",
                "serving.paged.PagePool._lock") in edges
        graph = lockmodel.build_order_graph(model)
        assert not graph.cycles()
