"""ZeRO-offload: optimizer state on the host (pinned pool), device step
produces grads only. Parity: fleet sharding/offload_helper.py (fp32 masters
+ moments on CPU, updates computed there, cast params copied back).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
from paddle_tpu.models.gpt import (
    GPTForPretraining,
    GPTPretrainingCriterion,
    gpt_config,
)
from paddle_tpu.optimizer.optimizers import AdamW


@pytest.fixture(autouse=True)
def _mesh():
    dist.init_mesh({"dp": 8})
    yield
    dist.clear_mesh()


def _cfg():
    return gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                      num_layers=2, num_attention_heads=4,
                      max_position_embeddings=32, hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0)


def _build(offload, opt_cls=AdamW, lr=1e-3):
    paddle.seed(0)
    model = GPTForPretraining(_cfg())
    crit = GPTPretrainingCriterion()
    opt = opt_cls(learning_rate=lr, parameters=model.parameters())
    return model, ParallelTrainer(
        model, lambda out, y: crit(out, y), opt, dp_axis="dp",
        offload_optimizer=offload)


def test_offload_step_parity_with_device_optimizer():
    """SGD: update linear in grads ⇒ exact parity. (Adam would amplify the
    float noise of mathematically-zero k-bias grads into ±lr flips.)"""
    from paddle_tpu.optimizer.optimizers import SGD

    x = np.random.default_rng(0).integers(0, 64, (8, 16)).astype("int32")
    m1, t1 = _build(offload=False, opt_cls=SGD, lr=0.05)
    m2, t2 = _build(offload=True, opt_cls=SGD, lr=0.05)
    assert t2.opt_state is None  # nothing optimizer-side on device
    for _ in range(4):
        l1 = float(t1.step(x, x)._data)
        l2 = float(t2.step(x, x)._data)
        np.testing.assert_allclose(l1, l2, rtol=1e-5)
    for n in t1.params:
        np.testing.assert_allclose(
            np.asarray(t1.params[n]), np.asarray(t2.params[n]),
            rtol=2e-5, atol=1e-6, err_msg=n)


def test_offload_adam_slots_on_host_and_converges():
    x = np.random.default_rng(0).integers(0, 64, (8, 16)).astype("int32")
    m2, t2 = _build(offload=True)
    assert t2.opt_state is None
    losses = [float(t2.step(x, x)._data) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # Adam moments really live host-side
    leaf = next(iter(t2._host_slots.values()))["moment1"]
    assert isinstance(leaf, np.ndarray)
    assert np.abs(leaf).sum() > 0  # they are being updated


def test_offload_via_distributed_strategy():
    from paddle_tpu.distributed.fleet import DistributedStrategy

    paddle.seed(0)
    model = GPTForPretraining(_cfg())
    crit = GPTPretrainingCriterion()
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    strategy = DistributedStrategy()
    strategy.sharding = True
    strategy.sharding_configs = {"optimize_offload": True, "stage": 1}
    trainer = ParallelTrainer(model, lambda o, y: crit(o, y), opt,
                              dp_axis="dp", strategy=strategy)
    assert trainer.offload
    x = np.random.default_rng(1).integers(0, 64, (8, 16)).astype("int32")
    losses = [float(trainer.step(x, x)._data) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    trainer.sync_to_model()
