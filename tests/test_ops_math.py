"""Math/reduction op correctness vs numpy + gradient checks."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_eager_vs_jit, check_grad, check_output


def _rand(*shape):
    return np.random.uniform(0.1, 1.0, shape).astype(np.float32)


class TestUnary:
    @pytest.mark.parametrize(
        "name,np_fn",
        [
            ("exp", np.exp),
            ("log", np.log),
            ("sqrt", np.sqrt),
            ("tanh", np.tanh),
            ("abs", np.abs),
            ("sin", np.sin),
            ("cos", np.cos),
            ("floor", np.floor),
            ("ceil", np.ceil),
            ("square", np.square),
            ("sign", np.sign),
            ("log1p", np.log1p),
        ],
    )
    def test_forward(self, name, np_fn):
        check_output(getattr(paddle, name), np_fn, [_rand(3, 4)])

    @pytest.mark.parametrize("name", ["exp", "log", "sqrt", "tanh", "sin", "square"])
    def test_grad(self, name):
        np_fn = {"exp": np.exp, "log": np.log, "sqrt": np.sqrt, "tanh": np.tanh,
                 "sin": np.sin, "square": np.square}[name]
        check_grad(getattr(paddle, name), np_fn, [_rand(3, 4)])

    def test_sigmoid(self):
        check_output(paddle.sigmoid, lambda x: 1 / (1 + np.exp(-x)), [_rand(5)])

    def test_rsqrt(self):
        check_output(paddle.rsqrt, lambda x: 1 / np.sqrt(x), [_rand(5)], atol=1e-4)

    def test_clip(self):
        x = np.random.randn(4, 5).astype(np.float32)
        got = paddle.clip(paddle.to_tensor(x), -0.5, 0.5)
        np.testing.assert_allclose(got.numpy(), np.clip(x, -0.5, 0.5))


class TestBinary:
    @pytest.mark.parametrize(
        "name,np_fn",
        [
            ("add", np.add),
            ("subtract", np.subtract),
            ("multiply", np.multiply),
            ("divide", np.divide),
            ("maximum", np.maximum),
            ("minimum", np.minimum),
            ("pow", np.power),
        ],
    )
    def test_forward(self, name, np_fn):
        check_output(getattr(paddle, name), np_fn, [_rand(3, 4), _rand(3, 4)])

    def test_broadcast(self):
        check_output(paddle.add, np.add, [_rand(3, 1, 4), _rand(2, 1)])

    def test_grad_add_mul(self):
        check_grad(paddle.add, np.add, [_rand(3, 4), _rand(3, 4)], wrt=(0, 1))
        check_grad(paddle.multiply, np.multiply, [_rand(3, 4), _rand(3, 4)], wrt=(0, 1))

    def test_grad_broadcast(self):
        check_grad(paddle.add, np.add, [_rand(3, 4), _rand(4)], wrt=(0, 1))

    def test_dunders(self):
        a, b = paddle.to_tensor(_rand(3)), paddle.to_tensor(_rand(3))
        np.testing.assert_allclose((a + b).numpy(), a.numpy() + b.numpy(), rtol=1e-6)
        np.testing.assert_allclose((a - 1.0).numpy(), a.numpy() - 1.0, rtol=1e-6)
        np.testing.assert_allclose((2.0 * a).numpy(), 2.0 * a.numpy(), rtol=1e-6)
        np.testing.assert_allclose((a / b).numpy(), a.numpy() / b.numpy(), rtol=1e-6)
        np.testing.assert_allclose((-a).numpy(), -a.numpy(), rtol=1e-6)
        assert bool((a == a).all())


class TestReduce:
    @pytest.mark.parametrize(
        "name,np_fn",
        [("sum", np.sum), ("mean", np.mean), ("max", np.max), ("min", np.min), ("prod", np.prod)],
    )
    @pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False), (1, True), ([0, 1], False)])
    def test_forward(self, name, np_fn, axis, keepdim):
        np_axis = tuple(axis) if isinstance(axis, list) else axis
        check_output(
            lambda x: getattr(paddle, name)(x, axis=axis, keepdim=keepdim),
            lambda x: np_fn(x, axis=np_axis, keepdims=keepdim),
            [_rand(3, 4, 5)],
        )

    def test_grad_sum_mean(self):
        check_grad(lambda x: paddle.sum(x, axis=1), lambda x: np.sum(x, axis=1), [_rand(3, 4)])
        check_grad(lambda x: paddle.mean(x), lambda x: np.mean(x), [_rand(3, 4)])

    def test_std_var(self):
        x = _rand(4, 6)
        check_output(paddle.std, lambda a: np.std(a, ddof=1), [x], atol=1e-5)
        check_output(paddle.var, lambda a: np.var(a, ddof=1), [x], atol=1e-5)

    def test_logsumexp(self):
        from scipy.special import logsumexp as sls

        check_output(paddle.logsumexp, lambda a: sls(a), [_rand(3, 4)])

    def test_cumsum(self):
        check_output(lambda x: paddle.cumsum(x, axis=1), lambda x: np.cumsum(x, axis=1), [_rand(3, 4)])
        check_grad(lambda x: paddle.cumsum(x, axis=0), lambda x: np.cumsum(x, axis=0), [_rand(3, 2)])


class TestJitConsistency:
    def test_eager_vs_jit(self):
        check_eager_vs_jit(paddle.tanh, [_rand(4, 4)])
        check_eager_vs_jit(paddle.add, [_rand(2, 3), _rand(2, 3)])


class TestScaleTrace:
    def test_scale(self):
        x = _rand(3, 3)
        got = paddle.scale(paddle.to_tensor(x), scale=2.0, bias=1.0)
        np.testing.assert_allclose(got.numpy(), x * 2 + 1, rtol=1e-6)

    def test_trace_addmm(self):
        x = _rand(3, 3)
        np.testing.assert_allclose(paddle.trace(paddle.to_tensor(x)).numpy(), np.trace(x), rtol=1e-6)
        a, b, c = _rand(2, 2), _rand(2, 3), _rand(3, 2)
        got = paddle.addmm(paddle.to_tensor(a), paddle.to_tensor(b), paddle.to_tensor(c), beta=0.5, alpha=2.0)
        np.testing.assert_allclose(got.numpy(), 0.5 * a + 2.0 * (b @ c), rtol=1e-4)
