"""Deterministic fault-injection plane (ISSUE 8 tentpole).

Covers the schedule machinery (trigger counts, label matching, seeded
randomization, thread-local scoping, the fired-log replay certificate) and
each instrumented seam: elastic store message + RPC-attempt faults, the
retry budget's fail-fast interplay, checkpoint torn/crash-after-temp
writes, engine-tick faults contained by the serving loop, and router
transport timeout/garbage faults.
"""
import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.elastic.manager import (
    StoreUnavailable,
    _TcpStore,
)
from paddle_tpu.distributed.fleet.utils.http_server import KVServer
from paddle_tpu.framework.checkpoint import CheckpointManager
from paddle_tpu.resilience.inject import (
    FaultSchedule,
    FaultSpec,
    InjectedCrash,
    InjectedDeath,
    InjectedFault,
    active_schedule,
    fire,
)
from paddle_tpu.resilience.retry import (
    RetryBudget,
    RetryError,
    call_with_retries,
    set_default_budget,
)


@pytest.fixture(autouse=True)
def _no_leftover_schedule():
    yield
    sched = active_schedule()
    if sched is not None:
        sched.disarm()


@pytest.fixture()
def kv():
    srv = KVServer().start()
    yield f"127.0.0.1:{srv.port}"
    srv.stop()


# =====================================================================
# schedule machinery
# =====================================================================
class TestFaultSchedule:
    def test_unarmed_fire_is_none(self):
        assert fire("anything", foo=1) is None

    def test_trigger_count_fires_exactly_once(self):
        s = FaultSchedule().add("p", "drop", at=3)
        with s:
            assert fire("p") is None
            assert fire("p") is None
            assert fire("p").kind == "drop"
            assert fire("p") is None
        assert [f["count"] for f in s.fired_log()] == [3]

    def test_multiple_trigger_counts(self):
        s = FaultSchedule().add("p", "drop", at=(2, 4))
        with s:
            hits = [fire("p") is not None for _ in range(5)]
        assert hits == [False, True, False, True, False]

    def test_every_mode_with_max_fires(self):
        s = FaultSchedule().add("p", "drop", every=2, max_fires=2)
        with s:
            hits = [fire("p") is not None for _ in range(8)]
        assert hits == [False, True, False, True, False, False, False, False]

    def test_label_match_counts_only_matching(self):
        s = FaultSchedule().add("p", "drop", at=2, match={"node": "b"})
        with s:
            assert fire("p", node="a") is None  # does not count
            assert fire("p", node="b") is None  # count 1
            assert fire("p", node="a") is None
            assert fire("p", node="b").kind == "drop"  # count 2
        log = s.fired_log()
        assert log == [{"point": "p", "kind": "drop", "count": 2,
                        "labels": {"node": "b"}}]

    def test_raise_kind_default_and_custom_exception(self):
        s = (FaultSchedule()
             .add("p", "raise", at=1)
             .add("q", "raise", at=1, exception=OSError))
        with s:
            with pytest.raises(InjectedFault) as ei:
                fire("p")
            assert ei.value.point == "p" and ei.value.count == 1
            with pytest.raises(OSError):
                fire("q")

    def test_timeout_kind_raises_socket_timeout(self):
        import socket

        s = FaultSchedule().add("p", "timeout", at=1)
        with s:
            with pytest.raises(socket.timeout):
                fire("p")

    def test_delay_sleeps_and_proceeds(self):
        s = FaultSchedule().add("p", "delay", at=1, seconds=0.05)
        with s:
            t0 = time.perf_counter()
            assert fire("p") is None
            assert time.perf_counter() - t0 >= 0.04

    def test_seeded_randomize_is_deterministic(self):
        a = FaultSchedule(seed=42).randomize(["x", "y"], n=5,
                                             kinds=("raise", "drop"))
        b = FaultSchedule(seed=42).randomize(["x", "y"], n=5,
                                             kinds=("raise", "drop"))
        assert a.to_dict() == b.to_dict()
        c = FaultSchedule(seed=43).randomize(["x", "y"], n=5,
                                             kinds=("raise", "drop"))
        assert a.to_dict() != c.to_dict()

    def test_reset_allows_identical_replay(self):
        s = FaultSchedule().add("p", "drop", at=2)

        def run():
            out = []
            for _ in range(3):
                out.append(fire("p") is not None)
            return out

        with s:
            first = run()
            log1 = s.fired_log()
            s.reset()
            second = run()
            log2 = s.fired_log()
        assert first == second
        assert log1 == log2  # the replay certificate

    def test_thread_scope_isolates_schedules(self):
        """Two rank threads in one process each run their own chaos; the
        main thread sees none of it."""
        results = {}

        def worker(name, sched):
            with sched.scope():
                hit = []
                for _ in range(2):
                    try:
                        fire("p")
                        hit.append(False)
                    except InjectedFault:
                        hit.append(True)
                results[name] = hit

        s1 = FaultSchedule().add("p", "raise", at=1)
        s2 = FaultSchedule().add("p", "raise", at=2)
        ts = [threading.Thread(target=worker, args=("a", s1)),
              threading.Thread(target=worker, args=("b", s2))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert results == {"a": [True, False], "b": [False, True]}
        assert fire("p") is None  # main thread: nothing armed

    def test_thread_local_wins_over_global(self):
        g = FaultSchedule().add("p", "raise", every=1)
        local = FaultSchedule()  # empty: suppresses the global chaos
        with g:
            with local.scope():
                assert fire("p") is None
            with pytest.raises(InjectedFault):
                fire("p")


# =====================================================================
# elastic store seams
# =====================================================================
class TestStoreSeams:
    def test_kv_put_drop_loses_the_write(self, kv):
        st = _TcpStore(kv, "dropjob", ttl=5.0, retries=0)
        with FaultSchedule().add("elastic.store.kv.put", "drop", at=1):
            st.put("k", "v1")       # dropped in flight
            st.put("k", "v2")       # delivered
        assert st.get("k") == "v2"

    def test_kv_get_drop_reads_as_absence(self, kv):
        st = _TcpStore(kv, "dropjob2", ttl=5.0, retries=0)
        st.put("k", "v")
        with FaultSchedule().add("elastic.store.kv.get", "drop", at=1):
            assert st.get("k") is None
            assert st.get("k") == "v"

    def test_kv_scan_drop_reads_empty(self, kv):
        st = _TcpStore(kv, "dropjob3", ttl=5.0, retries=0)
        st.put("k", "v")
        with FaultSchedule().add("elastic.store.kv.scan", "drop", at=1):
            assert st.scan() == {}
            assert "k" in st.scan()

    def test_heartbeat_drop_skips_one_beat(self, kv):
        st = _TcpStore(kv, "beatjob", ttl=0.6, retries=0)
        st.register("n1", "ep1")
        with FaultSchedule().add("elastic.store.heartbeat", "drop",
                                 every=1):
            # every beat dropped: the server-side stamp goes stale
            deadline = time.monotonic() + 3.0
            while st.nodes() and time.monotonic() < deadline:
                st.heartbeat("n1")
                time.sleep(0.1)
        assert st.nodes() == []  # expired despite "beating"

    def test_duplicate_put_is_idempotent_on_the_kv_plane(self, kv):
        st = _TcpStore(kv, "dupjob", ttl=5.0, retries=0)
        with FaultSchedule().add("elastic.store.kv.put", "duplicate", at=1):
            st.put("k", "v")
        assert st.get("k") == "v"

    def test_rpc_attempt_fault_engages_retry_then_succeeds(self, kv):
        """A transient attempt-level OSError is absorbed by the retry
        layer — the operation still succeeds (the r7 self-healing
        contract, now provable without a flaky store)."""
        st = _TcpStore(kv, "rpcjob", ttl=5.0, retries=2)
        with FaultSchedule().add("elastic.store.rpc.put", "raise", at=1,
                                 exception=OSError) as s:
            st.put("k", "v")
        assert st.get("k") == "v"
        assert len(s.fired_log()) == 1

    def test_rpc_persistent_fault_exhausts_retries(self, kv):
        st = _TcpStore(kv, "rpcjob2", ttl=5.0, retries=1)
        with FaultSchedule().add("elastic.store.rpc.get", "raise",
                                 every=1, exception=OSError):
            with pytest.raises(StoreUnavailable):
                st.get("k")

    def test_rpc_default_fault_class_still_engages_retry(self, kv):
        """An attempt-level fault with the DEFAULT exception class
        (InjectedFault) must behave like a transport failure: retried,
        then surfaced as StoreUnavailable — never escaping unwrapped
        past the seam's contract."""
        st = _TcpStore(kv, "rpcjob3", ttl=5.0, retries=1)
        with FaultSchedule().add("elastic.store.rpc.get", "raise",
                                 every=1) as s:
            with pytest.raises(StoreUnavailable):
                st.get("k")
        assert len(s.fired_log()) == 2  # first attempt + 1 retry
        # transient default-class fault: absorbed, op succeeds
        st.put("k", "v")
        with FaultSchedule().add("elastic.store.rpc.get", "raise", at=1):
            assert st.get("k") == "v"


# =====================================================================
# retry budget (satellite)
# =====================================================================
class TestRetryBudget:
    def test_budget_caps_total_retries(self):
        budget = RetryBudget(max_retries=3, window_s=60.0)
        calls = []

        def failing():
            calls.append(1)
            raise OSError("down")

        with pytest.raises(RetryError) as ei:
            call_with_retries(failing, retries=10, base=0.001,
                              budget=budget, sleep=lambda s: None)
        assert ei.value.budget_exhausted
        # 1 first attempt + 3 budgeted retries, NOT 11 attempts
        assert len(calls) == 4
        assert budget.exhausted_count == 1
        assert budget.remaining() == 0

    def test_first_attempts_are_free(self):
        budget = RetryBudget(max_retries=1, window_s=60.0)
        for _ in range(5):
            assert call_with_retries(lambda: 7, retries=3,
                                     budget=budget) == 7
        assert budget.remaining() == 1  # healthy calls never charged

    def test_window_replenishes(self):
        budget = RetryBudget(max_retries=1, window_s=0.05)
        assert budget.try_spend()
        assert not budget.try_spend()
        time.sleep(0.08)
        assert budget.try_spend()

    def test_exhausted_counter_exported(self):
        from paddle_tpu.observability.metrics import default_registry

        budget = RetryBudget(max_retries=0, window_s=60.0)
        c = default_registry().get("retry_budget_exhausted_total")
        before = c.value() if c is not None else 0.0
        assert not budget.try_spend()
        c = default_registry().get("retry_budget_exhausted_total")
        assert c is not None and c.value() == before + 1

    def test_default_budget_applies_and_restores(self):
        budget = RetryBudget(max_retries=0, window_s=60.0)
        prev = set_default_budget(budget)
        try:
            with pytest.raises(RetryError) as ei:
                call_with_retries(lambda: (_ for _ in ()).throw(OSError()),
                                  retries=4, sleep=lambda s: None)
            assert ei.value.budget_exhausted
        finally:
            set_default_budget(prev)

    def test_injected_persistent_store_fault_fails_fast(self, kv):
        """The satellite acceptance: an injected every-attempt fault plus
        the shared budget = bounded total attempts across OPERATIONS, not
        unbounded per-op retry burn."""
        st = _TcpStore(kv, "budgetjob", ttl=5.0, retries=3)
        budget = RetryBudget(max_retries=2, window_s=60.0)
        prev = set_default_budget(budget)
        try:
            with FaultSchedule().add("elastic.store.rpc.get", "raise",
                                     every=1, exception=OSError) as s:
                with pytest.raises(StoreUnavailable):
                    st.get("k1")
                with pytest.raises(StoreUnavailable):
                    st.get("k2")  # budget already spent: fails fast
            # op1: 1 first + 2 budgeted retries; op2: 1 first + 0 retries
            assert len(s.fired_log()) == 4
            assert budget.exhausted_count >= 1
        finally:
            set_default_budget(prev)


# =====================================================================
# checkpoint write seams
# =====================================================================
class TestCheckpointSeams:
    STATE = {"params": {"w": np.arange(12.0).reshape(3, 4)}, "step": 0}

    def test_torn_write_falls_back_to_newest_intact(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(0, dict(self.STATE, step=0))
        with FaultSchedule().add("checkpoint.write", "torn",
                                 match={"step": 1}):
            mgr.save(1, dict(self.STATE, step=1))
        assert mgr.all_steps() == [0, 1]
        with pytest.warns(RuntimeWarning, match="falling back"):
            state, _ = mgr.load()
        assert state["step"] == 0  # step 1 is torn: CRC fallback took 0

    def test_crash_after_temp_leaves_temp_never_publishes(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(0, dict(self.STATE, step=0))
        with FaultSchedule().add("checkpoint.write", "crash_after_temp",
                                 match={"step": 1}):
            with pytest.raises(InjectedCrash):
                mgr.save(1, dict(self.STATE, step=1))
        # never published...
        assert mgr.all_steps() == [0]
        # ...but the temp dir survives like a real crash would leave it
        tmps = [d for d in os.listdir(tmp_path) if d.startswith(".tmp_step_")]
        assert len(tmps) == 1
        state, _ = mgr.load()
        assert state["step"] == 0
        # a fresh manager's stale sweep cleans genuinely old temps
        old = os.path.join(tmp_path, tmps[0])
        past = time.time() - 7200
        os.utime(old, (past, past))
        CheckpointManager(str(tmp_path))
        assert not any(d.startswith(".tmp_step_") for d in os.listdir(tmp_path))

    def test_same_schedule_replays_identical_fault_log(self, tmp_path):
        logs = []
        for leg in ("a", "b"):
            sched = FaultSchedule(seed=3).add(
                "checkpoint.write", "torn", at=2)
            mgr = CheckpointManager(str(tmp_path / leg))
            with sched:
                for s in range(3):
                    mgr.save(s, dict(self.STATE, step=s))
            logs.append(sched.fired_log())
        assert logs[0] == logs[1]
        assert logs[0] == [{"point": "checkpoint.write", "kind": "torn",
                            "count": 2, "labels": {"step": 1}}]


# =====================================================================
# engine tick + transport seams
# =====================================================================
@pytest.fixture(scope="module")
def model():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                     num_layers=1, num_attention_heads=2,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


def _prompt(n=4):
    return np.arange(1, n + 1, dtype=np.int32)


class TestEngineAndTransportSeams:
    def test_injected_tick_fault_is_contained(self, model):
        """Deterministic replay of the poison-tick suite: the Nth tick
        raises, the loop thread survives, affected requests surface
        FAILED, and later requests complete."""
        import threading as th

        from paddle_tpu.serving import ContinuousBatchingEngine, Request

        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2)
        stop = th.Event()
        with FaultSchedule().add("engine.tick", "raise", at=1) as s:
            t = th.Thread(target=eng.serve_forever, args=(stop,),
                          daemon=True)
            t.start()
            req = eng.submit(_prompt(), max_new_tokens=4)
            assert req.wait(timeout=60)
            assert req.state == Request.FAILED
            assert "InjectedFault" in req.error
            # the loop survived: a fresh request completes
            req2 = eng.submit(_prompt(), max_new_tokens=4)
            assert req2.wait(timeout=60)
            assert req2.state == Request.DONE
            assert len(req2.tokens) == 4
            stop.set()
            t.join(30)
            assert not t.is_alive()
        assert [f["point"] for f in s.fired_log()] == ["engine.tick"]

    def test_raise_at_replica_tick_is_contained_not_thread_death(
            self, model):
        """A raise-kind fault at replica.tick (not the kill kind) must be
        contained like a tick failure — requests fail visibly and the
        loop thread keeps serving, never a silently dead engine behind a
        live HTTP plane."""
        import threading as th

        from paddle_tpu.serving import ContinuousBatchingEngine, Request

        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2)
        stop = th.Event()
        with FaultSchedule().add("replica.tick", "raise", at=1):
            t = th.Thread(target=eng.serve_forever, args=(stop,),
                          daemon=True)
            t.start()
            req = eng.submit(_prompt(), max_new_tokens=4)
            assert req.wait(timeout=60)
            assert req.state == Request.FAILED
            req2 = eng.submit(_prompt(), max_new_tokens=4)
            assert req2.wait(timeout=60)
            assert req2.state == Request.DONE
            stop.set()
            t.join(30)
            assert not t.is_alive()

    def test_transport_timeout_and_garbage_fault(self, model):
        """Transport faults at the client seam: an injected timeout is an
        OSError (the retry/breaker classes treat it as a dead socket); a
        garbage body lets the request REACH the server — the engine has
        the request even though the caller saw garbage (the lost-202
        shape submit() must never retry through)."""
        import socket

        from paddle_tpu.resilience.retry import RetryError
        from paddle_tpu.serving import (ContinuousBatchingEngine,
                                        ServingClient, ServingServer)

        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2)
        with ServingServer(eng) as srv:
            c = ServingClient(srv.addr, retries=0)
            with FaultSchedule().add("router.transport", "timeout", at=1):
                # retries=0: the single attempt dies on the injected
                # socket.timeout and surfaces through the retry wrapper
                with pytest.raises(RetryError) as ei:
                    c.metrics()
                assert isinstance(ei.value.last, socket.timeout)
            # a timeout injected with retry headroom is absorbed: the
            # second attempt goes through
            c2 = ServingClient(srv.addr, retries=2)
            with FaultSchedule().add("router.transport", "timeout", at=1):
                assert "requests" in c2.metrics()
            before = eng.metrics.requests_submitted
            with FaultSchedule().add(
                    "router.transport", "garbage", at=1,
                    match={"path": "/v1/generate"}):
                with pytest.raises(ValueError):
                    c.submit(_prompt().tolist(), max_new_tokens=2)
            assert eng.metrics.requests_submitted == before + 1

    def test_router_survives_injected_poll_timeout_on_live_replica(
            self, model):
        """One injected poll timeout against a HEALTHY replica must not
        trigger failover — the confirming probe sees it alive (the
        deterministic form of the GIL-held-jit false-death scenario)."""
        from paddle_tpu.serving import (ContinuousBatchingEngine, Request,
                                        ServingRouter, ServingServer)

        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2)
        with ServingServer(eng) as srv:
            with ServingRouter([srv.addr], health_interval_s=5.0,
                               request_timeout=5.0) as router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=6)
                with FaultSchedule().add(
                        "router.transport", "timeout", at=1,
                        match={"path": f"/v1/result/{rr.remote_id}"}):
                    out = router.wait(rr, timeout=60)
                assert out["status"] == Request.DONE
                assert rr.resubmits == 0  # never failed over
                snap = router.snapshot()
                assert snap["replicas"][srv.addr]["state"] == "closed"


# =====================================================================
# r16: replicated-store seams are documented injection points
# =====================================================================
class TestReplicatedStorePoints:
    def test_store_seams_documented(self):
        """The r16 coordination-store seams belong to the documented
        POINTS registry (schedules and tests should name them from
        here); behavioral coverage lives in test_replicated_store."""
        from paddle_tpu.resilience.inject import POINTS

        for point in ("store.replica.append", "store.lease.renew",
                      "store.replica.kill", "store.election.start",
                      "store.election.won"):
            assert point in POINTS
