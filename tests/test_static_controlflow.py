"""Static-graph control flow: paddle.static.nn.cond / while_loop lowered to
lax.cond / lax.while_loop inside the Program jit.

Parity model: the reference's conditional_block/while ops
(paddle/fluid/operators/controlflow/while_op.cc, conditional_block_op.cc)
and their book tests (fluid/tests/unittests/test_cond.py,
test_while_loop_op.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    from paddle_tpu.static.program import _reset_default_programs

    _reset_default_programs()
    yield
    paddle.disable_static()


def _run(fetch, feed=None):
    exe = static.Executor()
    return exe.run(feed=feed or {}, fetch_list=fetch)


class TestCond:
    def test_cond_branches_on_feed(self):
        from paddle_tpu.ops import math as M

        x = static.data("x", [1], "float32")
        pred = M.greater_than(x, paddle.to_tensor_static_safe(0.0)) \
            if hasattr(paddle, "to_tensor_static_safe") else None
        # build pred inside the program: x > 0
        import paddle_tpu.ops.logic as L

        pred = x > 0.0 if pred is None else pred
        out = static.nn.cond(pred,
                             lambda: x * 2.0,
                             lambda: x - 1.0)
        for val, want in ((3.0, 6.0), (-2.0, -3.0)):
            (got,) = _run([out], {"x": np.asarray([val], "float32")})
            np.testing.assert_allclose(got, [want], rtol=1e-6)

    def test_cond_multiple_outputs_and_closure(self):
        x = static.data("x", [2, 2], "float32")
        y = x + 1.0  # an op-out the branches close over

        a, b = static.nn.cond(
            (x.sum() > 0.0),
            lambda: (y * 2.0, y + 10.0),
            lambda: (y * 0.0, y - 10.0),
        )
        xs = np.ones((2, 2), "float32")
        got_a, got_b = _run([a, b], {"x": xs})
        np.testing.assert_allclose(got_a, (xs + 1) * 2)
        np.testing.assert_allclose(got_b, (xs + 1) + 10)
        got_a, got_b = _run([a, b], {"x": -xs})
        np.testing.assert_allclose(got_a, (1 - xs) * 0)
        np.testing.assert_allclose(got_b, (1 - xs) - 10)

    def test_cond_differentiable(self):
        """Grads flow through the taken branch (reference test_cond backward)."""
        x = static.data("x", [3], "float32")
        out = static.nn.cond(x.sum() > 0.0,
                             lambda: (x * x).sum(),
                             lambda: (x * 2.0).sum())
        (gvar,) = static.gradients(out, x)
        xs = np.asarray([1.0, 2.0, 3.0], "float32")
        got, g = _run([out, gvar], {"x": xs})
        np.testing.assert_allclose(got, (xs * xs).sum(), rtol=1e-6)
        np.testing.assert_allclose(g, 2 * xs, rtol=1e-6)
        got, g = _run([out, gvar], {"x": -xs})
        np.testing.assert_allclose(got, (-xs * 2).sum(), rtol=1e-6)
        np.testing.assert_allclose(g, np.full(3, 2.0, "float32"), rtol=1e-6)

    def test_cond_eager_fallback(self):
        paddle.disable_static()
        t = paddle.to_tensor([1.0])
        out = static.nn.cond(t.sum() > 0, lambda: t * 3, lambda: t * 5)
        np.testing.assert_allclose(np.asarray(out._data), [3.0])


class TestWhileLoop:
    def test_while_counts_to_ten(self):
        """Reference book test: i < 10 loop (test_while_loop_op.py)."""
        from paddle_tpu.ops import creation

        i = static.data("i", [1], "int32")
        limit = static.data("limit", [1], "int32")

        (out_i,) = static.nn.while_loop(
            lambda i: i < limit,
            lambda i: i + 1,
            [i],
        )
        (got,) = _run([out_i], {"i": np.asarray([0], "int32"),
                                "limit": np.asarray([10], "int32")})
        np.testing.assert_array_equal(got, [10])

    def test_while_accumulates_tensor(self):
        i = static.data("i", [1], "float32")
        acc = static.data("acc", [4], "float32")
        step = static.data("step", [4], "float32")

        out_i, out_acc = static.nn.while_loop(
            lambda i, a: i < 5.0,
            lambda i, a: (i + 1.0, a + step),
            [i, acc],
        )
        got_i, got_acc = _run(
            [out_i, out_acc],
            {"i": np.zeros(1, "float32"), "acc": np.zeros(4, "float32"),
             "step": np.asarray([1, 2, 3, 4], "float32")},
        )
        np.testing.assert_allclose(got_i, [5.0])
        np.testing.assert_allclose(got_acc, 5 * np.asarray([1, 2, 3, 4.0]))

    def test_while_eager_fallback(self):
        paddle.disable_static()
        i = paddle.to_tensor([0.0])
        (out,) = static.nn.while_loop(lambda i: i < 3.0, lambda i: i + 1.0, [i])
        np.testing.assert_allclose(np.asarray(out._data), [3.0])


class TestNameShadowing:
    def test_branch_local_ops_do_not_shadow_outer_vars(self):
        """A branch's own op outputs must not capture-by-name an outer var
        with the same auto-generated name (regression: sub-programs restart
        the name counter)."""
        x = static.data("x", [2], "float32")
        a = x + 1.0  # outer op-out, auto-named

        def true_fn():
            t1 = x + 100.0   # branch-local op-outs with colliding names
            t2 = t1 + 10.0
            return t2 + a    # must see the OUTER a = x + 1

        out = static.nn.cond(x.sum() > 0.0, true_fn, lambda: x * 0.0 + a)
        xs = np.asarray([1.0, 3.0], "float32")
        (got,) = _run([out], {"x": xs})
        np.testing.assert_allclose(got, (xs + 110.0) + (xs + 1.0), rtol=1e-6)

    def test_while_body_local_ops_do_not_shadow(self):
        i = static.data("i", [1], "float32")
        bias = i * 2.0  # outer op-out

        def body(i):
            t = i + 1.0
            return t + bias * 0.0  # references outer bias

        (out,) = static.nn.while_loop(lambda i: i < 4.0, body, [i])
        (got,) = _run([out], {"i": np.zeros(1, "float32")})
        np.testing.assert_allclose(got, [4.0])
