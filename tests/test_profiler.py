"""profiler.scope / annotate / timer registry + the pipeline profile probes.

r6 CI tier (fast): annotations must compose under jit and compile away when
disabled; the timer registry must aggregate sanely and stay inert by
default; the pipeline profile JSON schema must be stable; and one
pp=2-emulated pipeline step must profile end-to-end on CPU.
"""
import json
import time

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import profiler


@pytest.fixture(autouse=True)
def _clean():
    profiler.disable_timers()
    profiler.reset_timers()
    yield
    profiler.disable_timers()
    profiler.reset_timers()
    dist.clear_mesh()


class TestScope:
    def test_scope_composes_under_jit(self):
        @jax.jit
        def f(x):
            with profiler.scope("test.mul"):
                y = x * 2.0
            with profiler.scope("test.add"):
                return y + 1.0

        assert float(f(2.0)) == 5.0

    def test_annotate_decorator(self):
        @profiler.annotate("test.fn")
        def f(x):
            return x + 1

        assert f(1) == 2
        assert f.__name__ == "f"

    def test_disabled_annotations_compile_away(self):
        """The lowered computation with scopes is structurally identical to
        the plain one — same equations, same primitives (names only touch
        HLO metadata)."""

        def with_scopes(x):
            with profiler.scope("a"):
                y = x * 2.0
            with profiler.scope("b"):
                return y + 1.0

        def plain(x):
            return x * 2.0 + 1.0

        ja = jax.make_jaxpr(with_scopes)(1.0).jaxpr
        jb = jax.make_jaxpr(plain)(1.0).jaxpr
        assert [e.primitive for e in ja.eqns] == [e.primitive for e in jb.eqns]

    def test_enabled_timers_do_not_change_jaxpr(self):
        profiler.enable_timers()

        def with_scopes(x):
            with profiler.scope("a"):
                return x * 2.0

        ja = jax.make_jaxpr(with_scopes)(1.0).jaxpr
        jb = jax.make_jaxpr(lambda x: x * 2.0)(1.0).jaxpr
        assert [e.primitive for e in ja.eqns] == [e.primitive for e in jb.eqns]


class TestTimerRegistry:
    def test_disabled_by_default_records_nothing(self):
        with profiler.scope("idle.region"):
            time.sleep(0.002)
        assert profiler.timer_report() == {}

    def test_enabled_records_host_spans(self):
        profiler.enable_timers()
        for _ in range(3):
            with profiler.scope("host.region"):
                time.sleep(0.002)
        rep = profiler.timer_report()
        assert rep["host.region"]["count"] == 3
        # deterministic invariants only (r14 sweep): sleep() guarantees the
        # lower bound; a wall-clock UPPER bound here flaked under loaded CI
        # boxes (the r13 shed-bound pattern) — timing claims live in bench.py
        assert rep["host.region"]["avg_s"] >= 0.002
        assert rep["host.region"]["total_s"] == pytest.approx(
            3 * rep["host.region"]["avg_s"])

    def test_reset(self):
        profiler.enable_timers()
        with profiler.scope("r"):
            pass
        profiler.reset_timers()
        assert profiler.timer_report() == {}

    def test_state_roundtrip_and_accessors(self):
        """save_state/restore_state (r14: the perf doctor borrows the
        shared registry and must hand back the caller's measurements)
        plus the last()/averages() accessors."""
        from paddle_tpu.profiler.scope import timer_registry as reg

        reg.reset()
        reg.record("a.x", 0.5)
        reg.record("a.x", 1.5)
        reg.record("b.y", 2.0)
        assert reg.last("a.x") == 1.5
        assert reg.last("missing") is None
        assert reg.averages() == {"a.x": 1.0, "b.y": 2.0}
        assert reg.averages("a.") == {"a.x": 1.0}
        state = reg.save_state()
        reg.reset()
        reg.record("other", 9.0)
        reg.restore_state(state)
        assert reg.averages() == {"a.x": 1.0, "b.y": 2.0}
        assert reg.count("a.x") == 2 and reg.total("b.y") == 2.0
        reg.reset()

    def test_tracing_spans_not_timed(self):
        """Inside a trace the scope must not record wall time (trace time
        is not runtime)."""
        profiler.enable_timers()

        @jax.jit
        def f(x):
            with profiler.scope("traced.region"):
                return x + 1

        f(1.0)
        assert "traced.region" not in profiler.timer_report()


def _tiny_pp2_step(microbatches=2):
    from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
        build_gpt_pipeline_step,
    )
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.optimizer.optimizers import AdamW

    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                     num_layers=4, num_attention_heads=4,
                     max_position_embeddings=32, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    paddle.seed(0)
    dist.init_mesh({"pp": 2})
    model = GPTForPretraining(cfg)
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = build_gpt_pipeline_step(model, opt, microbatches=microbatches)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, (4, 16)).astype("int32")
    return step, x


class TestPipelineProfile:
    TICK_REGIONS = {"stage_compute", "boundary_ppermute", "inject",
                    "head_loss", "tick_bookkeeping"}
    STEP_REGIONS = {"forward_backward", "grad_reduce", "optimizer_apply"}

    def test_pp2_tick_under_profiler_smoke(self, tmp_path):
        """One pp=2-emulated pipeline step profiles end-to-end on CPU: the
        schema is exactly the frozen one and every named region measured."""
        from paddle_tpu.profiler.pipeline import (
            PROFILE_SCHEMA,
            profile_pipeline_step,
            write_profile,
        )

        step, x = _tiny_pp2_step()
        prof = profile_pipeline_step(step, x, x, steps=2, reps=1)
        assert prof["schema"] == PROFILE_SCHEMA
        assert prof["config"]["pp"] == 2
        assert prof["config"]["ticks"] == step.pipe.schedule_ticks()
        assert set(prof["per_tick_ms"]["regions"]) == self.TICK_REGIONS
        assert set(prof["per_step_ms"]["regions"]) == self.STEP_REGIONS
        assert prof["per_tick_ms"]["total_forward"] > 0
        assert prof["per_step_ms"]["total"] > 0
        assert prof["per_tick_ms"]["regions"]["stage_compute"] > 0
        assert prof["per_tick_ms"]["regions"]["boundary_ppermute"] > 0
        assert prof["per_step_ms"]["host_dispatch"] > 0
        # deterministic invariant only (r14 sweep): every region measured
        # and the fraction well-formed. The ">= 0.75 attributed" QUALITY
        # claim is wall-clock (a GC pause outside a region sinks it under
        # concurrent CI load) and is pinned on the committed bench artifact
        # below, not re-measured here.
        assert 0 < prof["per_tick_ms"]["attributed_fraction"] <= 1.5
        # the caller's timer state is restored (disabled here) and the
        # registry is NOT reset (only the profiler's own dispatch spans
        # may have landed)
        assert not profiler.timers_enabled()
        assert set(profiler.timer_report()) <= {"pipeline.step.host_dispatch"}
        # round-trips as json
        p = write_profile(str(tmp_path / "prof.json"), prof)
        with open(p) as f:
            assert json.load(f)["schema"] == PROFILE_SCHEMA

    def test_committed_artifact_schema(self):
        """benchmarks/pipeline_profile_r6.json stays valid against the
        frozen schema (whatever device generated it last)."""
        import os

        path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                            "pipeline_profile_r6.json")
        with open(path) as f:
            prof = json.load(f)
        assert prof["schema"] == "paddle_tpu.pipeline_profile.v1"
        legs = prof["legs"]
        assert any(k.startswith("pp") for k in legs)
        for name, leg in legs.items():
            if not name.startswith("pp"):
                continue
            assert set(leg["per_tick_ms"]["regions"]) == self.TICK_REGIONS
            # the headline property: per-tick wall time is attributed to
            # named regions, not left as an unexplained residual
            assert leg["per_tick_ms"]["attributed_fraction"] >= 0.75
